#!/usr/bin/env bash
# CI entry point (ref: ci/docker/runtime_functions.sh — the executable
# spec of the reference's test matrix). Reproduces the conftest mesh
# setup explicitly so the suite also runs under environments whose site
# hooks pre-pin a JAX platform.
#
# Usage: ci/run_tests.sh [pytest args...]
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

# 8-device virtual CPU mesh: exercises every dp/tp/sp/pp/ep sharding path
# without TPU hardware (SURVEY §4 distributed-tests row)
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
# strip any site hook that would dial a TPU tunnel at interpreter start
export PYTHONPATH="$REPO"

cd "$REPO"
python -m pytest tests/ -q "$@"

#!/usr/bin/env bash
# CI entry point (ref: ci/docker/runtime_functions.sh — the executable
# spec of the reference's test matrix). Tiered like the reference's
# sanity_check / unittest / nightly split:
#
#   ci/run_tests.sh sanity          tier-0 static analysis only (graftlint:
#                                   ci/lint.py path-loads mxnet_tpu/analysis
#                                   without executing the runtime package —
#                                   JAX-hazard G-rules + generic W-rules,
#                                   new-vs-baseline gated; still runs when
#                                   the runtime or jax itself is broken)
#   ci/run_tests.sh fast            tier-0 + the quick unit tier
#   ci/run_tests.sh sanitize        native runtime under ASAN/UBSAN + TSAN
#                                   (ref: runtime_functions.sh sanitizer
#                                   builds — SURVEY §5.2)
#   ci/run_tests.sh [full]          lint + the whole suite (default)
#   ci/run_tests.sh full -k expr    extra args go to pytest
#
# Reproduces the conftest mesh setup explicitly so the suite also runs
# under environments whose site hooks pre-pin a JAX platform.
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

# 8-device virtual CPU mesh: exercises every dp/tp/sp/pp/ep sharding path
# without TPU hardware (SURVEY §4 distributed-tests row)
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
# strip any site hook that would dial a TPU tunnel at interpreter start
export PYTHONPATH="$REPO"

cd "$REPO"

TIER="full"
case "${1:-}" in
  sanity|fast|full|sanitize) TIER="$1"; shift ;;
esac

if [ "$TIER" = "sanitize" ]; then
  echo "== tier: sanitize (native ASAN/UBSAN + TSAN) =="
  cd native
  CXX="${CXX:-g++}"
  COMMON="-O1 -g -std=c++17 -fno-omit-frame-pointer -pthread"
  SRCS="test_sanitize.cc engine.cc recordio.cc predict.cc"
  WORK="$(mktemp -d)"          # run-scoped: concurrent CI jobs don't collide
  trap 'rm -rf "$WORK"' EXIT
  "$CXX" $COMMON -fsanitize=address,undefined -fno-sanitize-recover=all \
      -o "$WORK/asan" $SRCS
  ASAN_OPTIONS=detect_leaks=1 "$WORK/asan" "$WORK/roundtrip.rec"
  "$CXX" $COMMON -fsanitize=thread -o "$WORK/tsan" $SRCS
  TSAN_OPTIONS=halt_on_error=1 "$WORK/tsan" "$WORK/roundtrip.rec"
  echo "sanitize tier PASS"
  exit 0
fi

echo "== tier 0: graftlint static analysis (docs/static_analysis.md) =="
# shared-AST + summary-cache + --jobs keep the full scan (incl. the
# interprocedural G15-G19 tier) inside a hard wall-clock budget; on
# failure a SARIF artifact lands next to the baseline for the review UI
LINT_BUDGET_S="${MXNET_TPU_LINT_BUDGET_S:-120}"
LINT_T0=$SECONDS
if ! python ci/lint.py --jobs 0; then
  python ci/lint.py --jobs 0 --format=sarif > ci/graftlint.sarif || true
  echo "graftlint FAILED — SARIF artifact: ci/graftlint.sarif"
  exit 1
fi
LINT_WALL=$((SECONDS - LINT_T0))
echo "graftlint wall-clock: ${LINT_WALL}s (budget ${LINT_BUDGET_S}s)"
if [ "$LINT_WALL" -gt "$LINT_BUDGET_S" ]; then
  echo "tier-0 lint exceeded its ${LINT_BUDGET_S}s budget — the CI" \
       "contract is fast lint; check the summary cache + --jobs path"
  exit 1
fi

if [ "$TIER" = "sanity" ]; then
  exit 0
fi

# chaos smoke: a fast crash-matrix subset (kill the checkpoint writer at
# key phases, prove old-or-new recovery) so a torn-file regression fails
# in seconds, before the unit tiers spend minutes (docs/checkpointing.md)
echo "== tier 0.5: chaos smoke (crash-matrix subset) =="
python -m pytest tests/test_crash_matrix.py -q -k smoke -p no:cacheprovider

# serving smoke: spin the dynamic-batching server on a real thread, push
# 50 mixed requests (incl. an oversized-shape reject), prove bounded
# compiles + clean shutdown (docs/serving.md); the soak test is `slow`
echo "== tier 0.5: serving smoke (dynamic batcher) =="
python -m pytest tests/test_serving.py -q -k smoke -p no:cacheprovider

# warm-start smoke: serve -> stop -> restart on the same AOT cache dir
# -> the second start performs ZERO XLA compiles for the warmed bucket
# set (compile_stats) with bit-identical responses, and a bit-flipped
# entry degrades to a compile with a journaled aot_fallback — the
# bounded-startup guarantee (docs/serving.md AOT cache)
echo "== tier 0.5: warm-start smoke (persistent AOT cache) =="
python -m pytest tests/test_aotcache.py -q -k smoke -p no:cacheprovider

# sharded-serving smoke: the SAME weights served through a 2-device
# tensor-parallel predictor and a plain single-device server answer
# bit-identically (the default plan column-shards the output dim — no
# cross-shard reduction), with the placement journaled shard_place
# (docs/serving.md tensor-parallel predictors)
echo "== tier 0.5: sharded-serving smoke (tensor-parallel bit parity) =="
python -m pytest tests/test_serving_sharded.py -q -k smoke -p no:cacheprovider

# decode smoke: a tensor-parallel server on a 2-device CPU mesh runs 8
# concurrent autoregressive streams with staggered lengths through the
# continuous batcher -> every stream bit-identical to the reference
# within its deadline, ZERO XLA compiles outside the warmed program
# set, and a cancelled stream frees its slot for a successor
# (docs/serving.md continuous batching)
echo "== tier 0.5: decode smoke (continuous batching, zero mid-run compiles) =="
python -m pytest tests/test_decode.py -q -k smoke -p no:cacheprovider

# tenant-fleet chaos smoke: tenant A fed a corrupt committed checkpoint
# + oversized-shape flood + predictor poison while tenant B runs
# closed-loop load on the SAME fleet -> B's p99 stays in its SLO bound
# with zero corruption errors, A quarantines itself with tenant-labeled
# structured errors, the quarantine->half-open->re-admit trail is
# trace-correlated, and the mixed-version reload keeps every response
# stamped with its own tenant's step (docs/serving.md tenant matrix)
echo "== tier 0.5: tenant-fleet chaos smoke (tenant isolation) =="
python -m pytest tests/test_serving_fleet.py -q -k smoke -p no:cacheprovider

# pool chaos smoke: 3 REAL replica worker processes behind the
# health-routed front door under closed-loop load; SIGKILL one ->
# detection within the heartbeat deadline, retries complete on
# survivors inside their deadline budget, zero corrupt responses, the
# respawned replica re-admitted through a half-open breaker probe, and
# the journal reduction (doctor --serving-journal) tells the story —
# bounded wall-clock end to end (docs/serving.md failure matrix)
echo "== tier 0.5: pool chaos smoke (replica SIGKILL -> reroute) =="
python -m pytest tests/test_serving_pool.py -q -k smoke -p no:cacheprovider

# canary deploy chaos smoke: a REGRESSED (CRC-valid, wrong-answer)
# step is canaried onto 1 of 3 replicas under closed-loop load -> the
# sampled output-parity gate trips, the fleet auto-rolls-back within
# the deadline budget, zero responses whose value contradicts their
# version stamp, control replicas never serve the bad root (blast
# radius = the canary set), the rolled-back store stays PINNED against
# the bad-but-newest commit, and the trace-correlated deploy trail is
# rendered by doctor --serving-journal (docs/serving.md canary
# deployment)
echo "== tier 0.5: canary deploy chaos smoke (parity gate -> rollback) =="
python -m pytest tests/test_serving_deploy.py -q -k smoke -p no:cacheprovider

# guardrail chaos smoke: poison a batch (NaN) -> the fused guard skips
# the step bitwise and journals it; a persistent-poison divergence drill
# rolls back bit-exact to the last committed step — the run stays green
# (docs/guardrails.md)
echo "== tier 0.5: guardrail chaos smoke (anomaly skip + rollback) =="
python -m pytest tests/test_guardrails.py -q -k smoke -p no:cacheprovider

# elastic chaos smoke: a real multi-process CPU cohort loses a rank to
# SIGTERM mid-run; the survivor detects it within the heartbeat
# deadline (no hung collective), resizes, restores the newest committed
# checkpoint RESHARDED onto the survivor mesh, and trains to completion
# — plus the 2->1/1->2 bit-exact reshard and corrupt-shard fallback
# (docs/elastic.md)
echo "== tier 0.5: elastic chaos smoke (rank loss -> resharded resume) =="
python -m pytest tests/test_elastic.py -q -k smoke -p no:cacheprovider

# chaos mini-campaign: the five single-fault drills above are also
# registered as conductor scenarios (mxnet_tpu/chaos/scenarios.py), so
# faults COMPOSE: here a seeded 2-fault schedule (torn heartbeat +
# disk_full at the replace phase — the seed pins both) lands mid-window
# on the same 3-replica pool the SIGKILL smoke drives, every declared
# invariant is evaluated, and the CHAOS_rNN.json artifact must
# parse-check; a failing invariant ships a shrunk reproducer and rc 1
# (docs/chaos.md).  Hard wall budget: a hung campaign is a failure,
# not a stall.
echo "== tier 0.5: chaos mini-campaign (composed faults via conductor) =="
CHAOS_DIR="$(mktemp -d)"
timeout -k 10 120 python -m mxnet_tpu.chaos run pool --seed 9 \
    --faults 2 --classes durability,resource --budget 5 \
    --out-dir "$CHAOS_DIR" > /dev/null
python - "$CHAOS_DIR" <<'EOF'
import sys
from mxnet_tpu.chaos.artifact import latest_artifact, read_artifact
path = latest_artifact(sys.argv[1])
doc = read_artifact(path)
kinds = [s["kind"] for s in doc["schedule"]]
assert "disk_full" in kinds, kinds
assert doc["ok"], f"failed invariants: {doc['failed']}"
print(f"chaos mini-campaign PASS: {len(kinds)} composed faults "
      f"({', '.join(kinds)}), artifact {path}")
EOF
rm -rf "$CHAOS_DIR"

# autotune smoke: the closed-loop autotuner's table discipline on CPU —
# a committed tuned table survives the corruption/truncation/envelope
# fuzz matrix (defaults + exact journaled tuned_fallback reason, zero
# crashes), runtime consumers (pallas.dispatch, Server) demonstrably
# load tuned knobs with a journaled tuned_load, and a tuned block is
# bit-identical to the default tiling; the full ≤8-trial search CLI
# loop is `slow` (docs/autotune.md)
echo "== tier 0.5: autotune smoke (tuned-table fuzz + consumer load) =="
python -m pytest tests/test_autotune.py -q -k smoke -p no:cacheprovider

# pallas interpret smoke: every registered custom kernel passes its CPU
# interpret-mode parity gate vs its XLA reference (forward AND custom_vjp
# gradients), the non-TPU fallback journals its reason, and dropout keys
# stay independent under the (layer, tick, shard) fold — a numerics
# regression in the hand-kernel tier fails in seconds (docs/pallas.md)
echo "== tier 0.5: pallas interpret smoke (kernel parity gate) =="
python -m pytest tests/test_pallas.py -q -k smoke -p no:cacheprovider

# observability smoke: one traced training step + one traced serving
# request -> the Chrome-trace/Perfetto export and the Prometheus
# exposition both parse, with compile events and linked request span
# trees present (docs/observability.md)
echo "== tier 0.5: observability smoke (trace + exporters) =="
python -m pytest tests/test_observability.py -q -k smoke -p no:cacheprovider

# distributed-trace smoke: a 3-replica pool under load sharing one
# trace run dir, SIGKILL one replica -> ONE trace_id links the router
# request root to worker-side request spans across the wire, the
# killed replica's flight-recorder dump is present and parseable, and
# the merged cross-process Perfetto trace + doctor --timeline critical
# path assemble from per-process files alone (docs/observability.md)
echo "== tier 0.5: distributed-trace smoke (SIGKILL -> assembled story) =="
python -m pytest tests/test_distributed_trace.py -q -k smoke -p no:cacheprovider

# quick unit tier: core ndarray/op/autograd/gluon/io surface, no
# model-zoo or multi-process tests (ref: runtime_functions.sh unittest
# vs nightly split)
FAST_TESTS=(tests/test_ndarray.py tests/test_operator.py
            tests/test_autograd.py tests/test_io.py tests/test_gluon.py
            tests/test_aux.py tests/test_numpy_ns.py)

if [ "$TIER" = "fast" ]; then
  echo "== tier: fast =="
  exec python -m pytest "${FAST_TESTS[@]}" -q "$@"
fi

echo "== tier: full =="
# slow-marked tests (soak / subprocess CLIs) stay out of the default
# budget; append `-m ''` (or `-m slow`) to opt back in — later -m wins
exec python -m pytest tests/ -q -m "not slow" "$@"

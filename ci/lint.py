#!/usr/bin/env python
"""Self-contained lint tier (ref: ci/docker/runtime_functions.sh
sanity_check — the reference runs cpplint/pylint there). No third-party
linters are baked into this image, so this is a dependency-free
pylint-lite over the AST:

  E1  syntax error (file does not compile)
  W1  unused import
  W2  bare ``except:``
  W3  mutable default argument (list/dict/set literal)
  W4  f-string with no placeholders
  W5  trailing whitespace / tab indentation
  W6  line longer than 100 columns

Usage: python ci/lint.py [paths...]   (default: mxnet_tpu tools examples
benchmarks tests bench.py __graft_entry__.py)
Exit code 1 on any finding — wired as the first CI tier.
"""
from __future__ import annotations

import ast
import os
import sys

DEFAULT_PATHS = ["mxnet_tpu", "tools", "examples", "benchmarks", "tests",
                 "ci", "bench.py", "__graft_entry__.py"]
MAX_LINE = 100


def iter_py(paths):
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


class ImportTracker(ast.NodeVisitor):
    """Collect imported names and every referenced name. Imports inside
    try/except are feature probes (the import IS the use) and
    ``from __future__`` imports are semantic — neither is flagged."""

    def __init__(self):
        self.imports = {}       # name -> lineno
        self.used = set()
        self._try_depth = 0

    def visit_Try(self, node):
        self._try_depth += 1
        self.generic_visit(node)
        self._try_depth -= 1

    def visit_Import(self, node):
        if self._try_depth:
            return
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node):
        if self._try_depth or node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imports.setdefault(a.asname or a.name, node.lineno)

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def lint_file(path):
    findings = []
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, "E1", f"syntax error: {e.msg}")]

    lines = src.splitlines()
    for i, line in enumerate(lines, 1):
        if line != line.rstrip():
            findings.append((path, i, "W5", "trailing whitespace"))
        if line.startswith("\t") or (line[:1] == " " and "\t" in
                                     line[:len(line) - len(line.lstrip())]):
            findings.append((path, i, "W5", "tab indentation"))
        if len(line) > MAX_LINE:
            findings.append((path, i, "W6",
                             f"line too long ({len(line)} > {MAX_LINE})"))

    tracker = ImportTracker()
    tracker.visit(tree)
    # names exported via __all__ strings or re-exported in __init__ count
    exported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" and \
                        isinstance(node.value, (ast.List, ast.Tuple)):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant):
                            exported.add(str(elt.value))
    is_init = os.path.basename(path) == "__init__.py"
    for name, lineno in tracker.imports.items():
        if name.startswith("_"):
            continue
        if name not in tracker.used and name not in exported and \
                not is_init:
            findings.append((path, lineno, "W1", f"unused import {name!r}"))

    _format_specs = {id(n.format_spec) for n in ast.walk(tree)
                     if isinstance(n, ast.FormattedValue)
                     and n.format_spec is not None}
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append((path, node.lineno, "W2", "bare except:"))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    findings.append((path, d.lineno, "W3",
                                     "mutable default argument"))
        if isinstance(node, ast.JoinedStr):
            # skip format-spec JoinedStrs nested inside FormattedValue
            # (e.g. the ':8.1f' in f"{x:8.1f}" parses as a JoinedStr)
            if id(node) in _format_specs:
                continue
            if not any(isinstance(v, ast.FormattedValue)
                       for v in node.values):
                findings.append((path, node.lineno, "W4",
                                 "f-string without placeholders"))
    # `# noqa` suppression, checked here while the lines are in memory
    return [f for f in findings
            if not (1 <= f[1] <= len(lines) and "# noqa" in lines[f[1] - 1])]


def main():
    paths = sys.argv[1:] or DEFAULT_PATHS
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.chdir(repo)
    all_findings = []
    n_files = 0
    for path in iter_py(paths):
        n_files += 1
        all_findings.extend(lint_file(path))
    for path, line, code, msg in all_findings:
        print(f"{path}:{line}: {code} {msg}")
    print(f"lint: {n_files} files, {len(all_findings)} findings")
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main())

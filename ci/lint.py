#!/usr/bin/env python
"""Thin shim over the graftlint framework (``mxnet_tpu/analysis/``).

The seed shipped this file as a self-contained dependency-free
pylint-lite (W1-W6). Those rules now live in
``mxnet_tpu/analysis/rules_generic.py`` on the same walker, suppression
syntax, and baseline as the JAX-hazard G-rules — this entry point is
kept so ``python ci/lint.py [paths...]`` and every script that calls it
keep working unchanged.

Dependency-free by construction: the analysis package is loaded BY PATH
under a private name, so ``mxnet_tpu/__init__.py`` (which imports jax
and the whole runtime) never executes. The linter therefore still runs
— and still reports E1 — when the runtime package itself is broken or
jax is absent, which is exactly when a lint tier earns its keep. CI
tier-0 uses this entry point; ``python -m mxnet_tpu.analysis`` is the
convenience form for developers with a working checkout.

Full CLI (formats, baseline regeneration, rule filtering):
``python ci/lint.py --help``; rule catalog in docs/static_analysis.md.
"""
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_graftlint():
    """Import mxnet_tpu/analysis as a standalone package (no parent
    package execution, no jax)."""
    pkg_dir = os.path.join(REPO, "mxnet_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "_graftlint", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_graftlint"] = mod
    spec.loader.exec_module(mod)
    return mod


def main():
    os.chdir(REPO)
    return _load_graftlint().main(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())

"""RecordIO — the reference's packed binary record format, byte-compatible.

ref: 3rdparty/dmlc-core/include/dmlc/recordio.h (kMagic, record framing),
3rdparty/dmlc-core/src/recordio.cc (RecordIOWriter::WriteRecord splitting),
python/mxnet/recordio.py (MXRecordIO, MXIndexedRecordIO, IRHeader,
pack/unpack/pack_img/unpack_img).

Framing: every record is ``[magic:u32][lrec:u32][payload][pad to 4B]`` where
``lrec`` packs cflag (upper 3 bits) + length (lower 29). Payloads containing
the magic u32 at 4-byte alignment are split into parts (cflag 1=begin,
2=middle, 3=end); the reader re-joins them re-inserting the magic. Files
written here are readable by the reference tooling and vice versa.
"""
from __future__ import annotations

import numbers
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_kMagic = 0xced7230a
_magic_bytes = struct.pack("<I", _kMagic)


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(lrec):
    return lrec >> 29, lrec & ((1 << 29) - 1)


class MXRecordIO:
    """Sequential RecordIO reader/writer (ref: recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.fid = None
        self.open()

    def open(self):
        self._native = None
        if self.flag == "w":
            self.writable = True
            self._native = self._try_native_writer()
            self.fid = None if self._native else open(self.uri, "wb")
        elif self.flag == "r":
            self.writable = False
            self._native = self._try_native_reader()
            self.fid = None if self._native else open(self.uri, "rb")
        else:
            raise MXNetError(f"invalid flag {self.flag!r} (use 'r' or 'w')")

    def _try_native_reader(self):
        """Prefer the C++ reader (native/recordio.cc) — same byte format,
        no Python framing overhead."""
        try:
            from ._native import NativeReader
            return NativeReader(self.uri)
        except Exception:
            return None

    def _try_native_writer(self):
        try:
            from ._native import NativeWriter
            return NativeWriter(self.uri)
        except Exception:
            return None

    def close(self):
        if getattr(self, "_native", None) is not None:
            self._native.close()
            self._native = None
        if self.fid is not None:
            self.fid.close()
            self.fid = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        """Pickling (e.g. into DataLoader workers) reopens by path."""
        d = dict(self.__dict__)
        d["fid"] = None
        d["_native"] = None
        if self.writable:
            raise MXNetError("cannot pickle a writable MXRecordIO")
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if self._native is not None and self.writable:
            return self._native.tell()
        return self.fid.tell()

    def write(self, buf):
        """ref: RecordIOWriter::WriteRecord — split payload at aligned
        occurrences of the magic."""
        if not self.writable:
            raise MXNetError("recordio not opened for writing")
        if isinstance(buf, str):
            buf = buf.encode("utf-8")
        buf = bytes(buf)
        if self._native is not None:
            self._native.write(buf)
            return
        # find 4-byte-aligned magic occurrences
        splits = []
        for off in range(0, len(buf) - 3, 4):
            if buf[off:off + 4] == _magic_bytes:
                splits.append(off)
        parts = []
        start = 0
        for off in splits:
            parts.append(buf[start:off])
            start = off + 4
        parts.append(buf[start:])
        n = len(parts)
        for i, part in enumerate(parts):
            if n == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == n - 1:
                cflag = 3
            else:
                cflag = 2
            self.fid.write(_magic_bytes)
            self.fid.write(struct.pack("<I", _encode_lrec(cflag, len(part))))
            self.fid.write(part)
            pad = (4 - len(part) % 4) % 4
            if pad:
                self.fid.write(b"\x00" * pad)

    def _read_one_part(self):
        head = self.fid.read(8)
        if len(head) < 8:
            return None, None
        magic, lrec = struct.unpack("<II", head)
        if magic != _kMagic:
            raise MXNetError(f"recordio: bad magic {magic:#x} in {self.uri}")
        cflag, length = _decode_lrec(lrec)
        data = self.fid.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.fid.read(pad)
        return cflag, data

    def read(self):
        """Next record payload, or None at EOF (ref: MXRecordIO.read)."""
        if self.writable:
            raise MXNetError("recordio not opened for reading")
        if self._native is not None:
            return self._native.read()
        cflag, data = self._read_one_part()
        if cflag is None:
            return None
        if cflag == 0:
            return data
        if cflag != 1:
            raise MXNetError("recordio: stream does not start at a record "
                             "boundary")
        parts = [data]
        while True:
            cflag, data = self._read_one_part()
            if cflag is None:
                raise MXNetError("recordio: truncated multi-part record")
            parts.append(data)
            if cflag == 3:
                break
        return _magic_bytes.join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a ``key\\tpos`` index for random access
    (ref: recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.exists(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    line = line.strip().split("\t")
                    if len(line) != 2:
                        continue
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        is_open = self.fid is not None or \
            getattr(self, "_native", None) is not None
        if is_open and self.writable:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        pos = self.idx[idx]
        if self._native is not None:
            self._native.seek(pos)
        else:
            self.fid.seek(pos)

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


# -- header packing (ref: recordio.py IRHeader/pack/unpack) ------------------
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """ref: recordio.py pack — header + raw bytes."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        head = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                           header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        head = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
        head += label.tobytes()
    return head + (s if isinstance(s, bytes) else bytes(s))


def unpack(s):
    """ref: recordio.py unpack → (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """ref: recordio.py pack_img — encode image (cv2) then pack."""
    import cv2
    ret, buf = cv2.imencode(
        img_fmt, img,
        [cv2.IMWRITE_JPEG_QUALITY, quality] if img_fmt in (".jpg", ".jpeg")
        else [])
    if not ret:
        raise MXNetError(f"failed to encode image as {img_fmt}")
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=-1):
    """ref: recordio.py unpack_img → (IRHeader, ndarray image)."""
    import cv2
    header, s = unpack(s)
    img = cv2.imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
    return header, img

"""``mx.operator`` — Python custom operators
(ref: python/mxnet/operator.py CustomOp/CustomOpProp +
src/operator/custom/custom.cc).

The reference runs user Python forward/backward on a dedicated engine
thread with GIL juggling; the TPU translation is ``jax.pure_callback``:
the custom op becomes a host callback embedded in the XLA program, with a
``jax.custom_vjp`` wiring the user's ``backward`` as the pullback — so
custom ops compose with autograd, jit, and hybridize like any registry op.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get"]

_CUSTOM_REGISTRY = {}


class CustomOp:
    """User op base (ref: operator.py CustomOp): override forward/backward
    working on numpy arrays via ``in_data``/``out_data`` lists and
    ``self.assign``."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        if req in ("write", "inplace", None):
            dst[...] = src
        elif req == "add":
            dst[...] += src
        elif req == "null":
            pass
        else:
            raise MXNetError(f"unknown req {req!r}")


class CustomOpProp:
    """Shape/type metadata provider (ref: operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """ref: mx.operator.register — class decorator for CustomOpProp."""
    def deco(prop_cls):
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls
    return deco


def get(reg_name):
    if reg_name not in _CUSTOM_REGISTRY:
        raise MXNetError(f"custom op {reg_name!r} is not registered; known: "
                         f"{sorted(_CUSTOM_REGISTRY)}")
    return _CUSTOM_REGISTRY[reg_name]


def _custom_impl(op_type, datas, kwargs):
    """Build the pure_callback + custom_vjp computation for one call."""
    import jax

    prop = get(op_type)(**kwargs)
    in_shapes = [tuple(d.shape) for d in datas]
    in_types = [d.dtype for d in datas]
    _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
    _, out_types, _ = prop.infer_type(in_types)
    out_shapes = [tuple(s) for s in out_shapes]
    operator = prop.create_operator(None, in_shapes, in_types)
    n_in, n_out = len(in_shapes), len(out_shapes)
    out_struct = tuple(jax.ShapeDtypeStruct(s, t)
                       for s, t in zip(out_shapes, out_types))
    in_struct = tuple(jax.ShapeDtypeStruct(s, t)
                      for s, t in zip(in_shapes, in_types))

    def host_forward(*arrs):
        ins = [np.asarray(a) for a in arrs]
        outs = [np.zeros(s, t) for s, t in zip(out_shapes, out_types)]
        operator.forward(is_train=True, req=["write"] * n_out,
                         in_data=ins, out_data=outs, aux=[])
        return tuple(outs)

    def host_backward(*arrs):
        ogs = [np.asarray(a) for a in arrs[:n_out]]
        ins = [np.asarray(a) for a in arrs[n_out:n_out + n_in]]
        outs = [np.asarray(a) for a in arrs[n_out + n_in:]]
        igs = [np.zeros(s, t) for s, t in zip(in_shapes, in_types)]
        operator.backward(req=["write"] * n_in, out_grad=ogs, in_data=ins,
                          out_data=outs, in_grad=igs, aux=[])
        return tuple(igs)

    @jax.custom_vjp
    def core(*xs):
        return jax.pure_callback(host_forward, out_struct, *xs)

    def fwd(*xs):
        outs = jax.pure_callback(host_forward, out_struct, *xs)
        return outs, (xs, outs)

    def bwd(res, gs):
        xs, outs = res
        if not isinstance(gs, tuple):
            gs = (gs,)
        igs = jax.pure_callback(host_backward, in_struct,
                                *(tuple(gs) + tuple(xs) + tuple(outs)))
        return tuple(igs)

    core.defvjp(fwd, bwd)
    out = core(*datas)
    return out if n_out > 1 else out[0]


def _register_custom_dispatch():
    """Expose ``mx.nd.Custom(*inputs, op_type=...)`` (ref: the reference
    generates `Custom` from src/operator/custom/custom.cc)."""
    from .ops import registry as _reg
    from .ops.registry import OpParam, register as reg_op

    @reg_op("Custom", num_inputs=-1,
            params=[OpParam("op_type", str, None, required=True)],
            doc="Run a registered Python CustomOp "
                "(ref: src/operator/custom/custom.cc; executes as a host "
                "callback inside the XLA program)")
    def _custom(*datas, op_type=None, **kwargs):
        return _custom_impl(op_type, list(datas), kwargs)

    _reg.get("Custom").allow_unknown_params = True
    # the nd namespace was generated before this module imported — attach
    # the wrapper now (the reference regenerates on MXCustomOpRegister too)
    from . import ndarray as _nd_ns
    _nd_ns.Custom = _nd_ns._make_wrapper("Custom", _reg.get("Custom"))
    setattr(_nd_ns.op, "Custom", _nd_ns.Custom)


_register_custom_dispatch()

"""Sparse NDArray storage types (ref: python/mxnet/ndarray/sparse.py;
include/mxnet/ndarray.h kCSRStorage/kRowSparseStorage).

SURVEY §2 #2 defers sparse behind dense parity; this module provides the
real storage formats (compressed, not dense-pretending) with conversions
and the hot ops: ``sparse.dot`` runs on jax's BCOO sparse kernels;
everything else densifies explicitly (a visible `.tostype('default')`, not
a silent one). Row-sparse remains the gradient format for embedding-style
updates, matching the reference's usage.

The sparse-gradient training path (Embedding(sparse_grad=True) →
row-sparse tape cotangent → lazy per-row optimizer update, see
optimizer.Optimizer.update_row_sparse) is an eager-mode path with
per-step host work; it wins when the table is large relative to the
batch's touched rows (measured: 3.3x over dense at vocab 500k/dim 64
with adam; dense wins below ~10k rows). Under jit (hybridize /
ShardedTrainer) gradients stay dense and XLA fuses the scatter.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .ndarray import NDArray, array as _dense_array

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix",
           "row_sparse_array", "BaseSparseNDArray"]


class BaseSparseNDArray:
    @property
    def stype(self):
        raise NotImplementedError

    def asnumpy(self):
        raise NotImplementedError

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return _dense_array(self.asnumpy())
        raise MXNetError(f"cannot convert {self.stype} to {stype}")

    @property
    def size(self):
        return int(np.prod(self.shape))

    def __repr__(self):
        return (f"<{self.__class__.__name__} {self.shape} "
                f"stype={self.stype}>")


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (ref: CSRNDArray)."""

    def __init__(self, data, indices, indptr, shape, dtype=None):
        self.data = np.asarray(data, dtype=dtype or np.float32)
        self.indices = np.asarray(indices, dtype=np.int64)
        self.indptr = np.asarray(indptr, dtype=np.int64)
        self.shape = tuple(shape)
        if len(self.shape) != 2:
            raise MXNetError("CSR arrays are 2-D")
        if len(self.indptr) != self.shape[0] + 1:
            raise MXNetError("indptr length must be rows+1")

    @property
    def stype(self):
        return "csr"

    @property
    def dtype(self):
        return self.data.dtype

    def asnumpy(self):
        out = np.zeros(self.shape, dtype=self.data.dtype)
        for r in range(self.shape[0]):
            lo, hi = self.indptr[r], self.indptr[r + 1]
            out[r, self.indices[lo:hi]] = self.data[lo:hi]
        return out

    def _to_bcoo(self):
        from jax.experimental import sparse as jsparse
        import jax.numpy as jnp
        rows = np.repeat(np.arange(self.shape[0]),
                         np.diff(self.indptr))
        coords = np.stack([rows, self.indices], axis=1)
        return jsparse.BCOO((jnp.asarray(self.data),
                             jnp.asarray(coords)), shape=self.shape)

    def dot(self, rhs):
        """CSR @ dense on jax's BCOO sparse kernels (ref: sparse dot in
        src/operator/tensor/dot.cc csr path)."""
        from jax.experimental import sparse as jsparse
        rhs_data = rhs._data if isinstance(rhs, NDArray) else \
            np.asarray(rhs)
        out = self._to_bcoo() @ rhs_data
        return NDArray(out, _skip_device_put=True)

    def copyto(self, other):
        raise MXNetError("copyto on sparse arrays: use tostype('default')")


class RowSparseNDArray(BaseSparseNDArray):
    """Only a subset of rows stored (ref: RowSparseNDArray — the gradient
    format of Embedding/sparse pull)."""

    def __init__(self, data, indices, shape, dtype=None):
        self.data = np.asarray(data, dtype=dtype or np.float32)
        self.indices = np.asarray(indices, dtype=np.int64)
        self.shape = tuple(shape)
        if self.data.shape[0] != len(self.indices):
            raise MXNetError("data rows must match indices length")

    @property
    def stype(self):
        return "row_sparse"

    @property
    def dtype(self):
        return self.data.dtype

    def asnumpy(self):
        out = np.zeros(self.shape, dtype=self.data.dtype)
        out[self.indices] = self.data
        return out

    def retain(self, row_ids):
        """ref: sparse.retain — keep only the given rows."""
        row_ids = np.asarray(row_ids, dtype=np.int64)
        mask = np.isin(self.indices, row_ids)
        return RowSparseNDArray(self.data[mask], self.indices[mask],
                                self.shape)


class _RowSparseCT:
    """Internal row-sparse cotangent flowing through the autograd tape
    (the Embedding sparse_grad backward, ref: indexing_op.cc
    SparseEmbeddingOpBackwardRspImpl). ``rows`` may contain duplicates
    until :func:`dedupe_rows` folds them at leaf-deposit time."""
    __slots__ = ("rows", "values", "shape")

    def __init__(self, rows, values, shape):
        self.rows = rows          # jax/np int array [nnz]
        self.values = values      # jax/np array [nnz, row_width]
        self.shape = tuple(shape)

    def todense(self):
        import jax.numpy as jnp
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[self.rows].add(self.values)


def dedupe_rows(ct):
    """_RowSparseCT -> RowSparseNDArray with unique sorted rows and
    summed duplicate contributions."""
    rows = np.asarray(ct.rows).reshape(-1)
    vals = np.asarray(ct.values).reshape(len(rows), -1)
    uniq, inv = np.unique(rows, return_inverse=True)
    summed = np.zeros((len(uniq), vals.shape[1]), vals.dtype)
    np.add.at(summed, inv, vals)
    return RowSparseNDArray(
        summed.reshape((len(uniq),) + ct.shape[1:]), uniq, ct.shape,
        dtype=vals.dtype)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """ref: nd.sparse.csr_matrix — from (data, indices, indptr) or dense."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indices, indptr, shape, dtype=dtype)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else \
        np.asarray(arg1)
    if dense.ndim != 2:
        raise MXNetError("csr_matrix needs a 2-D input")
    indptr = [0]
    indices, data = [], []
    for row in dense:
        nz = np.nonzero(row)[0]
        indices.extend(nz.tolist())
        data.extend(row[nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(data, indices, indptr, dense.shape,
                      dtype=dtype or dense.dtype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """ref: nd.sparse.row_sparse_array."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(data, indices, shape, dtype=dtype)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else \
        np.asarray(arg1)
    nz_rows = np.nonzero(np.any(dense != 0, axis=tuple(
        range(1, dense.ndim))))[0]
    return RowSparseNDArray(dense[nz_rows], nz_rows, dense.shape,
                            dtype=dtype or dense.dtype)

"""Eager op dispatch.

TPU-native equivalent of the reference's imperative invoke path
(ref: src/c_api/c_api_ndarray.cc MXImperativeInvokeEx ->
src/imperative/imperative.cc Imperative::Invoke): coerce hyperparameters,
run the op's pure jax function (asynchronously dispatched by PjRt — the
ThreadedEngine's job happens inside the runtime), and, if autograd is
recording, capture the ``jax.vjp`` pullback on the tape
(ref: Imperative::RecordOp).
"""
from __future__ import annotations

from typing import Sequence

import jax

from . import _rng, engine
from .base import MXNetError
from .ops.registry import get as get_op

__all__ = ["invoke", "set_amp_cast_hook"]

# Per-op AMP cast policy (ref: the amp_cast pairs the reference's graph
# pass inserts from its fp16 allow/deny lists, python/mxnet/contrib/amp/
# lists/symbol_fp16.py). Installed by contrib.amp.init when op lists are
# given; called with (op_name, datas, params) and returns the input arrays
# recast per policy. Runs on eager arrays and on tracers alike, so the
# policy applies inside hybridized/jitted programs too.
_amp_cast_hook = None
_amp_epoch = 0      # bumped on every policy change: jit caches key on it


def set_amp_cast_hook(fn):
    global _amp_cast_hook, _amp_epoch
    _amp_cast_hook = fn
    _amp_epoch += 1


def amp_epoch():
    """Monotonic counter of AMP-policy changes. Compiled-program caches
    (HybridBlock._cached_fns, ShardedTrainer) include it in their keys so
    installing/clearing a per-op cast policy retraces instead of silently
    running the stale program."""
    return _amp_epoch


def _tracked(arr) -> bool:
    return (getattr(arr, "_tape_node", None) is not None
            or getattr(arr, "_grad", None) is not None)


def _as_context(value):
    """Accept Context objects or 'tpu' / 'tpu(0)' strings."""
    from .context import Context
    if isinstance(value, Context):
        return value
    if isinstance(value, str):
        if "(" in value:
            kind, _, rest = value.partition("(")
            return Context(kind, int(rest.rstrip(")")))
        return Context(value, 0)
    raise MXNetError(f"invalid ctx argument: {value!r}")


def _tape_wiring(inputs, datas):
    """Per-input tape graph wiring: (parents, fwd_inputs) where each
    parent is (TapeNode | None, out_index, leaf_NDArray | None)."""
    from .ndarray import NDArray
    parents = []
    fwd_inputs = []
    for x, d in zip(inputs, datas):
        if isinstance(x, NDArray) and getattr(x, "_grad", None) is not None:
            parents.append((None, 0, x))            # leaf
        elif isinstance(x, NDArray) and \
                getattr(x, "_tape_node", None) is not None:
            parents.append((x._tape_node, x._tape_out_idx, None))
        else:
            parents.append((None, 0, None))         # constant
        fwd_inputs.append(x if isinstance(x, NDArray) else d)
    return parents, fwd_inputs


def invoke(op, inputs: Sequence, kwargs: dict, out=None):
    """Run operator `op` on NDArray `inputs`; returns NDArray or list."""
    from .autograd import TapeNode, is_recording, is_training
    from .ndarray import NDArray

    if isinstance(op, str):
        op = get_op(op)
    params = op.coerce_params(kwargs)
    call_kwargs = dict(params)
    if op.needs_rng:
        call_kwargs["rng"] = _rng.next_key()
    if op.needs_mode and "training" not in call_kwargs:
        call_kwargs["training"] = is_training()

    datas = []
    for x in inputs:
        if isinstance(x, NDArray):
            datas.append(x._data)
        else:
            import jax.numpy as jnp
            datas.append(jnp.asarray(x))

    if _amp_cast_hook is not None:
        datas = _amp_cast_hook(op.name, datas, params)

    n_out = op.num_outputs(params) if callable(op.num_outputs) else op.num_outputs

    recording = (is_recording() and op.differentiable
                 and any(_tracked(x) for x in inputs if isinstance(x, NDArray)))

    if recording and op.name == "Embedding" \
            and call_kwargs.get("sparse_grad") \
            and not isinstance(datas[0], jax.core.Tracer):
        # eager sparse-grad path: the weight cotangent is emitted as a
        # row-sparse (rows=batch indices, values=output cotangent) instead
        # of a dense scatter over the full table (ref: indexing_op.cc
        # SparseEmbeddingOpBackwardRspImpl). Under jit tracing (hybridize/
        # ShardedTrainer) the dense path below applies — XLA fuses the
        # scatter there anyway.
        from .ndarray.sparse import _RowSparseCT
        out_data = op.fn(*datas, **call_kwargs)
        idx_data, w_data = datas[0], datas[1]
        w_shape = tuple(w_data.shape)

        def sparse_vjp(ct):
            import numpy as _np
            import jax.numpy as jnp
            rows = jnp.reshape(idx_data, (-1,)).astype(jnp.int32)
            vals = jnp.reshape(ct, (rows.shape[0], w_shape[1]))
            idx_ct = _np.zeros(idx_data.shape, dtype=jax.dtypes.float0)
            return (idx_ct, _RowSparseCT(rows, vals, w_shape))

        outs = [out_data]
        avals = [jax.ShapeDtypeStruct(out_data.shape, out_data.dtype)]
        parents, fwd_inputs = _tape_wiring(inputs, datas)
        node = TapeNode(sparse_vjp, parents, avals, fwd_fn=op.fn,
                        fwd_kwargs=call_kwargs, fwd_inputs=fwd_inputs)
    elif recording:
        fn = lambda *arrays: op.fn(*arrays, **call_kwargs)
        out_data, vjp_fn = jax.vjp(fn, *datas)
        outs = list(out_data) if isinstance(out_data, tuple) else [out_data]
        avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]
        parents, fwd_inputs = _tape_wiring(inputs, datas)
        node = TapeNode(vjp_fn, parents, avals, fwd_fn=op.fn,
                        fwd_kwargs=call_kwargs, fwd_inputs=fwd_inputs)
    else:
        out_data = op.fn(*datas, **call_kwargs)
        outs = list(out_data) if isinstance(out_data, tuple) else [out_data]
        node = None

    explicit_ctx = _as_context(params.get("ctx")) if params.get("ctx") else None
    ctx = explicit_ctx
    if ctx is None:
        for x in inputs:
            if isinstance(x, NDArray):
                ctx = x.ctx
                break
    if ctx is None:
        from .context import current_context
        ctx = current_context()

    engine.on_op_done(outs[0])

    results = []
    for i, o in enumerate(outs):
        # explicit ctx (creation ops): commit the output to that device
        nd = NDArray(o, ctx=ctx, _skip_device_put=explicit_ctx is None)
        if node is not None:
            nd._tape_node = node
            nd._tape_out_idx = i
        results.append(nd)

    if out is not None:
        targets = out if isinstance(out, (list, tuple)) else [out]
        for tgt, res in zip(targets, results):
            tgt._rebind(res._data)
            tgt._tape_node = getattr(res, "_tape_node", None)
            tgt._tape_out_idx = getattr(res, "_tape_out_idx", 0)
        return out

    if n_out == 1 or len(results) == 1:
        return results[0]
    return results

"""Weight initializers (ref: python/mxnet/initializer.py).

Same registry + name-pattern dispatch design as the reference: an
``Initializer`` is called with an ``InitDesc`` (parameter name + attrs) and
fills an NDArray; `_init_weight/_init_bias/_init_gamma/...` dispatch by the
parameter-name suffix exactly like the reference's ``__call__``.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from . import ndarray as nd

__all__ = ["InitDesc", "Initializer", "Uniform", "Normal", "Constant", "Zero",
           "One", "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias",
           "Mixed", "register", "create"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if isinstance(name, str):
        if name.lower() not in _REGISTRY:
            raise MXNetError(f"unknown initializer {name!r}")
        return _REGISTRY[name.lower()](**kwargs)
    raise MXNetError(f"cannot create initializer from {name!r}")


class InitDesc(str):
    """Parameter name + attrs handed to initializers (ref: InitDesc)."""
    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer with the reference's name-suffix dispatch."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init = desc.attrs.get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- default fills ------------------------------------------------------
    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_bias(self, desc, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_gamma(self, desc, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_beta(self, desc, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_zero(self, desc, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_one(self, desc, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    @staticmethod
    def _set(arr, value):
        arr._rebind(nd.array(value, dtype=arr.dtype)._data)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._kwargs})"


@register
class Uniform(Initializer):
    """U(-scale, scale) — the reference's default (scale=0.07)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        self._set(arr, np.random.uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        self._set(arr, np.random.normal(0, self.sigma, arr.shape))


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        val = self.value
        if isinstance(val, nd.NDArray):
            self._set(arr, val.asnumpy())
        else:
            self._set(arr, np.full(arr.shape, val))

    _init_default = _init_weight


@register
class Zero(Constant):
    def __init__(self):
        super().__init__(0.0)


@register
class One(Constant):
    def __init__(self):
        super().__init__(1.0)


# the reference accepts 'zeros'/'ones' spellings (mx.init.Zero aliases)
_REGISTRY["zeros"] = Zero
_REGISTRY["ones"] = One


@register
class Xavier(Initializer):
    """Glorot init (ref: initializer.py Xavier) — default for conv nets."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(f"Xavier requires >=2D weight, got {shape} "
                             f"for {desc}")
        if len(shape) > 2:
            hw_scale = float(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, np.random.uniform(-scale, scale, shape))
        else:
            self._set(arr, np.random.normal(0, scale, shape))


@register
class MSRAPrelu(Xavier):
    """He init for PReLU nets (ref: initializer.py MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1, 1, (nout, nin))
        else:
            tmp = np.random.normal(0, 1, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        self._set(arr, (self.scale * q).reshape(arr.shape))


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (ref: initializer.py Bilinear)."""

    def _init_weight(self, desc, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (ref: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = np.zeros(arr.shape)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias  # i, f, g, o order
        self._set(arr, b)

    _init_bias = _init_weight


class Mixed:
    """Pattern->initializer dispatch (ref: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        import re
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must pair up")
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, desc, arr):
        for prog, init in self.map:
            if prog.search(str(desc)):
                init(desc, arr)
                return
        raise MXNetError(f"no initializer pattern matches {desc}")

"""Graph passes — the NNVM pass machinery + subgraph-hook analog
(ref: nnvm::ApplyPass / src/operator/subgraph/ SubgraphProperty,
env MXNET_SUBGRAPH_BACKEND; SURVEY §2.2 #12).

XLA already does the heavy rewriting (fusion, layout, CSE *within* a
compiled program); these passes operate on the Symbol DAG *before* bind,
where graph-level decisions live — dedup of repeated subgraphs across the
Python-built DAG, pattern substitutions toward custom kernels, etc.
Custom backends register passes and are selected with
``MXNET_SUBGRAPH_BACKEND=<name>[,<name>…]`` exactly like the reference's
subgraph-backend hook.
"""
from __future__ import annotations

import warnings

from ..base import MXNetError, getenv
from ..ops import registry as _registry
from .symbol import Symbol, _Node

__all__ = ["register_pass", "apply_pass", "apply_env_passes", "list_passes"]

_PASSES = {}


def register_pass(name):
    """Decorator: register ``fn(Symbol) -> Symbol`` as a named pass."""
    def deco(fn):
        _PASSES[name] = fn
        return fn
    return deco


def list_passes():
    return sorted(_PASSES)


def apply_pass(sym: Symbol, name: str) -> Symbol:
    """ref: nnvm::ApplyPass."""
    if name not in _PASSES:
        raise MXNetError(f"unknown graph pass {name!r}; "
                         f"known: {list_passes()}")
    return _PASSES[name](sym)


def apply_env_passes(sym: Symbol) -> Symbol:
    """Apply the passes selected by MXNET_SUBGRAPH_BACKEND (comma list) —
    the reference's subgraph-backend activation point (bind time)."""
    backends = getenv("MXNET_SUBGRAPH_BACKEND", "")
    for name in filter(None, (b.strip() for b in backends.split(","))):
        if name in _PASSES:
            sym = _PASSES[name](sym)
        else:                  # lenient like the reference, but visible
            warnings.warn(f"MXNET_SUBGRAPH_BACKEND: unknown pass {name!r} "
                          f"ignored (known: {list_passes()})")
    return sym


@register_pass("CSE")
def common_subexpression_elimination(sym: Symbol) -> Symbol:
    """Merge structurally identical nodes (same op, same attrs, same
    inputs) so duplicated Python-built subgraphs compile & execute once
    (ref: nnvm pass 'CommonSubexprElim' era; XLA CSEs *within* a program,
    this dedups at the graph level so shared work is traced once)."""
    canon = {}      # signature -> canonical _Node
    rebuilt = {}    # id(old node) -> new _Node

    def key_of(node, new_inputs):
        # op node signature: names intentionally excluded — structurally
        # identical ops are the same computation regardless of name
        attrs = tuple(sorted((k, str(v)) for k, v in node.attrs.items()))
        ins = tuple((id(s._node), s._index) for s in new_inputs)
        return (node.op, attrs, ins)

    def _mergeable(node):
        if node.op is None or node.op == "_group":
            return False
        try:
            op = _registry.get(node.op)
        except MXNetError:
            return False
        # stochastic ops draw a fresh PRNG key per node — merging them
        # would collapse independent random draws into one shared draw
        return not op.needs_rng

    def rebuild(node):
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        new_inputs = [Symbol(rebuild(s._node), s._index)
                      for s in node.inputs]
        # variables unify by NAME (two auto-created `fc_weight` vars are
        # one argument — binding is name-keyed); ops unify structurally
        if node.op is None:
            sig = ("var", node.name)
        elif _mergeable(node):
            sig = key_of(node, new_inputs)
        else:
            sig = ("unique", id(node))
        if sig in canon:
            new = canon[sig]
        else:
            new = _Node(node.op, node.name, new_inputs, dict(node.attrs),
                        num_outputs=node.num_outputs)
            canon[sig] = new
        rebuilt[id(node)] = new
        return new

    return Symbol(rebuild(sym._node), sym._index)


@register_pass("FuseAttention")
def fuse_attention(sym: Symbol) -> Symbol:
    """Rewrite full-attention subgraphs to the fused flash-attention op at
    bind time — the stated purpose of keeping the subgraph hook (SURVEY §2
    #12: 'keep a pass hook for Pallas-fused attention'). Two patterns:

    1. ``batch_dot(softmax(batch_dot(q, k, transpose_b=True) [*/ scale],
       axis=-1), v)`` -> ``_contrib_flash_attention(q, k, v,
       sm_scale=scale)`` — the graph's explicit scale (1.0 when it had
       none) passes through sm_scale verbatim, overriding the op's
       d^-0.5 default, so the rewrite is exact for any scale.
    2. The reference's fused transformer pair
       ``_contrib_interleaved_matmul_selfatt_valatt(qkv,
       softmax(_contrib_interleaved_matmul_selfatt_qk(qkv, heads)))``
       -> reshape/transpose + flash + inverse reshape (one compiled
       attention kernel instead of two matmuls with a materialized
       [B*H, S, S] score tensor).

    Activate with ``MXNET_SUBGRAPH_BACKEND=FuseAttention`` like the
    reference's subgraph backends.
    """
    from .symbol import _create

    rebuilt = {}

    def is_softmax_lastdim(node):
        # a temperature or length attr changes the math / applies masking:
        # those softmaxes must NOT be rewritten away
        return node.op in ("softmax", "Softmax") and \
            int(node.attrs.get("axis", -1)) in (-1,) and \
            not node.attrs.get("temperature") and \
            node.attrs.get("length") is None

    def match_pattern1(node):
        """outer batch_dot(att, v): returns (q, k, v, scale) or None."""
        if node.op != "batch_dot" or node.attrs.get("transpose_a") or \
                node.attrs.get("transpose_b"):
            return None
        att, v = node.inputs
        an = att._node
        if not is_softmax_lastdim(an):
            return None
        scores = an.inputs[0]._node
        scale = 1.0
        if scores.op == "_mul_scalar":
            scale = float(scores.attrs.get("scalar", 1.0))
            scores = scores.inputs[0]._node
        elif scores.op == "_div_scalar":
            scale = 1.0 / float(scores.attrs.get("scalar", 1.0))
            scores = scores.inputs[0]._node
        if scores.op != "batch_dot" or scores.attrs.get("transpose_a") \
                or not scores.attrs.get("transpose_b"):
            return None
        q, k = scores.inputs
        return q, k, v, scale

    def match_pattern2(node):
        """valatt(qkv, softmax(qk(qkv))): returns (qkv, heads) or None."""
        if node.op != "_contrib_interleaved_matmul_selfatt_valatt":
            return None
        qkv, att = node.inputs
        an = att._node
        if not is_softmax_lastdim(an):
            return None
        qk = an.inputs[0]._node
        if qk.op != "_contrib_interleaved_matmul_selfatt_qk":
            return None
        if qk.inputs[0]._node is not qkv._node:
            return None
        return qkv, int(qk.attrs["heads"])

    def rebuild(node):
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        m1 = match_pattern1(node) if node.op else None
        m2 = match_pattern2(node) if node.op else None
        if m1 is not None:
            q, k, v, scale = m1
            qn = Symbol(rebuild(q._node), q._index)
            kn = Symbol(rebuild(k._node), k._index)
            vn = Symbol(rebuild(v._node), v._index)
            # the graph's explicit scale (or 1.0 when it had none) passes
            # through sm_scale verbatim — exact rewrite, no shape needed
            new = _create("_contrib_flash_attention", [qn, kn, vn],
                          {"sm_scale": scale}, name=node.name + "_flash")
            rebuilt[id(node)] = new._node
            return new._node
        if m2 is not None:
            qkv, heads = m2
            qkvn = Symbol(rebuild(qkv._node), qkv._index)
            h = heads
            # interleaved layout: (T, N, 3E) decomposes per head as
            # (T, N, H, 3, D) — see _interleaved_qk's reshape. Slice
            # q/k/v on the '3' axis, go to (N, H, T, D) for flash, and
            # invert afterwards.
            r1 = _create("reshape", [qkvn], {"shape": (0, 0, -4, h, -1)},
                         name=node.name + "_qh")       # (T, N, H, 3D)
            r2 = _create("reshape", [r1],
                         {"shape": (0, 0, 0, -4, 3, -1)},
                         name=node.name + "_q3")       # (T, N, H, 3, D)
            outs = []
            for i, nm in enumerate(("q", "k", "v")):
                sl = _create("slice_axis", [r2],
                             {"axis": 3, "begin": i, "end": i + 1},
                             name=f"{node.name}_{nm}sl")  # (T,N,H,1,D)
                sq = _create("reshape", [sl], {"shape": (0, 0, 0, -1)},
                             name=f"{node.name}_{nm}sq")  # (T, N, H, D)
                tr = _create("transpose", [sq],
                             {"axes": (1, 2, 0, 3)},
                             name=f"{node.name}_{nm}t")   # (N, H, T, D)
                outs.append(tr)
            fa = _create("_contrib_flash_attention", outs, {},
                         name=node.name + "_flash")
            # (N, H, T, D) -> (T, N, E)
            back = _create("transpose", [fa], {"axes": (2, 0, 1, 3)},
                           name=node.name + "_bt")
            out = _create("reshape", [back], {"shape": (0, 0, -3)},
                          name=node.name + "_merge")
            rebuilt[id(node)] = out._node
            return out._node
        new_inputs = [Symbol(rebuild(s._node), s._index)
                      for s in node.inputs]
        new = _Node(node.op, node.name, new_inputs, dict(node.attrs),
                    num_outputs=node.num_outputs)
        rebuilt[id(node)] = new
        return new

    return Symbol(rebuild(sym._node), sym._index)

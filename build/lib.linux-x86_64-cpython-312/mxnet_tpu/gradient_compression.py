"""2-bit gradient compression with error-feedback residual
(ref: src/kvstore/gradient_compression.cc GradientCompression).

Same semantics as the reference: values ≥ threshold quantize to
+threshold, ≤ -threshold to -threshold, the rest to 0; the quantization
error accumulates in a per-key residual added to the next gradient
(error feedback), so the scheme is unbiased over time. The reference
compresses to 2 bits on the wire between worker and server; here the
codec runs around the DCN all-reduce (and is exercised by the kvstore
tests even single-process).
"""
from __future__ import annotations

import jax.numpy as jnp

from .base import MXNetError

__all__ = ["GradientCompression"]


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):
        if type != "2bit":
            raise MXNetError(f"unsupported compression type {type!r}; the "
                             f"reference implements '2bit' only as well")
        if threshold <= 0:
            raise MXNetError("compression threshold must be positive")
        self.type = type
        self.threshold = float(threshold)
        self._residual = {}

    def compress(self, key, grad_data):
        """Quantize with error feedback; returns the dequantized gradient
        (what the receiving end reconstructs)."""
        t = self.threshold
        res = self._residual.get(key)
        if res is None:
            res = jnp.zeros_like(grad_data)
        g = grad_data + res
        q = jnp.where(g >= t, t, jnp.where(g <= -t, -t, 0.0)) \
            .astype(grad_data.dtype)
        self._residual[key] = g - q
        return q

    def reset(self):
        self._residual = {}

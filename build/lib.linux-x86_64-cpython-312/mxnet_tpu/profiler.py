"""``mx.profiler`` — profiling facade (ref: python/mxnet/profiler.py over
src/profiler/profiler.cc).

The reference's profiler instruments the engine's op execution and writes
chrome://tracing JSON (SURVEY §5.1). On TPU the equivalent truth source is
the XLA/JAX profiler (xplane traces viewable in TensorBoard/Perfetto,
including per-op device timing), so this facade drives ``jax.profiler``
under the reference's API: ``set_config`` + ``set_state('run'/'stop')``,
scoped ``Marker``/``scope`` (→ ``jax.profiler.TraceAnnotation`` so Gluon
block names appear on device traces), and ``dumps()`` for a host-side
aggregate table.
"""
from __future__ import annotations

import os
import time
from collections import defaultdict

from .base import MXNetError

__all__ = ["set_config", "set_state", "state", "dumps", "dump", "pause",
           "resume", "Marker", "scope"]

_config = {"filename": "profile.json", "profile_all": False,
           "profile_symbolic": True, "profile_imperative": True,
           "profile_memory": True, "profile_api": True,
           "aggregate_stats": False}
_state = "stop"
_trace_dir = None
_agg = defaultdict(lambda: [0, 0.0])    # name -> [count, total_sec]


def set_config(**kwargs):
    """ref: profiler.py set_config(filename=..., profile_all=...)."""
    _config.update(kwargs)


def set_state(state_name="stop", profile_process="worker"):
    """'run' starts a JAX profiler trace; 'stop' ends it. The trace
    directory derives from the configured filename."""
    global _state, _trace_dir
    import jax
    if state_name == _state:
        return
    if state_name == "run":
        base = _config.get("filename", "profile.json")
        _trace_dir = os.path.splitext(base)[0] + "_trace"
        os.makedirs(_trace_dir, exist_ok=True)
        jax.profiler.start_trace(_trace_dir)
        _state = "run"
    elif state_name == "stop":
        try:
            jax.profiler.stop_trace()
        except RuntimeError:
            pass
        _state = "stop"
    else:
        raise MXNetError(f"invalid profiler state {state_name!r}")


def state():
    return _state


def pause(profile_process="worker"):
    set_state("stop")


def resume(profile_process="worker"):
    set_state("run")


def dump(finished=True, profile_process="worker"):
    """Finish the trace (the xplane files under <filename>_trace are the
    chrome-trace analog; open with TensorBoard's profile plugin)."""
    set_state("stop")


def dumps(reset=False, format="table"):
    """Host-side aggregate of Marker/scope timings (the reference's
    aggregate_stats table, ref: src/profiler/aggregate_stats.cc)."""
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
    for name, (count, total) in sorted(_agg.items()):
        avg = total / count * 1e3 if count else 0.0
        lines.append(f"{name:<40}{count:>8}{total * 1e3:>12.3f}{avg:>12.3f}")
    if reset:
        _agg.clear()
    return "\n".join(lines)


class Marker:
    """Scoped annotation: host-side aggregate timing + device-trace
    annotation (ref: profiler.py Marker / mx.profiler.scope)."""

    def __init__(self, name, scope_name="<unk>"):
        self.name = name
        self._ann = None
        self._t0 = None

    def __enter__(self):
        import jax
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        entry = _agg[self.name]
        entry[0] += 1
        entry[1] += dt
        self._ann.__exit__(*exc)

    # one-shot API parity (ref: Marker.mark)
    def mark(self, scope_name="process"):
        entry = _agg[self.name]
        entry[0] += 1


def scope(name="<unk>:"):
    return Marker(name)

"""Pipeline parallelism over a ``pipe`` mesh axis (net-new capability:
MXNet 1.x has no pipeline schedule — SURVEY §2.4 #32 marks PP absent; the
reference's closest tool is hand `ctx_group` placement).

Design (GPipe-style, TPU-idiomatic):
- every pipeline stage runs the SAME traced computation with its own
  parameter shard (stage params stacked on a leading axis sharded over
  ``pipe``) — SPMD-friendly: one program, P devices;
- microbatches stream through a static tick loop; activations hop to the
  next stage via ``lax.ppermute`` (one ICI neighbor hop per tick);
- the schedule is differentiable end-to-end: jax transposes the ppermute
  chain, so backward is the reverse pipeline automatically — no hand-rolled
  1F1B bookkeeping;
- bubbles: (P-1) ticks of the M+P-1 total, the standard GPipe cost; use
  microbatches ≥ 4×P to amortize.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..base import MXNetError

try:
    from jax import shard_map
except ImportError:                      # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn, stage_params, x, mesh: Mesh = None,
                   axis_name="pipe", num_microbatches=None):
    """Run ``x`` through P pipeline stages.

    stage_fn(params_i, x) -> y        same signature for every stage
    stage_params: pytree whose leaves are stacked (P, ...) — stage i's
        slice feeds device i (sharded over ``axis_name``)
    x: (B, ...) global batch; split into ``num_microbatches`` chunks
        (default: pipeline depth).

    Returns the (B, ...) output of the final stage, replicated.
    """
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if axis_name not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    p_size = mesh.shape[axis_name]
    m = num_microbatches or p_size
    b = x.shape[0]
    if b % m:
        raise MXNetError(f"batch {b} not divisible by {m} microbatches")
    micro = x.reshape((m, b // m) + x.shape[1:])

    param_spec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stage_params)
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    def body(params_local, micro_all):
        # params_local leaves: (1, ...) — this device's stage
        params_i = jax.tree_util.tree_map(lambda a: a[0], params_local)
        d = lax.axis_index(axis_name)
        is_first = d == 0
        is_last = d == p_size - 1
        micro_bs = micro_all.shape[1]

        def stage_step(cur, t):
            # device 0 injects microbatch t (if any); others take the
            # activation that just arrived
            inj_idx = jnp.clip(t, 0, m - 1)
            injected = micro_all[inj_idx]
            inp = jnp.where(is_first, injected.astype(cur.dtype), cur)
            y = stage_fn(params_i, inp)
            nxt = lax.ppermute(y, axis_name, perm)
            return nxt, y

        # probe output shape of one stage application
        cur0 = jnp.zeros_like(stage_fn(params_i, micro_all[0]))
        _, ys = lax.scan(stage_step, cur0, jnp.arange(m + p_size - 1))
        # microbatch j exits the last stage at tick j + (P-1)
        outs = ys[p_size - 1:]
        outs = jnp.where(is_last, outs, jnp.zeros_like(outs))
        outs = lax.psum(outs, axis_name)       # broadcast from last stage
        return outs.reshape((m * micro_bs,) + outs.shape[2:])

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P())
    return fn(stage_params, micro)

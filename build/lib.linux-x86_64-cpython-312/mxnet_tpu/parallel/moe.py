"""Mixture-of-Experts with expert parallelism over an ``expert`` mesh axis
(net-new capability: MXNet 1.x has no MoE dispatch — SURVEY §2.4 #32).

Design: experts' parameters are stacked on a leading axis sharded over
``expert``; under ``shard_map`` each device computes its own expert over
the full token batch, masked/weighted by the router's gate, and the
outputs combine with one ``psum`` over ICI. This is the dense-dispatch
formulation — compute O(E·tokens) instead of all-to-all token exchange,
which is the robust choice at small expert counts (the all-to-all variant
drops in behind the same API when profiling demands it); routing is top-1
(Switch-style) with everything differentiable, including the gate.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..base import MXNetError

try:
    from jax import shard_map
except ImportError:                      # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["moe_apply"]


def moe_apply(expert_fn, expert_params, gate_logits, x, mesh: Mesh = None,
              axis_name="expert"):
    """Top-1-routed mixture of experts.

    expert_fn(params_e, x) -> y       same signature for every expert
    expert_params: pytree with leaves stacked (E, ...), sharded over
        ``axis_name``
    gate_logits: (B, E) router scores (a Dense over x, computed outside)
    x: (B, D) tokens.

    Returns (B, D_out): each token processed by its argmax expert, scaled
    by the (differentiable) gate probability — Switch-transformer routing.
    """
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if axis_name not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    e_size = mesh.shape[axis_name]
    if gate_logits.shape[-1] != e_size:
        raise MXNetError(f"gate width {gate_logits.shape[-1]} != expert "
                         f"axis size {e_size}")
    param_spec = jax.tree_util.tree_map(lambda _: P(axis_name),
                                        expert_params)

    def body(params_local, gates, xs):
        e = lax.axis_index(axis_name)
        params_e = jax.tree_util.tree_map(lambda a: a[0], params_local)
        probs = jax.nn.softmax(gates, axis=-1)            # (B, E)
        top = jnp.argmax(probs, axis=-1)                  # (B,)
        weight = jnp.where(top == e, probs[:, e], 0.0)    # (B,)
        y = expert_fn(params_e, xs)                       # (B, D_out)
        y = y * weight[:, None].astype(y.dtype)
        return lax.psum(y, axis_name)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(param_spec, P(), P()),
                   out_specs=P())
    return fn(expert_params, gate_logits, x)

#!/usr/bin/env python
"""kvstore bandwidth measurement (ref: tools/bandwidth/measure.py —
the reference's kvstore perf tool). Measures push/pull/pushpull rates for
a ladder of tensor sizes on the selected kvstore type.

Usage: mx-bandwidth [--kv-type device] [--sizes 1e5 1e6 1e7] [--iters 10]
"""
from __future__ import annotations

import argparse
import time


def main():
    parser = argparse.ArgumentParser(
        description="kvstore push/pull bandwidth "
                    "(ref: tools/bandwidth/measure.py)")
    parser.add_argument("--kv-type", default="device")
    parser.add_argument("--sizes", type=float, nargs="+",
                        default=[1e5, 1e6, 1e7])
    parser.add_argument("--iters", type=int, default=10)
    args = parser.parse_args()

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    kv = mx.kv.create(args.kv_type)
    print(f"kvstore type={kv.type} workers={kv.num_workers}")
    print(f"{'size':>12} {'push GB/s':>10} {'pull GB/s':>10} "
          f"{'pushpull GB/s':>14}")
    for size in args.sizes:
        n = int(size)
        key = f"bw{n}"
        val = nd.array(np.random.randn(n).astype(np.float32))
        out = nd.zeros((n,))
        kv.init(key, val)
        nbytes = n * 4

        def timed(fn):
            fn()                         # warm
            t0 = time.perf_counter()
            for _ in range(args.iters):
                fn()
                out.wait_to_read()       # block on THIS iteration's work
            return nbytes * args.iters / (time.perf_counter() - t0) / 1e9

        def push_synced():
            kv.push(key, val)
            kv._store[key].wait_to_read()   # block on the reduce itself
                                            # (no pull bytes credited)

        push = timed(push_synced)
        pull = timed(lambda: kv.pull(key, out=out))
        pushpull = timed(lambda: kv.pushpull(key, val, out=out))
        print(f"{n:>12d} {push:>10.2f} {pull:>10.2f} {pushpull:>14.2f}")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Parse training logs into a table (ref: tools/parse_log.py): extracts
per-epoch train/validation metrics and Speedometer throughput."""
from __future__ import annotations

import argparse
import re
import sys


def parse(lines):
    rows = {}
    speed = {}
    re_metric = re.compile(
        r"Epoch\[(\d+)\]\s+(Train|Validation)-([\w-]+)=([\d.eE+-]+)")
    re_speed = re.compile(
        r"Epoch\[(\d+)\]\s+Batch\s*\[\d+\]\s+Speed:\s*([\d.]+)")
    re_time = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([\d.]+)")
    for line in lines:
        m = re_metric.search(line)
        if m:
            epoch, kind, name, val = m.groups()
            rows.setdefault(int(epoch), {})[f"{kind.lower()}-{name}"] = \
                float(val)
        m = re_speed.search(line)
        if m:
            speed.setdefault(int(m.group(1)), []).append(float(m.group(2)))
        m = re_time.search(line)
        if m:
            rows.setdefault(int(m.group(1)), {})["time"] = \
                float(m.group(2))
    for epoch, speeds in speed.items():
        rows.setdefault(epoch, {})["speed"] = sum(speeds) / len(speeds)
    return rows


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("logfile", nargs="?", default="-")
    parser.add_argument("--format", default="markdown",
                        choices=["markdown", "csv"])
    args = parser.parse_args()
    lines = sys.stdin if args.logfile == "-" else open(args.logfile)
    rows = parse(lines)
    if not rows:
        print("no metrics found", file=sys.stderr)
        return
    cols = sorted({k for r in rows.values() for k in r})
    if args.format == "csv":
        print("epoch," + ",".join(cols))
        for epoch in sorted(rows):
            print(f"{epoch}," + ",".join(
                str(rows[epoch].get(c, "")) for c in cols))
    else:
        print("| epoch | " + " | ".join(cols) + " |")
        print("|" + "---|" * (len(cols) + 1))
        for epoch in sorted(rows):
            print(f"| {epoch} | " + " | ".join(
                f"{rows[epoch][c]:.6g}" if c in rows[epoch] else ""
                for c in cols) + " |")


if __name__ == "__main__":
    main()

"""Command-line tools (ref: tools/ — im2rec, launch, parse_log), installed
as console scripts (mx-im2rec / mx-launch / mx-parse-log) by the package
metadata; thin wrappers in the repo-root tools/ keep the reference's
`python tools/launch.py ...` invocation working."""

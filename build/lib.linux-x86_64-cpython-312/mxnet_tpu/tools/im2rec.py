#!/usr/bin/env python
"""im2rec — pack an image dataset into RecordIO (ref: tools/im2rec.py).

Two modes, same CLI shape as the reference:
  --list: generate a .lst file (index \\t label \\t relpath) from a folder
  default: pack images named by a .lst into prefix.rec (+ .idx)
"""
from __future__ import annotations

import argparse
import os
import random
import sys


def list_image(root, recursive, exts):
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in os.walk(root, followlinks=True):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and suffix in exts:
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and suffix in exts:
                yield (i, os.path.relpath(fpath, root), 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            line = [i.strip() for i in line.strip().split("\t")]
            if len(line) < 3:
                continue
            yield (int(line[0]), line[-1], [float(i) for i in line[1:-1]])


def make_list(args):
    image_list = list(list_image(args.root, args.recursive, args.exts))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    n = len(image_list)
    chunk_size = (n + args.chunks - 1) // args.chunks
    for i in range(args.chunks):
        chunk = image_list[i * chunk_size:(i + 1) * chunk_size]
        suffix = f"_{i}" if args.chunks > 1 else ""
        sep = int(len(chunk) * args.train_ratio)
        sep_test = int(len(chunk) * args.test_ratio)
        if args.train_ratio == 1.0:
            write_list(args.prefix + suffix + ".lst", chunk)
        else:
            if args.test_ratio:
                write_list(args.prefix + suffix + "_test.lst",
                           chunk[:sep_test])
            if args.train_ratio + args.test_ratio < 1.0:
                write_list(args.prefix + suffix + "_val.lst",
                           chunk[sep_test + sep:])
            write_list(args.prefix + suffix + "_train.lst",
                       chunk[sep_test:sep_test + sep])


def image_encode(args, item):
    from mxnet_tpu import recordio

    fullpath = os.path.join(args.root, item[1])
    header = recordio.IRHeader(0, item[2] if len(item[2]) > 1
                               else item[2][0], item[0], 0)
    if args.pass_through:
        # raw pack never decodes: keep cv2 optional for this mode
        with open(fullpath, "rb") as fin:
            img = fin.read()
        return recordio.pack(header, img)
    import cv2
    img = cv2.imread(fullpath, args.color)
    if img is None:
        print(f"imread error: {fullpath}", file=sys.stderr)
        return None
    if args.center_crop:
        if img.shape[0] > img.shape[1]:
            margin = (img.shape[0] - img.shape[1]) // 2
            img = img[margin:margin + img.shape[1], :]
        else:
            margin = (img.shape[1] - img.shape[0]) // 2
            img = img[:, margin:margin + img.shape[0]]
    if args.resize:
        if img.shape[0] > img.shape[1]:
            newsize = (args.resize,
                       img.shape[0] * args.resize // img.shape[1])
        else:
            newsize = (img.shape[1] * args.resize // img.shape[0],
                       args.resize)
        img = cv2.resize(img, newsize)
    return recordio.pack_img(header, img, quality=args.quality,
                             img_fmt=args.encoding)


def make_rec(args):
    from mxnet_tpu import recordio
    fname = os.path.basename(args.prefix)
    working_dir = os.path.dirname(os.path.abspath(args.prefix)) or "."
    for lst_name in sorted(os.listdir(working_dir)):
        if not (lst_name.startswith(fname) and lst_name.endswith(".lst")):
            continue
        lst_path = os.path.join(working_dir, lst_name)
        base = os.path.splitext(lst_path)[0]
        record = recordio.MXIndexedRecordIO(base + ".idx", base + ".rec",
                                            "w")
        count = 0
        for item in read_list(lst_path):
            packed = image_encode(args, item)
            if packed is None:
                continue
            record.write_idx(item[0], packed)
            count += 1
            if count % 1000 == 0:
                print(f"{lst_name}: packed {count} images")
        record.close()
        print(f"{base}.rec: {count} images")


def main():
    parser = argparse.ArgumentParser(
        description="Create an image list / RecordIO pack "
                    "(ref: tools/im2rec.py)")
    parser.add_argument("prefix", help="prefix of .lst/.rec files")
    parser.add_argument("root", help="root folder of images")
    cgroup = parser.add_argument_group("list options")
    cgroup.add_argument("--list", action="store_true")
    cgroup.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    cgroup.add_argument("--chunks", type=int, default=1)
    cgroup.add_argument("--train-ratio", type=float, default=1.0)
    cgroup.add_argument("--test-ratio", type=float, default=0)
    cgroup.add_argument("--recursive", action="store_true")
    cgroup.add_argument("--no-shuffle", dest="shuffle",
                        action="store_false")
    rgroup = parser.add_argument_group("rec options")
    rgroup.add_argument("--pass-through", action="store_true")
    rgroup.add_argument("--resize", type=int, default=0)
    rgroup.add_argument("--center-crop", action="store_true")
    rgroup.add_argument("--quality", type=int, default=95)
    rgroup.add_argument("--encoding", type=str, default=".jpg")
    rgroup.add_argument("--color", type=int, default=1,
                        choices=[-1, 0, 1])
    args = parser.parse_args()
    if args.list:
        make_list(args)
    else:
        make_rec(args)


if __name__ == "__main__":
    main()

"""Self-contained ONNX protobuf wire codec.

The environment has no ``onnx`` pip and no network, but the ONNX file
format is just protobuf wire encoding of a stable, documented schema
(onnx/onnx.proto). This module encodes/decodes the subset of that schema
the converters use — ModelProto / GraphProto / NodeProto / AttributeProto /
TensorProto / ValueInfoProto — directly to/from bytes, so ``export_model``
writes real ``.onnx`` files that the official ``onnx``/onnxruntime stack
can load, and ``import_model`` reads files they produce. No third-party
dependency involved (ref: python/mxnet/contrib/onnx/ requires the onnx
pip for the same job).

The in-memory representation is plain dicts/lists ("dict-proto"):

    model = {"ir_version": 8, "opset": 13, "producer_name": "mxnet_tpu",
             "graph": {"name": str,
                       "inputs":  [{"name", "dtype", "shape"}],
                       "outputs": [{"name", "dtype", "shape"}],
                       "initializers": [{"name", "data": np.ndarray}],
                       "nodes": [{"op_type", "name", "inputs": [str],
                                  "outputs": [str], "attrs": {...}}]}}

Attr values may be int, float, str, bytes, list[int], list[float],
or np.ndarray (encoded as a TensorProto attribute).
"""
from __future__ import annotations

import struct

import numpy as np

from ...base import MXNetError

# ONNX TensorProto.DataType enum (onnx.proto) <-> numpy
DTYPE_TO_ONNX = {
    np.dtype("float32"): 1, np.dtype("uint8"): 2, np.dtype("int8"): 3,
    np.dtype("uint16"): 4, np.dtype("int16"): 5, np.dtype("int32"): 6,
    np.dtype("int64"): 7, np.dtype("bool"): 9, np.dtype("float16"): 10,
    np.dtype("float64"): 11, np.dtype("uint32"): 12, np.dtype("uint64"): 13,
}
ONNX_TO_DTYPE = {v: k for k, v in DTYPE_TO_ONNX.items()}
ONNX_TO_DTYPE[16] = np.dtype("float32")  # bfloat16 tensors load as fp32


# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------
def _varint(n):
    n &= 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field, wire):
    return _varint((field << 3) | wire)


def _ld(field, payload):                      # length-delimited
    return _key(field, 2) + _varint(len(payload)) + payload


def _vint(field, value):                      # varint field (int64 semantics)
    return _key(field, 0) + _varint(int(value))


def _vstr(field, s):
    return _ld(field, s.encode() if isinstance(s, str) else s)


def _vfloat(field, f):                        # 32-bit float field
    return _key(field, 5) + struct.pack("<f", float(f))


def _read_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def _fields(buf):
    """Iterate (field_number, wire_type, value) over a message payload."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 1:
            v = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos:pos + 4]
            pos += 4
        else:
            raise MXNetError(f"unsupported protobuf wire type {wire}")
        yield field, wire, v


def _packed_varints(payload):
    out, pos = [], 0
    while pos < len(payload):
        v, pos = _read_varint(payload, pos)
        out.append(_signed(v))
    return out


# ---------------------------------------------------------------------------
# encoding
# ---------------------------------------------------------------------------
def _enc_tensor(name, arr):
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in DTYPE_TO_ONNX:
        raise MXNetError(f"ONNX export: unsupported dtype {arr.dtype}")
    out = b"".join(_vint(1, d) for d in arr.shape)
    out += _vint(2, DTYPE_TO_ONNX[arr.dtype])
    out += _vstr(8, name)
    out += _ld(9, arr.tobytes())              # raw_data, little-endian
    return out


def _enc_attr(name, value):
    out = _vstr(1, name)
    if isinstance(value, bool):
        value = int(value)
    if isinstance(value, float):
        out += _vfloat(2, value) + _vint(20, 1)           # FLOAT
    elif isinstance(value, int):
        out += _vint(3, value) + _vint(20, 2)             # INT
    elif isinstance(value, (str, bytes)):
        out += _vstr(4, value) + _vint(20, 3)             # STRING
    elif isinstance(value, np.ndarray):
        out += _ld(5, _enc_tensor(name + "_t", value)) + _vint(20, 4)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            out += b"".join(_key(7, 5) + struct.pack("<f", v)
                            for v in value) + _vint(20, 6)   # FLOATS
        else:
            out += b"".join(_vint(8, int(v)) for v in value) \
                + _vint(20, 7)                               # INTS
    else:
        raise MXNetError(f"ONNX export: bad attribute {name}={value!r}")
    return out


def _enc_value_info(vi):
    tensor_type = _vint(1, DTYPE_TO_ONNX[np.dtype(vi.get("dtype",
                                                         "float32"))])
    shape = vi.get("shape")
    if shape is not None:
        # absent shape field = unknown rank (ONNX semantics), encoded as
        # shape=None; shape=() is a genuine rank-0 scalar and gets an
        # empty TensorShapeProto
        shape_msg = b"".join(
            _ld(1, _vint(1, d) if isinstance(d, int) and d > 0
                else _vstr(2, str(d or "?")))
            for d in shape)
        tensor_type += _ld(2, shape_msg)
    return _vstr(1, vi["name"]) + _ld(2, _ld(1, tensor_type))


def _enc_node(node):
    out = b"".join(_vstr(1, i) for i in node["inputs"])
    out += b"".join(_vstr(2, o) for o in node["outputs"])
    out += _vstr(3, node.get("name", node["outputs"][0]))
    out += _vstr(4, node["op_type"])
    out += b"".join(_ld(5, _enc_attr(k, v))
                    for k, v in sorted(node.get("attrs", {}).items()))
    return out


def encode_model(model):
    """dict-proto -> ONNX ModelProto bytes."""
    g = model["graph"]
    graph = b"".join(_ld(1, _enc_node(n)) for n in g["nodes"])
    graph += _vstr(2, g.get("name", "mxnet_tpu"))
    graph += b"".join(_ld(5, _enc_tensor(t["name"], np.asarray(t["data"])))
                      for t in g.get("initializers", []))
    graph += b"".join(_ld(11, _enc_value_info(v)) for v in g["inputs"])
    graph += b"".join(_ld(12, _enc_value_info(v)) for v in g["outputs"])
    out = _vint(1, model.get("ir_version", 8))
    out += _vstr(2, model.get("producer_name", "mxnet_tpu"))
    out += _ld(8, _vstr(1, "") + _vint(2, model.get("opset", 13)))
    out += _ld(7, graph)
    return out


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------
def _dec_tensor(buf):
    dims, dtype, raw, name = [], 1, None, ""
    float_data, int64_data, int32_data, double_data = [], [], [], []
    for field, wire, v in _fields(buf):
        if field == 1:
            dims.extend(_packed_varints(v) if wire == 2 else [_signed(v)])
        elif field == 2:
            dtype = v
        elif field == 4:
            float_data.extend(
                struct.unpack(f"<{len(v)//4}f", v) if wire == 2
                else struct.unpack("<f", v))
        elif field == 5:
            int32_data.extend(_packed_varints(v) if wire == 2
                              else [_signed(v)])
        elif field == 7:
            int64_data.extend(_packed_varints(v) if wire == 2
                              else [_signed(v)])
        elif field == 8:
            name = v.decode()
        elif field == 9:
            raw = v
        elif field == 10:
            double_data.extend(
                struct.unpack(f"<{len(v)//8}d", v) if wire == 2
                else struct.unpack("<d", v))
    np_dtype = ONNX_TO_DTYPE.get(dtype)
    if np_dtype is None:
        raise MXNetError(f"ONNX import: unsupported tensor dtype {dtype}")
    if raw is not None:
        if dtype == 16:   # bfloat16 raw: widen to fp32
            u = np.frombuffer(raw, dtype=np.uint16).astype(np.uint32) << 16
            arr = u.view(np.float32)
        else:
            arr = np.frombuffer(raw, dtype=np_dtype)
    elif float_data:
        arr = np.asarray(float_data, np.float32)
    elif double_data:
        arr = np.asarray(double_data, np.float64)
    elif int64_data:
        arr = np.asarray(int64_data, np.int64)
    elif int32_data:
        arr = np.asarray(int32_data, np.int32)
    else:
        arr = np.zeros(0, np_dtype)
    return {"name": name, "data": arr.astype(np_dtype, copy=False)
            .reshape(dims)}


def _dec_attr(buf):
    name, atype = "", None
    val = {}
    ints, floats, strs = [], [], []
    for field, wire, v in _fields(buf):
        if field == 1:
            name = v.decode()
        elif field == 2:
            val["f"] = struct.unpack("<f", v)[0]
        elif field == 3:
            val["i"] = _signed(v)
        elif field == 4:
            val["s"] = v
        elif field == 5:
            val["t"] = _dec_tensor(v)["data"]
        elif field == 7:
            floats.extend(struct.unpack(f"<{len(v)//4}f", v) if wire == 2
                          else struct.unpack("<f", v))
        elif field == 8:
            ints.extend(_packed_varints(v) if wire == 2 else [_signed(v)])
        elif field == 20:
            atype = v
    if atype == 1:
        return name, val.get("f", 0.0)
    if atype == 2:
        return name, val.get("i", 0)
    if atype == 3:
        s = val.get("s", b"")
        try:
            return name, s.decode()
        except UnicodeDecodeError:
            return name, s
    if atype == 4:
        return name, val.get("t")
    if atype == 6:
        return name, list(floats)
    if atype == 7:
        return name, list(ints)
    # untyped (some exporters omit type when value fields disambiguate)
    if "f" in val:
        return name, val["f"]
    if "i" in val:
        return name, val["i"]
    if floats:
        return name, list(floats)
    if ints:
        return name, list(ints)
    if "s" in val:
        return name, val["s"].decode()
    return name, None


def _dec_node(buf):
    node = {"inputs": [], "outputs": [], "attrs": {}, "op_type": "",
            "name": ""}
    for field, wire, v in _fields(buf):
        if field == 1:
            node["inputs"].append(v.decode())
        elif field == 2:
            node["outputs"].append(v.decode())
        elif field == 3:
            node["name"] = v.decode()
        elif field == 4:
            node["op_type"] = v.decode()
        elif field == 5:
            k, val = _dec_attr(v)
            node["attrs"][k] = val
    return node


def _dec_value_info(buf):
    out = {"name": "", "dtype": "float32", "shape": ()}
    for field, wire, v in _fields(buf):
        if field == 1:
            out["name"] = v.decode()
        elif field == 2:                        # TypeProto
            for f2, w2, v2 in _fields(v):
                if f2 != 1:                     # tensor_type only
                    continue
                for f3, w3, v3 in _fields(v2):
                    if f3 == 1:
                        out["dtype"] = str(ONNX_TO_DTYPE.get(v3,
                                                             "float32"))
                    elif f3 == 2:               # TensorShapeProto
                        dims = []
                        for f4, w4, v4 in _fields(v3):
                            if f4 != 1:
                                continue
                            dim = 0
                            for f5, w5, v5 in _fields(v4):
                                if f5 == 1:
                                    dim = _signed(v5)
                                elif f5 == 2:
                                    dim = 0     # symbolic dim -> unknown
                            dims.append(dim)
                        out["shape"] = tuple(dims)
    return out


def _dec_graph(buf):
    g = {"name": "", "nodes": [], "initializers": [], "inputs": [],
         "outputs": []}
    for field, wire, v in _fields(buf):
        if field == 1:
            g["nodes"].append(_dec_node(v))
        elif field == 2:
            g["name"] = v.decode()
        elif field == 5:
            g["initializers"].append(_dec_tensor(v))
        elif field == 11:
            g["inputs"].append(_dec_value_info(v))
        elif field == 12:
            g["outputs"].append(_dec_value_info(v))
    return g


def decode_model(buf):
    """ONNX ModelProto bytes -> dict-proto."""
    model = {"ir_version": 0, "opset": 0, "producer_name": "",
             "graph": None}
    for field, wire, v in _fields(buf):
        if field == 1:
            model["ir_version"] = _signed(v)
        elif field == 2:
            model["producer_name"] = v.decode()
        elif field == 7:
            model["graph"] = _dec_graph(v)
        elif field == 8:
            for f2, w2, v2 in _fields(v):
                if f2 == 2:
                    model["opset"] = max(model["opset"], _signed(v2))
    if model["graph"] is None:
        raise MXNetError("ONNX import: no graph in model file")
    return model

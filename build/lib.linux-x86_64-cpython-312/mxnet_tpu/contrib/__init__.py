"""``mx.contrib`` (ref: python/mxnet/contrib/__init__.py): amp, plus
stubs that document intentional TPU divergences."""
from . import amp
from . import onnx
from . import quantization

__all__ = ["amp", "onnx", "quantization"]

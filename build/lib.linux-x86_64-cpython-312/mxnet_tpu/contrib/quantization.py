"""INT8 quantization (ref: python/mxnet/contrib/quantization.py).

The reference's calibration flow (entropy/minmax thresholds feeding
quantized_conv/fc kernels, SURVEY §2 #19) targets INT8 GEMMs. On TPU the
idiomatic equivalent is AQT-style quantized XLA matmuls; this round ships
calibration utilities and documents the kernel gap explicitly rather than
pretending parity.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["quantize_model", "quantize_net", "calib_thresholds_minmax",
           "calib_thresholds_entropy"]


def calib_thresholds_minmax(arrays):
    """Per-tensor min/max calibration (ref: quantization.py _LayerOutput
    MinMaxCollector)."""
    out = {}
    for name, arr in arrays.items():
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        out[name] = (float(a.min()), float(a.max()))
    return out


def _smooth(p, eps=0.0001):
    """ref: quantization.py _smooth_distribution — move eps mass onto
    zero bins so KL is defined."""
    is_zero = p == 0
    n_zero = is_zero.sum()
    n_nonzero = p.size - n_zero
    if n_nonzero == 0:
        return None
    eps1 = eps * n_zero / n_nonzero
    out = p.astype(np.float64).copy()
    out[is_zero] = eps
    out[~is_zero] -= eps1
    if (out[~is_zero] <= 0).any():
        return None
    return out


def _optimal_threshold(a, num_bins=2001, num_quantized_bins=255):
    """KL-divergence threshold search over the |activation| histogram
    (ref: quantization.py _get_optimal_threshold). Clipped distribution p
    (outlier mass saturated into the last bin) is compared against its
    255-level quantization q, with q's per-group mass redistributed over
    the group's nonzero bins like the reference does."""
    amax = float(a.max()) if a.size else 0.0
    if amax == 0:
        return 0.0
    hist, edges = np.histogram(a, bins=num_bins, range=(0, amax))
    best_kl, best_t = np.inf, amax
    step = max(1, (num_bins - num_quantized_bins) // 256)
    for i in range(num_quantized_bins, num_bins + 1, step):
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()
        if p.sum() == 0:
            continue
        nonzero = (p != 0)
        # quantize the i bins into num_quantized_bins groups
        group = (np.arange(i) * num_quantized_bins) // i
        sums = np.bincount(group, weights=hist[:i].astype(np.float64),
                           minlength=num_quantized_bins)
        counts = np.bincount(group, weights=nonzero.astype(np.float64),
                             minlength=num_quantized_bins)
        q = np.zeros(i)
        with np.errstate(divide="ignore", invalid="ignore"):
            per_bin = np.where(counts > 0, sums / np.maximum(counts, 1),
                               0.0)
        q[nonzero] = per_bin[group[nonzero]]
        # smooth the raw count vectors (reference order: smooth, then the
        # KL normalizes) — smoothing after normalization would drive small
        # bins negative and skip valid candidates
        ps = _smooth(p)
        qs = _smooth(q) if q.sum() else None
        if ps is None or qs is None:
            continue
        ps = ps / ps.sum()
        qs = qs / qs.sum()
        kl = float(np.sum(ps * np.log(ps / qs)))
        if kl < best_kl:
            best_kl, best_t = kl, edges[i]
    return best_t


def calib_thresholds_entropy(arrays, num_bins=2001, num_quantized_bins=255):
    """KL-divergence calibration per tensor (ref: quantization.py
    _get_optimal_thresholds)."""
    out = {}
    for name, arr in arrays.items():
        a = np.abs(np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy")
                              else arr)).ravel()
        t = _optimal_threshold(a, num_bins=num_bins,
                               num_quantized_bins=num_quantized_bins)
        out[name] = (-t, t)
    return out


def _collect_layer_inputs(sym, arg_params, aux_params, calib_data,
                          data_names, tensor_names, max_batches):
    """Run calib batches through the graph internals and collect the
    fp32 values of ``tensor_names`` (the inputs of to-be-quantized ops)
    (ref: quantization.py _collect_layer_statistics)."""
    from .. import ndarray as nd
    from ..context import current_context
    internals = sym.get_internals()
    by_name = {}
    for s in internals:
        by_name.setdefault(s.name, s)
    wanted = [n for n in tensor_names if n in by_name]
    if not wanted:
        return {}
    from ..symbol import Group
    group = Group([by_name[n] for n in wanted])
    collected = {n: [] for n in wanted}
    # convert params once, outside the per-batch loop
    args_nd = {k: v if isinstance(v, nd.NDArray) else nd.array(v)
               for k, v in arg_params.items()}
    aux_nd = {k: v if isinstance(v, nd.NDArray) else nd.array(v)
              for k, v in aux_params.items()}
    n_done = 0
    for batch in calib_data:
        datas = batch if isinstance(batch, (list, tuple)) else [batch]
        binds = dict(zip(data_names, [nd.array(d) for d in datas]))
        binds.update(args_nd)
        ex = group.bind(current_context(), binds, aux_states=aux_nd)
        outs = ex.forward()
        for n, o in zip(wanted, outs):
            collected[n].append(o.asnumpy())
        n_done += 1
        if max_batches is not None and n_done >= max_batches:
            break
    return {n: np.concatenate([a.ravel() for a in arrs])
            for n, arrs in collected.items() if arrs}


_QUANTIZABLE = ("Convolution", "FullyConnected")

# ops an int8 (q, scale) value can flow THROUGH without dequantizing —
# the int8-subgraph surface (ref: src/operator/subgraph/mkldnn int8
# fusion, SURVEY §2 #12/#19)
_INT8_STRUCTURAL = ("Flatten", "Reshape", "reshape", "squeeze",
                    "expand_dims")


def fold_batchnorm(sym, arg_params, aux_params, eps_default=1e-3):
    """Fold inference BatchNorm into the preceding Convolution's weights
    and bias (ref: the reference's quantization flow runs on BN-folded
    graphs; mkldnn subgraph conv+bn fusion). Returns (sym', args', aux').

    Only folds when the conv feeds ONLY this BN (its scale/shift is then
    a per-channel affine on the conv output) and only BN output 0 is
    consumed. Unfoldable BNs stay; they become int8-chain breakers."""
    import numpy as np

    from ..symbol import Group
    from ..symbol.symbol import Symbol, _create
    arg_np = {k: (v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v))
              for k, v in arg_params.items()}
    aux_np = {k: (v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v))
              for k, v in aux_params.items()}
    topo = sym._topo()
    consumers = {}
    out_syms = sym._output_symbols() if hasattr(sym, "_output_symbols") \
        else [sym]
    for node in topo:
        for s in node.inputs:
            consumers.setdefault(id(s._node), {}).setdefault(
                s._index, 0)
            consumers[id(s._node)][s._index] += 1
    for s in out_syms:
        consumers.setdefault(id(s._node), {}).setdefault(s._index, 0)
        consumers[id(s._node)][s._index] += 1

    new_of = {}

    def mapped(s):
        if s._node.op is None:
            return Symbol(s._node, s._index)
        return new_of[id(s._node)][s._index]

    for node in topo:
        if node.op is None or node.op == "_group":
            continue
        fold = False
        if node.op == "BatchNorm":
            src = node.inputs[0]._node
            names = [i._node.name for i in node.inputs[1:5]]
            conv_sole = (src.op == "Convolution"
                         and consumers.get(id(src), {}).get(0, 0) == 1
                         and sum(consumers.get(id(src), {}).values()) == 1)
            bn_outs_ok = all(i == 0 or c == 0 for i, c in
                             consumers.get(id(node), {}).items())
            names_ok = (names[0] in arg_np and names[1] in arg_np
                        and names[2] in aux_np and names[3] in aux_np)
            wname = src.inputs[1]._node.name if len(src.inputs) > 1 else None
            # (use_global_stats is irrelevant here: inference always
            # normalizes by the moving statistics being folded)
            fold = conv_sole and bn_outs_ok and names_ok and wname in arg_np
        if fold:
            src = node.inputs[0]._node
            g_name, b_name = [i._node.name for i in node.inputs[1:3]]
            m_name, v_name = [i._node.name for i in node.inputs[3:5]]
            eps = float(node.attrs.get("eps", eps_default) or eps_default)
            fix_gamma = str(node.attrs.get("fix_gamma",
                                           "True")) in ("True", "1", "true")
            gamma = np.ones_like(arg_np[g_name]) if fix_gamma \
                else arg_np[g_name]
            beta = arg_np[b_name]
            mean, varr = aux_np[m_name], aux_np[v_name]
            inv = gamma / np.sqrt(varr + eps)
            wname = src.inputs[1]._node.name
            w = arg_np[wname]
            w_new = w * inv.reshape((-1,) + (1,) * (w.ndim - 1))
            no_bias = str(src.attrs.get("no_bias",
                                        "False")) in ("True", "1", "true")
            b_old = 0.0 if no_bias else arg_np[
                src.inputs[2]._node.name]
            b_new = (b_old - mean) * inv + beta
            folded_w = wname + "_bnfold"
            folded_b = wname + "_bnfold_bias"   # collision-proof vs folded_w
            arg_np[folded_w] = w_new.astype(w.dtype)
            arg_np[folded_b] = b_new.astype(np.float32)
            from ..symbol.symbol import var as _var
            plain = {k: v for k, v in src.attrs.items()
                     if not k.startswith("__")}
            plain["no_bias"] = False
            conv_in = mapped(src.inputs[0])
            out = _create("Convolution",
                          [conv_in, _var(folded_w), _var(folded_b)],
                          plain, name=src.name + "_bnfold")
            new_of[id(node)] = [out] + [out] * 2   # mean/var outs unused
            continue
        ins = [mapped(s) for s in node.inputs]
        plain = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        out = _create(node.op, ins, plain, name=node.name)
        new_of[id(node)] = [Symbol(out._node, i)
                            for i in range(node.num_outputs)]

    mapped_outs = [mapped(s) for s in out_syms]
    new_sym = mapped_outs[0] if len(mapped_outs) == 1 \
        else Group(mapped_outs)
    referenced = set(new_sym.list_arguments()) \
        | set(new_sym.list_auxiliary_states())
    args_out = {k: v for k, v in arg_np.items() if k in referenced}
    aux_out = {k: v for k, v in aux_np.items() if k in referenced}
    return new_sym, args_out, aux_out


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   excluded_sym_names=(), calib_mode="none",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", ctx=None, logger=None,
                   fold_bn=True):
    """Rewrite Convolution/FullyConnected nodes to int8 compute and keep
    CHAINS int8 (ref: python/mxnet/contrib/quantization.py quantize_model
    + src/operator/subgraph/mkldnn int8 fusion).

    Pipeline: (1) inference BatchNorms fold into their convolutions
    (``fold_bn``); (2) quantizable ops emit (int8, scale) whenever a
    consumer can stay int8; (3) Pooling / ReLU / residual adds / Concat /
    reshape-family ops run DIRECTLY on int8 — a ResNet residual block is
    one quantize at entry and one dequantize at exit, not a round-trip
    per layer.

    Returns (qsym, qarg_params, aux_params). Weights are pre-quantized
    per-output-channel; activations quantize at runtime with a static
    scale when calibrated (``calib_mode`` 'naive'/'entropy') or a dynamic
    per-batch scale (``calib_mode='none'``). Compute is a real int8
    GEMM/conv accumulated in int32 (ops/quantization.py).
    """
    from ..symbol.symbol import Symbol, _create, var
    if quantized_dtype != "int8":
        raise MXNetError(f"quantized_dtype {quantized_dtype!r}: only "
                         f"'int8' is supported (symmetric)")
    excluded = set(excluded_sym_names or ())

    if fold_bn:
        sym, arg_params, aux_params = fold_batchnorm(
            sym, arg_params, aux_params)
    arg_np = {k: (v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v))
              for k, v in arg_params.items()}

    def _is_excluded(name):
        return name in excluded or (name.endswith("_bnfold")
                                    and name[:-len("_bnfold")] in excluded)

    topo = sym._topo()

    def _tensor_name(s):
        return s.name

    # which tensors need activation calibration: data inputs of q-ops
    # AND q-op outputs (the chain path requantizes the producer's output
    # to int8 — a static scale there needs the OUTPUT's range, matching
    # the reference's requantize.cc calibrated mode)
    calib_tensors = []
    for node in topo:
        if node.op in _QUANTIZABLE and not _is_excluded(node.name):
            calib_tensors.append(_tensor_name(node.inputs[0]))
            calib_tensors.append(node.name)
    thresholds = {}
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} needs calib_data")
        arrays = _collect_layer_inputs(
            sym, arg_params, aux_params, calib_data, list(data_names),
            calib_tensors, num_calib_examples)
        calib_fn = (calib_thresholds_minmax if calib_mode == "naive"
                    else calib_thresholds_entropy)
        thresholds = calib_fn(arrays)

    # consumer-op map (folded graph): does any consumer keep int8 alive?
    out_syms = sym._output_symbols() if hasattr(sym, "_output_symbols") \
        else [sym]
    consumer_ops = {}
    for node in topo:
        for s in node.inputs:
            consumer_ops.setdefault((id(s._node), s._index),
                                    []).append(node)
    _ADD_OPS = ("elemwise_add", "_plus", "broadcast_add")

    def _int8_capable_producer(n2):
        """One-level check: will node n2 plausibly produce int8?"""
        return ((n2.op in _QUANTIZABLE and not _is_excluded(n2.name))
                or n2.op in _INT8_STRUCTURAL
                or (n2.op == "Pooling"
                    and n2.attrs.get("pool_type", "max") in ("max", "avg"))
                or n2.op == "relu"
                or (n2.op == "Activation"
                    and n2.attrs.get("act_type") == "relu"))

    def _keeps_int8(node, out_idx=0):
        """True if at least one consumer of this output consumes int8."""
        for c in consumer_ops.get((id(node), out_idx), ()):
            if c.op in _QUANTIZABLE and not _is_excluded(c.name) \
                    and c.inputs[0]._node is node:
                return True
            if c.op in _INT8_STRUCTURAL \
                    or (c.op == "Pooling"
                        and c.attrs.get("pool_type", "max") in
                        ("max", "avg")) \
                    or c.op == "relu" \
                    or (c.op == "Activation"
                        and c.attrs.get("act_type") == "relu"):
                return True
            if c.op in _ADD_OPS and len(c.inputs) == 2:
                # only worth emitting int8 if the add's OTHER side will
                # be int8 too — otherwise the add runs fp32 and the
                # requantize round-trip just loses precision
                other = c.inputs[1]._node if c.inputs[0]._node is node \
                    else c.inputs[0]._node
                if _int8_capable_producer(other):
                    return True
            if c.op == "Concat" and all(
                    _int8_capable_producer(s._node) or s._node is node
                    for s in c.inputs):
                return True
        return False

    qargs = {}
    new_of = {}      # id(old node) -> list[Symbol] fp32 outputs (lazy)
    int8_of = {}     # id(old node) -> {out_idx: (q_sym, scale_sym)}
    deq_cache = {}

    def mapped(s):
        """fp32 view of an old symbol (dequantize an int8 pair once)."""
        node = s._node
        if node.op is None:
            return Symbol(node, s._index)
        if id(node) in new_of:
            return new_of[id(node)][s._index]
        key = (id(node), s._index)
        if key not in deq_cache:
            q, sc = int8_of[id(node)][s._index]
            deq_cache[key] = _create(
                "_contrib_dequantize", [q, sc], {},
                name=f"{node.name}_dequantize")
        return deq_cache[key]

    def mapped_int8(s):
        """(q, scale) view if this old symbol carries int8, else None."""
        return int8_of.get(id(s._node), {}).get(s._index)

    def _store_fp(node, syms):
        new_of[id(node)] = list(syms)

    def _store_int8(node, idx, pair):
        int8_of.setdefault(id(node), {})[idx] = pair

    for node in topo:
        if node.op is None or node.op == "_group":
            continue
        if node.op in _QUANTIZABLE and not _is_excluded(node.name) \
                and node.inputs[1]._node.op is None \
                and node.inputs[1]._node.name in arg_np:
            wname = node.inputs[1]._node.name
            # don't pop: another (e.g. excluded or weight-sharing) layer
            # may still reference the fp32 weight; unreferenced originals
            # are dropped against the rebuilt graph at the end
            w = arg_np[wname]
            if wname + "_quantized" not in qargs:
                from ..ops.quantization import quantize_array
                wq, wscale = quantize_array(w, channel_axis=0)
                qargs[wname + "_quantized"] = np.asarray(wq)
                qargs[wname + "_scale"] = np.asarray(wscale)
            wq_sym = var(wname + "_quantized")
            ws_sym = var(wname + "_scale")
            in_pair = mapped_int8(node.inputs[0])
            if in_pair is not None:
                xq, xscale = in_pair          # chain: no re-quantize
            else:
                in_name = _tensor_name(node.inputs[0])
                qkw = {}
                if in_name in thresholds:
                    lo, hi = thresholds[in_name]
                    qkw = {"min_calib_range": float(lo),
                           "max_calib_range": float(hi)}
                xq_pair = _create("_contrib_quantize_v2",
                                  [mapped(node.inputs[0])], qkw,
                                  name=f"{node.name}_x_quantize")
                xq, xscale = xq_pair[0], xq_pair[1]
            emit_int8 = _keeps_int8(node)
            bias_ins = [mapped(s) for s in node.inputs[2:]] \
                if not node.attrs.get("no_bias") else []
            common = {"no_bias": node.attrs.get("no_bias", False),
                      "out_type": "int8" if emit_int8 else "float32"}
            if emit_int8 and node.name in thresholds:
                # static requantize scale from the calibrated OUTPUT range
                lo, hi = thresholds[node.name]
                common["min_calib_range"] = float(lo)
                common["max_calib_range"] = float(hi)
            if node.op == "FullyConnected":
                out = _create(
                    "_contrib_quantized_fully_connected",
                    [xq, wq_sym, xscale, ws_sym] + bias_ins,
                    {"num_hidden": node.attrs["num_hidden"],
                     "flatten": node.attrs.get("flatten", True),
                     **common},
                    name=f"{node.name}_quantized")
            else:
                out = _create(
                    "_contrib_quantized_conv",
                    [xq, wq_sym, xscale, ws_sym] + bias_ins,
                    {"kernel": node.attrs["kernel"],
                     "stride": node.attrs.get("stride"),
                     "dilate": node.attrs.get("dilate"),
                     "pad": node.attrs.get("pad"),
                     "num_filter": node.attrs["num_filter"],
                     "num_group": node.attrs.get("num_group", 1),
                     **common},
                    name=f"{node.name}_quantized")
            if emit_int8:
                _store_int8(node, 0, (out[0], out[1]))
            else:
                _store_fp(node, [out])
            continue
        # int8-transparent consumers: stay int8 when the input is int8
        pair0 = mapped_int8(node.inputs[0]) if node.inputs else None
        if pair0 is not None and node.op == "Pooling" \
                and node.attrs.get("pool_type", "max") in ("max", "avg"):
            q, sc = pair0
            out = _create(
                "_contrib_quantized_pooling", [q, sc],
                {"kernel": node.attrs.get("kernel", ()),
                 "pool_type": node.attrs.get("pool_type", "max"),
                 "global_pool": node.attrs.get("global_pool", False),
                 "stride": node.attrs.get("stride"),
                 "pad": node.attrs.get("pad"),
                 "pooling_convention":
                     node.attrs.get("pooling_convention", "valid")},
                name=f"{node.name}_quantized")
            _store_int8(node, 0, (out[0], out[1]))
            continue
        if pair0 is not None and (
                node.op == "relu" or (node.op == "Activation"
                                      and node.attrs.get("act_type")
                                      == "relu")):
            q, sc = pair0
            out = _create("_contrib_quantized_act", [q, sc],
                          {"act_type": "relu"},
                          name=f"{node.name}_quantized")
            _store_int8(node, 0, (out[0], out[1]))
            continue
        if pair0 is not None and node.op in _INT8_STRUCTURAL:
            q, sc = pair0
            plain = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            out = _create(node.op, [q], plain,
                          name=f"{node.name}_quantized")
            _store_int8(node, 0, (out, sc))
            continue
        if node.op in _ADD_OPS and len(node.inputs) == 2:
            pa, pb = mapped_int8(node.inputs[0]), \
                mapped_int8(node.inputs[1])
            if pa is not None and pb is not None:
                out = _create("_contrib_quantized_elemwise_add",
                              [pa[0], pa[1], pb[0], pb[1]], {},
                              name=f"{node.name}_quantized")
                _store_int8(node, 0, (out[0], out[1]))
                continue
        if node.op == "Concat" and node.inputs and all(
                mapped_int8(s) is not None for s in node.inputs):
            pairs = [mapped_int8(s) for s in node.inputs]
            out = _create(
                "_contrib_quantized_concat",
                [p[0] for p in pairs] + [p[1] for p in pairs],
                {"num_args": len(pairs),
                 "dim": node.attrs.get("dim", 1)},
                name=f"{node.name}_quantized")
            _store_int8(node, 0, (out[0], out[1]))
            continue
        # everything else consumes fp32 (dequantizing pairs at most once)
        ins = [mapped(s) for s in node.inputs]
        # scoped attrs (__ctx_group__ etc.) aren't op params; re-add
        # them after creation like symbol.load_json does
        plain = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        scoped = {k: v for k, v in node.attrs.items()
                  if k.startswith("__")}
        out = _create(node.op, ins, plain, name=node.name)
        out._node.attrs.update(scoped)
        _store_fp(node, [Symbol(out._node, i)
                         for i in range(node.num_outputs)])

    mapped_outs = [mapped(s) for s in out_syms]
    from ..symbol import Group
    qsym = mapped_outs[0] if len(mapped_outs) == 1 else Group(mapped_outs)
    from .. import ndarray as nd
    still_referenced = set(qsym.list_arguments()) \
        | set(qsym.list_auxiliary_states())
    qarg_params = {k: nd.array(v) for k, v in arg_np.items()
                   if k in still_referenced}
    qarg_params.update({k: nd.array(v) for k, v in qargs.items()})
    aux_out = {k: v for k, v in dict(aux_params).items()
               if k in still_referenced}
    return qsym, qarg_params, aux_out


def quantize_net(network, calib_data=None, calib_mode="none",
                 data_shapes=None, excluded_sym_names=(),
                 num_calib_examples=None):
    """Gluon route: HybridBlock -> int8 SymbolBlock
    (ref: quantization.py quantize_net). ``data_shapes`` is required when
    ``calib_data`` is None (to trace the network)."""
    import tempfile

    from .. import ndarray as nd
    from .. import symbol as sym_mod
    from ..gluon import SymbolBlock
    from ..model import load_checkpoint

    if calib_data is not None:
        first = calib_data[0] if isinstance(calib_data, (list, tuple)) \
            else calib_data
        example = first if not isinstance(first, (list, tuple)) else \
            first[0]
        x = nd.array(example)
    elif data_shapes:
        x = nd.zeros(data_shapes[0])
    else:
        raise MXNetError("quantize_net needs calib_data or data_shapes")
    network.hybridize()
    network(x)
    with tempfile.TemporaryDirectory() as td:
        prefix = f"{td}/net"
        network.export(prefix)
        sym, arg_params, aux_params = load_checkpoint(prefix, 0)
    batches = None
    if calib_data is not None:
        batches = calib_data if isinstance(calib_data, (list, tuple)) \
            else [calib_data]
    data_name = [n for n in sym.list_arguments()
                 if n not in arg_params
                 and n not in sym.list_auxiliary_states()]
    qsym, qarg, qaux = quantize_model(
        sym, arg_params, aux_params, data_names=data_name,
        excluded_sym_names=excluded_sym_names, calib_mode=calib_mode,
        calib_data=batches, num_calib_examples=num_calib_examples)
    inputs = [sym_mod.var(n) for n in data_name]
    net = SymbolBlock(qsym, inputs)
    params = net.collect_params()
    from ..context import current_context
    ctx = current_context()
    for name, arr in list(qarg.items()) + list(qaux.items()):
        if name in params:
            # int8 weights / fp32 scales must keep their dtype — the
            # SymbolBlock default (fp32) would silently turn the int8
            # GEMM into an fp32 one
            params[name].dtype = arr.asnumpy().dtype \
                if hasattr(arr, "asnumpy") else np.asarray(arr).dtype
            params[name]._load_init(arr, ctx)
    return net

"""Autograd: define-by-run automatic differentiation.

API-compatible with the reference's ``mxnet.autograd`` (ref:
python/mxnet/autograd.py — record/pause/train_mode/predict_mode/backward/grad,
backed by Imperative::RecordOp / Imperative::Backward in
src/imperative/imperative.cc). The TPU-native mechanism is different and
simpler: while recording, every dispatched op runs through ``jax.vjp``, whose
returned pullback is stored on a tape node; ``backward()`` walks the tape in
reverse topological order pushing cotangents through the stored pullbacks.
XLA still sees whole fused programs when models are hybridized, because a
hybridized block records ONE tape node for its entire jitted forward.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax
import numpy as _np

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "get_symbol"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


class _RecordingScope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec = recording
        self._train = training

    def __enter__(self):
        st = _st()
        self._old = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *exc):
        st = _st()
        st.recording, st.training = self._old


def record(train_mode: bool = True):
    """Scope that turns on recording (and, by default, training mode)."""
    return _RecordingScope(True, train_mode)


def pause(train_mode: bool = False):
    """Scope that turns off recording (ref: autograd.pause)."""
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


# ---------------------------------------------------------------------------
# tape
# ---------------------------------------------------------------------------
class TapeNode:
    """One recorded op: holds the jax.vjp pullback and the graph wiring.

    For higher-order gradients the node can also carry the forward recipe
    (``fwd_fn``/``fwd_kwargs``/``fwd_inputs``): ``create_graph`` backward
    re-derives the pullback from it under recording, so grad-of-grad sees
    the full dependence on the primals (the stored ``vjp_fn`` closure holds
    them as constants and is only used by the fast first-order path)."""
    __slots__ = ("vjp_fn", "parents", "out_avals", "n_outputs", "grad_buffers",
                 "pending", "fwd_fn", "fwd_kwargs", "fwd_inputs",
                 "__weakref__")

    def __init__(self, vjp_fn, parents, out_avals, fwd_fn=None,
                 fwd_kwargs=None, fwd_inputs=None):
        self.vjp_fn = vjp_fn
        # parents[i] corresponds to the i-th primal input of the vjp:
        # each entry is (TapeNode | None, out_index, leaf_NDArray | None)
        self.parents = parents
        self.out_avals = out_avals      # list of jax.ShapeDtypeStruct
        self.n_outputs = len(out_avals)
        self.fwd_fn = fwd_fn
        self.fwd_kwargs = fwd_kwargs or {}
        self.fwd_inputs = fwd_inputs    # list of NDArray | jax.Array


def _zeros_for(aval):
    import jax.numpy as jnp
    if jax.dtypes.issubdtype(aval.dtype, jax.numpy.inexact):
        return jnp.zeros(aval.shape, aval.dtype)
    # integer/bool outputs get symbolic-zero cotangents
    return _np.zeros(aval.shape, dtype=jax.dtypes.float0)


def mark_variables(variables, gradients, grad_reqs="write"):
    """ref: autograd.mark_variables — attach grad buffers to leaves."""
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._grad = g
        var._grad_req = req
        var._tape_node = None          # marking detaches from any prior graph
        var._tape_out_idx = 0


def _toposort(roots: List[TapeNode]):
    order = []
    seen = set()
    stack = [(r, False) for r in roots]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for parent, _idx, _leaf in node.parents:
            if parent is not None and id(parent) not in seen:
                stack.append((parent, False))
    return order  # children appear after parents; reverse for backward


def backward(heads, head_grads=None, retain_graph=False, train_mode=True,
             _leaf_filter=None):
    """Compute gradients of `heads` w.r.t. all marked leaves
    (ref: MXAutogradBackwardEx -> Imperative::Backward).

    ``_leaf_filter``: internal — a set of leaf ids to restrict deposits to
    (used by :func:`grad` so it never touches other arrays' ``.grad``)."""
    import jax.numpy as jnp
    from .ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)

    # seed cotangents per tape node; leaf grads accumulate here during the
    # pass and are deposited once at the end (grad_req governs cross-pass
    # behavior, matching the reference)
    cotangents = {}   # id(node) -> list per output
    leaf_accum = {}   # id(leaf NDArray) -> (leaf, accumulated grad)
    roots = []
    for h, hg in zip(heads, head_grads):
        node = getattr(h, "_tape_node", None)
        if node is None:
            if getattr(h, "_grad", None) is not None:
                g = jnp.ones(h.shape, h._data.dtype) if hg is None else hg._data
                _accum_leaf(leaf_accum, h, g)
            continue
        roots.append(node)
        ct = cotangents.setdefault(
            id(node), [_zeros_for(a) for a in node.out_avals])
        seed = jnp.ones(h.shape, h._data.dtype) if hg is None else hg._data
        idx = h._tape_out_idx
        if isinstance(ct[idx], _np.ndarray) and ct[idx].dtype == jax.dtypes.float0:
            pass  # non-differentiable head: nothing to do
        else:
            ct[idx] = ct[idx] + seed
    if not roots:
        if not any(getattr(h, "_grad", None) is not None for h in heads):
            raise MXNetError("backward: no recorded graph reaches these heads "
                             "(did you call attach_grad() and compute inside "
                             "autograd.record()?)")
        return

    order = _toposort(roots)
    for node in reversed(order):
        ct = cotangents.get(id(node))
        if ct is None:
            continue
        if node.vjp_fn is None:
            raise MXNetError("backward: graph was already freed by a previous "
                             "backward pass; pass retain_graph=True to keep it")
        ct_arg = tuple(ct) if node.n_outputs > 1 else ct[0]
        in_cts = node.vjp_fn(ct_arg)
        for (parent, out_idx, leaf), g in zip(node.parents, in_cts):
            if isinstance(g, _np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            if leaf is not None:
                if _leaf_filter is None or id(leaf) in _leaf_filter:
                    _accum_leaf(leaf_accum, leaf, g)
            elif parent is not None:
                pct = cotangents.setdefault(
                    id(parent), [_zeros_for(a) for a in parent.out_avals])
                prev = pct[out_idx]
                if isinstance(prev, _np.ndarray) and prev.dtype == jax.dtypes.float0:
                    continue
                from .ndarray.sparse import _RowSparseCT
                if isinstance(g, _RowSparseCT):
                    g = g.todense()   # sparse stays sparse only to leaves
                pct[out_idx] = prev + g
        if not retain_graph:
            cotangents.pop(id(node), None)

    if not retain_graph:
        # free the recorded graph (ref: Imperative::Backward releases the
        # tape unless retain_graph): drop pullback closures so forward
        # residuals/activations aren't pinned by retained outputs
        for node in order:
            node.vjp_fn = None
            node.parents = []

    for leaf, g in leaf_accum.values():
        _deposit_leaf(leaf, g)


def _accum_leaf(leaf_accum, leaf, g):
    from .ndarray.sparse import _RowSparseCT
    key = id(leaf)
    if key not in leaf_accum:
        leaf_accum[key] = (leaf, g)
        return
    prev = leaf_accum[key][1]
    if isinstance(prev, _RowSparseCT) and isinstance(g, _RowSparseCT):
        import jax.numpy as jnp
        merged = _RowSparseCT(jnp.concatenate([prev.rows, g.rows]),
                              jnp.concatenate([prev.values, g.values]),
                              prev.shape)
        leaf_accum[key] = (leaf, merged)
    elif isinstance(prev, _RowSparseCT) or isinstance(g, _RowSparseCT):
        dense_p = prev.todense() if isinstance(prev, _RowSparseCT) else prev
        dense_g = g.todense() if isinstance(g, _RowSparseCT) else g
        leaf_accum[key] = (leaf, dense_p + dense_g)
    else:
        leaf_accum[key] = (leaf, prev + g)


def _deposit_leaf(leaf, g):
    from .ndarray.sparse import _RowSparseCT, dedupe_rows
    req = getattr(leaf, "_grad_req", "write")
    if req == "null" or leaf._grad is None:
        return
    if isinstance(g, _RowSparseCT):
        rs = dedupe_rows(g)
        if req == "add":
            prev = getattr(leaf._grad, "_sparse", None)
            if prev is not None:
                import numpy as np
                merged = _RowSparseCT(
                    np.concatenate([prev.indices, rs.indices]),
                    np.concatenate([prev.data, rs.data]), rs.shape)
                rs = dedupe_rows(merged)
            elif not getattr(leaf._grad, "_zeroed", False):
                # dense buffer holds prior dense grads; fold them in
                rs = None
        if rs is not None:
            leaf._grad._sparse = rs
            leaf._grad._sparse_used = False
            leaf._grad._zeroed = False
            return
        g = g.todense()
    prev_rs = getattr(leaf._grad, "_sparse", None)
    if prev_rs is not None and req == "add":
        # a dense add-deposit must fold the retained sparse view in (the
        # dense buffer under it is still zeros), not discard it
        import jax.numpy as jnp
        g = g + jnp.asarray(prev_rs.asnumpy(), dtype=g.dtype)
    leaf._grad._sparse = None      # dense deposit invalidates sparse view
    leaf._grad._zeroed = False
    g = g.astype(leaf._grad._data.dtype)
    if req == "add":
        leaf._grad._rebind(leaf._grad._data + g)
    else:
        leaf._grad._rebind(g)


def _replay_vjp(node, ct_nds):
    """Recompute the node's pullback from the forward recipe with BOTH
    primals and cotangents as recorded inputs — the create_graph backward
    step (differentiating through jax.vjp is jax-native)."""
    from .numpy import _call
    from .ndarray import NDArray
    fn, kwargs = node.fwd_fn, node.fwd_kwargs
    n_in = len(node.fwd_inputs)
    n_out = node.n_outputs

    def replay(*vals):
        xs, cts = vals[:n_in], vals[n_in:]
        _, vjp = jax.vjp(lambda *a: fn(*a, **kwargs), *xs)
        res = tuple(vjp(tuple(cts) if n_out > 1 else cts[0]))
        return res[0] if len(res) == 1 else res

    out = _call(replay, *node.fwd_inputs, *ct_nds)
    return out if isinstance(out, tuple) else (out,)


def _backward_create_graph(heads, head_grads, leaf_filter):
    """Tape walk with NDArray cotangents under recording → leaf grads that
    are themselves differentiable (ref: Imperative::Backward with
    create_graph=True)."""
    from .ndarray import NDArray

    cotangents = {}
    leaf_accum = {}
    roots = []
    for h, hg in zip(heads, head_grads):
        node = getattr(h, "_tape_node", None)
        seed = hg if hg is not None else \
            NDArray(jax.numpy.ones(h.shape, h._data.dtype),
                    _skip_device_put=True)
        if node is None:
            if getattr(h, "_grad", None) is not None:
                _accum_leaf(leaf_accum, h, seed)
            continue
        roots.append(node)
        ct = cotangents.setdefault(
            id(node), [None] * node.n_outputs)
        idx = h._tape_out_idx
        ct[idx] = seed if ct[idx] is None else ct[idx] + seed
    if not roots and not leaf_accum:
        raise MXNetError("backward: no recorded graph reaches these heads")

    order = _toposort(roots)
    for node in reversed(order):
        ct = cotangents.get(id(node))
        if ct is None:
            continue
        if node.fwd_fn is None:
            raise MXNetError(
                "create_graph backward needs the forward recipe on every "
                "tape node; this graph contains a node recorded without "
                "one (custom Function?)")
        ct_full = [c if c is not None else
                   NDArray(jax.numpy.zeros(a.shape, a.dtype),
                           _skip_device_put=True)
                   for c, a in zip(ct, node.out_avals)]
        in_cts = _replay_vjp(node, ct_full)
        for (parent, out_idx, leaf), g in zip(node.parents, in_cts):
            if not isinstance(g, NDArray):
                continue
            if leaf is not None:
                if leaf_filter is None or id(leaf) in leaf_filter:
                    _accum_leaf(leaf_accum, leaf, g)
            elif parent is not None:
                pct = cotangents.setdefault(
                    id(parent), [None] * parent.n_outputs)
                pct[out_idx] = g if pct[out_idx] is None else \
                    pct[out_idx] + g
    return leaf_accum


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """ref: autograd.grad — returns grads instead of writing .grad.
    ``create_graph=True`` returns differentiable gradients (higher-order
    autograd via pullback replay)."""
    from .ndarray import NDArray
    if create_graph:
        if isinstance(heads, NDArray):
            heads = [heads]
        if head_grads is None:
            head_grads = [None] * len(heads)
        elif not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
        single = isinstance(variables, NDArray)
        var_list = [variables] if single else list(variables)
        with record(train_mode):
            leaf_accum = _backward_create_graph(
                heads, head_grads, {id(v) for v in var_list})
        out = []
        for v in var_list:
            if id(v) in leaf_accum:
                out.append(leaf_accum[id(v)][1])
            else:
                out.append(NDArray(jax.numpy.zeros(v.shape, v._data.dtype),
                                   _skip_device_put=True))
        return out[0] if single else out
    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    saved = [(getattr(v, "_grad", None), getattr(v, "_grad_req", "write"))
             for v in variables]
    from .ndarray import zeros
    for v in variables:
        v._grad = zeros(v.shape, dtype=v.dtype, ctx=v.ctx)
        v._grad_req = "add"
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph),
                 _leaf_filter={id(v) for v in variables})
        out = [v._grad for v in variables]
    finally:
        for v, (g, r) in zip(variables, saved):
            v._grad, v._grad_req = g, r
    return out[0] if single else out


def get_symbol(x):
    raise MXNetError("autograd.get_symbol is not supported: the TPU build "
                     "records jax pullbacks, not NNVM nodes; use "
                     "HybridBlock.export for graph capture")

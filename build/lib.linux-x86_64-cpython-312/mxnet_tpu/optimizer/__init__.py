"""Optimizers (ref: python/mxnet/optimizer/optimizer.py)."""
from .optimizer import (SGD, NAG, Adam, AdamW, LAMB, RMSProp, AdaGrad, FTRL,
                        Signum, SGLD, Optimizer, Updater, create, register,
                        get_updater)

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "LAMB", "RMSProp",
           "AdaGrad", "FTRL", "Signum", "SGLD", "Updater", "create",
           "register", "get_updater"]

"""Optimizer classes (ref: python/mxnet/optimizer/optimizer.py).

Same design as the reference: an ``Optimizer`` holds hyperparameters +
per-weight state and calls the *fused update ops* (here
``mxnet_tpu/ops/optimizer_op.py``, jit-fused by XLA with donated buffers);
an ``Updater`` wraps it with a state dict keyed by weight index — the same
object the reference serializes to KVStore servers.

Multi-precision: like the reference's ``mp_*`` path, low-precision weights
(bf16/fp16) automatically keep an fp32 master copy in the state.
"""
from __future__ import annotations

import pickle

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "LAMB", "RMSProp",
           "AdaGrad", "FTRL", "Signum", "SGLD", "AdaDelta", "Nadam",
           "DCASGD", "FTML", "Updater", "create", "register",
           "get_updater"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    name = name.lower()
    if name not in _REGISTRY:
        raise MXNetError(f"unknown optimizer {name!r}; "
                         f"known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


class Optimizer:
    """ref: optimizer.py Optimizer — lr/wd multipliers per param, update
    counting for schedulers, state creation per weight."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 multi_precision=False, param_dict=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    # -- state --------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype != np.float32:
            master = weight.astype(np.float32)
            return (self.create_state(index, master), master)
        return self.create_state(index, weight)

    # -- bookkeeping ---------------------------------------------------------
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = (self.lr_scheduler(self.num_update) if self.lr_scheduler
              else self.lr)
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("cannot set lr directly when lr_scheduler is set")
        self.lr = lr

    @property
    def learning_rate(self):
        return (self.lr_scheduler(self.num_update) if self.lr_scheduler
                else self.lr)

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _common(self, index):
        return dict(lr=self._get_lr(index), wd=self._get_wd(index),
                    rescale_grad=self.rescale_grad,
                    clip_gradient=self.clip_gradient
                    if self.clip_gradient is not None else -1.0)

    # -- update --------------------------------------------------------------
    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_row_sparse(self, index, weight, rs_grad, state):
        """Apply this optimizer's own rule to ONLY the touched rows of a
        RowSparseNDArray gradient (the reference's lazy_update sparse
        semantics, ref: optimizer.py sgd/adam sparse paths +
        src/operator/optimizer_op.cc *_update row_sparse kernels):
        weight rows and state rows are gathered, the dense rule runs on
        the gathered slab, and results scatter back — untouched rows see
        no weight decay and no momentum decay."""
        from .. import ndarray as nd
        rows = np.asarray(rs_grad.indices)
        w_rows = nd.NDArray(weight._data[rows], _skip_device_put=True)
        g_rows = nd.NDArray(np.asarray(rs_grad.data), ctx=weight.ctx)

        def gather(s):
            if s is None:
                return None
            if isinstance(s, (tuple, list)):
                return tuple(gather(x) for x in s)
            return nd.NDArray(s._data[rows], _skip_device_put=True)

        def scatter(dst, src):
            if dst is None:
                return
            if isinstance(dst, (tuple, list)):
                for d, s in zip(dst, src):
                    scatter(d, s)
                return
            dst._rebind(dst._data.at[rows].set(src._data))

        state_rows = gather(state)
        self.update(index, w_rows, g_rows, state_rows)
        weight._rebind(weight._data.at[rows].set(w_rows._data))
        scatter(state, state_rows)

    def update_multi_precision(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            if self.multi_precision and weight.dtype != np.float32:
                inner_state, master = state
                rs32 = RowSparseNDArray(
                    np.asarray(grad.data, np.float32), grad.indices,
                    grad.shape, dtype=np.float32)
                self.update_row_sparse(index, master, rs32, inner_state)
                # write back only the touched rows — a full-table
                # master.astype() every step would erase the sparse win
                rows = np.asarray(grad.indices)
                weight._rebind(weight._data.at[rows].set(
                    master._data[rows].astype(weight.dtype)))
            else:
                self.update_row_sparse(index, weight, grad, state)
            return
        if self.multi_precision and weight.dtype != np.float32:
            inner_state, master = state
            grad32 = grad.astype(np.float32)
            self.update(index, master, grad32, inner_state)
            weight._rebind(master.astype(weight.dtype)._data)
        else:
            self.update(index, weight, grad, state)


@register
class SGD(Optimizer):
    """SGD with momentum (ref: optimizer.py SGD -> sgd_update/sgd_mom_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **kw)
        else:
            w, m = nd.sgd_mom_update(weight, grad, state,
                                     momentum=self.momentum, **kw)
            weight._rebind(w._data)
            state._rebind(m._data)


@register
class NAG(Optimizer):
    """Nesterov SGD (ref: optimizer.py NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **kw)
        else:
            w, m = nd.nag_mom_update(weight, grad, state,
                                     momentum=self.momentum, **kw)
            weight._rebind(w._data)
            state._rebind(m._data)


@register
class Adam(Optimizer):
    """Adam with the reference's bias-correction-in-lr formulation
    (ref: optimizer.py Adam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        kw["lr"] *= np.sqrt(coef2) / coef1
        mean, var = state
        w, m, v = nd.adam_update(weight, grad, mean, var, beta1=self.beta1,
                                 beta2=self.beta2, epsilon=self.epsilon, **kw)
        weight._rebind(w._data)
        mean._rebind(m._data)
        var._rebind(v._data)


@register
class AdamW(Adam):
    """Decoupled weight decay Adam (ref: contrib adamw)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        t = self._index_update_count[index]
        kw["lr"] *= np.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        mean, var = state
        w, m, v = nd.adamw_update(weight, grad, mean, var, beta1=self.beta1,
                                  beta2=self.beta2, epsilon=self.epsilon, **kw)
        weight._rebind(w._data)
        mean._rebind(m._data)
        var._rebind(v._data)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (ref: optimizer.py LAMB)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        t = self._index_update_count[index]
        mean, var = state
        g, m, v = nd.lamb_update_phase1(
            weight, grad, mean, var, beta1=self.beta1, beta2=self.beta2,
            epsilon=self.epsilon, t=t, bias_correction=self.bias_correction,
            wd=kw["wd"], rescale_grad=kw["rescale_grad"],
            clip_gradient=kw["clip_gradient"])
        r1 = nd.norm(weight)
        r2 = nd.norm(g)
        w = nd.lamb_update_phase2(
            weight, g, r1, r2, lr=kw["lr"],
            lower_bound=self.lower_bound if self.lower_bound else -1.0,
            upper_bound=self.upper_bound if self.upper_bound else -1.0)
        weight._rebind(w._data)
        mean._rebind(m._data)
        var._rebind(v._data)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2, self.epsilon = gamma1, gamma2, epsilon
        self.centered = centered

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        w, n = nd.rmsprop_update(weight, grad, state, gamma1=self.gamma1,
                                 epsilon=self.epsilon, **kw)
        weight._rebind(w._data)
        state._rebind(n._data)


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=0.01, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        w, h = nd.adagrad_update(weight, grad, state,
                                 epsilon=self.float_stable_eps, **kw)
        weight._rebind(w._data)
        state._rebind(h._data)


@register
class FTRL(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        z, n = state
        w, z2, n2 = nd.ftrl_update(weight, grad, z, n, lamda1=self.lamda1,
                                   beta=self.beta, **kw)
        weight._rebind(w._data)
        z._rebind(z2._data)
        n._rebind(n2._data)


@register
class Signum(Optimizer):
    """signSGD with momentum (ref: optimizer.py Signum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        if state is None:
            nd.signsgd_update(weight, grad, out=weight, **kw)
        else:
            # momentum variant: m = beta*m - (1-beta)*grad; w += lr*sign(m)
            g = grad * self.rescale_grad
            if kw["clip_gradient"] > 0:
                g = nd.clip(g, -kw["clip_gradient"], kw["clip_gradient"])
            state._rebind((state * self.momentum - g * (1 - self.momentum))._data)
            weight._rebind((weight * (1 - kw["lr"] * self.wd_lh)
                            + nd.sign(state) * kw["lr"])._data)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (ref: optimizer.py SGLD)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        g = grad * self.rescale_grad
        if kw["clip_gradient"] > 0:
            g = nd.clip(g, -kw["clip_gradient"], kw["clip_gradient"])
        noise = nd.random.normal(0, np.sqrt(kw["lr"]), shape=weight.shape,
                                 ctx=weight.ctx)
        weight._rebind((weight - kw["lr"] / 2 * (g + kw["wd"] * weight)
                        + noise)._data)


class Updater:
    """State-dict wrapper used by KVStore servers and Module
    (ref: optimizer.py Updater / get_updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        states_np = {}
        for k, s in self.states.items():
            states_np[k] = _state_to_np(s)
        payload = (states_np, self.optimizer) if dump_optimizer else states_np
        return pickle.dumps(payload)

    def set_states(self, states):
        payload = pickle.loads(states)
        if isinstance(payload, tuple):
            states_np, self.optimizer = payload
        else:
            states_np = payload
        self.states = {k: _state_from_np(v) for k, v in states_np.items()}


def _state_to_np(s):
    if s is None:
        return None
    if isinstance(s, (tuple, list)):
        return tuple(_state_to_np(x) for x in s)
    return s.asnumpy()


def _state_from_np(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_state_from_np(x) for x in s)
    return nd.array(s)


def get_updater(optimizer):
    return Updater(optimizer)


@register
class AdaDelta(Optimizer):
    """ref: optimizer.py AdaDelta (no learning rate in the update)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight
        acc_g, acc_delta = state
        acc_g_new = self.rho * acc_g + (1.0 - self.rho) * g * g
        delta = nd.sqrt(acc_delta + self.epsilon) / \
            nd.sqrt(acc_g_new + self.epsilon) * g
        acc_delta_new = self.rho * acc_delta + (1.0 - self.rho) * delta * delta
        acc_g._rebind(acc_g_new._data)
        acc_delta._rebind(acc_delta_new._data)
        weight._rebind((weight - delta)._data)


@register
class Nadam(Optimizer):
    """Adam with Nesterov momentum schedule (ref: optimizer.py Nadam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (
            t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** (
            (t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        mean, var = state
        m_new = self.beta1 * mean + (1.0 - self.beta1) * g
        v_new = self.beta2 * var + (1.0 - self.beta2) * g * g
        g_prime = g / (1.0 - self.m_schedule)
        m_prime = m_new / (1.0 - m_schedule_next)
        v_prime = v_new / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_prime
        mean._rebind(m_new._data)
        var._rebind(v_new._data)
        weight._rebind((weight - lr * m_bar /
                        (nd.sqrt(v_prime) + self.epsilon))._data)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref: optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = None
        if self.momentum != 0.0:
            mom = nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.ctx)
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        mom, prev = state
        comp = g + wd * weight + self.lamda * g * g * (weight - prev)
        if mom is None:
            step = -lr * comp
        else:
            mom._rebind((self.momentum * mom - lr * comp)._data)
            step = mom
        prev._rebind(weight._data)
        weight._rebind((weight + step)._data)


@register
class FTML(Optimizer):
    """Follow the Moving Leader (ref: optimizer.py FTML / ftml_update)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return tuple(nd.zeros(weight.shape, dtype=weight.dtype,
                              ctx=weight.ctx) for _ in range(3))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight
        d, v, z = state
        v_new = self.beta2 * v + (1.0 - self.beta2) * g * g
        d_new = (1.0 - self.beta1 ** t) / lr * (
            nd.sqrt(v_new / (1.0 - self.beta2 ** t)) + self.epsilon)
        sigma = d_new - self.beta1 * d
        z_new = self.beta1 * z + (1.0 - self.beta1) * g - sigma * weight
        v._rebind(v_new._data)
        d._rebind(d_new._data)
        z._rebind(z_new._data)
        weight._rebind((-z_new / d_new)._data)

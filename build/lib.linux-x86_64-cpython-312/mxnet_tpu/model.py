"""``mx.model`` — checkpoint helpers (ref: python/mxnet/model.py).

Format parity: ``prefix-symbol.json`` (graph) + ``prefix-%04d.params``
(NDArray dict with arg:/aux: prefixes), the same pair every reference-era
deployment pipeline consumes (SURVEY §5.4).
"""
from __future__ import annotations

from . import ndarray as nd
from .base import MXNetError

__all__ = ["save_checkpoint", "load_checkpoint", "load_params"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """ref: model.py save_checkpoint."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch):
    """ref: model.py load_params → (arg_params, aux_params)."""
    loaded = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        kind, _, name = k.partition(":")
        if kind == "arg":
            arg_params[name] = v
        elif kind == "aux":
            aux_params[name] = v
        else:
            raise MXNetError(f"invalid param key {k!r} (want arg:/aux:)")
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """ref: model.py load_checkpoint → (symbol, arg_params, aux_params)."""
    from . import symbol as sym_mod
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params

"""``mx.AttrScope`` (ref: python/mxnet/attribute.py): scoped attributes
attached to symbols created inside the scope — the reference's mechanism
behind ``ctx_group`` model-parallel placement hints and custom attrs."""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]

_state = threading.local()


class AttrScope:
    def __init__(self, **kwargs):
        self._attr = {k: str(v) for k, v in kwargs.items()}
        self._old = None

    def get(self, attr=None):
        """Compose current-scope attrs with the given ones."""
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        self._old = getattr(_state, "current", None)
        base = dict(self._old._attr) if self._old else {}
        base.update(self._attr)
        merged = AttrScope()
        merged._attr = base
        _state.current = merged
        return self

    def __exit__(self, *exc):
        _state.current = self._old


def current() -> AttrScope:
    cur = getattr(_state, "current", None)
    return cur if cur is not None else AttrScope()

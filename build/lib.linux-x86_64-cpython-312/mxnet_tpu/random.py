"""``mx.random`` — top-level random API (ref: python/mxnet/random.py).

Forwards to the generated ``nd.random`` namespace; ``seed`` reseeds the
global eager PRNG (stateless JAX keys under the hood, see _rng.py).
"""
from __future__ import annotations

from ._rng import seed as _seed_jax
from .ndarray import random as _ndrandom


def seed(seed_state):
    """ref: mx.random.seed — seeds every generator the framework draws
    from: the JAX key chain (nd.random ops) AND the numpy global RNG
    (weight initializers sample through numpy on the host, matching the
    reference where MXRandomSeed seeds all engines)."""
    import numpy as _np
    _seed_jax(seed_state)
    _np.random.seed(int(seed_state) % (2 ** 32))

uniform = _ndrandom.uniform
normal = _ndrandom.normal


def randn(*shape, loc=0.0, scale=1.0, **kwargs):
    """ref: mx.nd.random.randn(*shape) — positional args are the shape."""
    return _ndrandom.normal(loc=loc, scale=scale, shape=shape or (1,), **kwargs)
gamma = _ndrandom.gamma
exponential = _ndrandom.exponential
poisson = _ndrandom.poisson
randint = _ndrandom.randint
multinomial = _ndrandom.multinomial
shuffle = _ndrandom.shuffle
bernoulli = _ndrandom.bernoulli

__all__ = ["seed", "uniform", "normal", "randn", "gamma", "exponential",
           "poisson", "randint", "multinomial", "shuffle", "bernoulli"]

"""``mx.name`` — name manager (ref: python/mxnet/name.py NameManager /
Prefix): scoped control over auto-generated symbol names."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]

_state = threading.local()


class NameManager:
    """Assigns unique names per op hint; usable as a with-scope."""

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    def __enter__(self):
        self._old = getattr(_state, "current", None)
        _state.current = self
        return self

    def __exit__(self, *exc):
        _state.current = self._old


class Prefix(NameManager):
    """ref: name.py Prefix — prepends a prefix to every auto name."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


def current() -> NameManager:
    cur = getattr(_state, "current", None)
    if cur is None:
        cur = NameManager()
        _state.current = cur
    return cur

"""Recurrent cells (ref: python/mxnet/gluon/rnn/rnn_cell.py).

Unfused, step-at-a-time cells for custom decoding loops; ``unroll`` builds
the time loop in Python (traced once under hybridize, so XLA still sees a
static graph — the reference's explicit-unroll semantics). The fused layers
in rnn_layer.py are the ``lax.scan`` fast path.
"""
from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


class RecurrentCell(HybridBlock):
    """ref: rnn_cell.py RecurrentCell."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """ref: RecurrentCell.begin_state — zero (or custom) initial states."""
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=shape, **info, **kwargs)
                          if "shape" in func.__code__.co_varnames
                          else func(shape, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """ref: RecurrentCell.unroll."""
        from ... import ndarray as F
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            seq = list(inputs)
            batch = seq[0].shape[0]
        else:
            batch = inputs.shape[layout.find("N")]
            seq = [F.squeeze(s, axis=axis) for s in
                   F.split(inputs, num_outputs=length, axis=axis)]
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        outputs = []
        all_states = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
            all_states.append(states)
        if valid_length is not None:
            stacked = F.stack(*outputs, axis=axis)
            outputs = F.SequenceMask(stacked, sequence_length=valid_length,
                                     use_sequence_length=True, axis=axis)
            # final states: last valid step per sequence
            states = [F.SequenceLast(F.stack(*[s[i] for s in all_states],
                                             axis=0),
                                     sequence_length=valid_length,
                                     use_sequence_length=True, axis=0)
                      for i in range(len(states))]
            if merge_outputs is False:
                outputs = [F.squeeze(s, axis=axis) for s in
                           F.split(outputs, num_outputs=length, axis=axis)]
            return outputs, states
        if merge_outputs is None or merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell):
    pass


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell (ref: rnn_cell.py RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight._set_shape((self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """ref: rnn_cell.py LSTMCell — gates in i,f,g,o order."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight._set_shape((4 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        in_gate, forget_gate, in_trans, out_gate = F.split(
            gates, num_outputs=4, axis=-1)
        in_gate = F.Activation(in_gate, act_type="sigmoid")
        forget_gate = F.Activation(forget_gate, act_type="sigmoid")
        in_trans = F.Activation(in_trans, act_type="tanh")
        out_gate = F.Activation(out_gate, act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """ref: rnn_cell.py GRUCell — r,z,n gate order (cuDNN layout)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight._set_shape((3 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=-1)
        reset = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        trans = F.Activation(i2h_n + reset * h2h_n, act_type="tanh")
        next_h = (1 - update) * trans + update * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells applied in order per step (ref: SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, inputs, states):
        self._counter += 1
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            cell_states = states[pos:pos + n]
            pos += n
            inputs, new_states = cell(inputs, cell_states)
            next_states.extend(new_states)
        return inputs, next_states


class DropoutCell(HybridRecurrentCell):
    """ref: rnn_cell.py DropoutCell."""

    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._dropout = nn.Dropout(rate)

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, inputs, states):
        return self._dropout(inputs), states


class ZoneoutCell(HybridRecurrentCell):
    """Zoneout regularization wrapper (ref: rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def reset(self):
        super().reset()
        self._prev_output = None

    def forward(self, inputs, states):
        from ... import autograd
        from ... import ndarray as F
        out, next_states = self.base_cell(inputs, states)
        if autograd.is_training():
            def mask(rate, new, old):
                keep = F.random.bernoulli(1 - rate, shape=new.shape,
                                          ctx=new.ctx, dtype=new.dtype)
                return keep * new + (1 - keep) * old
            prev = self._prev_output
            if prev is None:
                prev = F.zeros(out.shape, ctx=out.ctx, dtype=out.dtype)
            if self._zo:
                out = mask(self._zo, out, prev)
            if self._zs:
                next_states = [mask(self._zs, ns, s)
                               for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class ResidualCell(HybridRecurrentCell):
    """Adds the input to the cell output (ref: rnn_cell.py ResidualCell)."""

    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Forward + backward cells over a full sequence; only usable through
    ``unroll`` (ref: rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return (self.l_cell.state_info(batch_size)
                + self.r_cell.state_info(batch_size))

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped — use unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            seq = [F.squeeze(s, axis=axis) for s in
                   F.split(inputs, num_outputs=length, axis=axis)]
        else:
            seq = list(inputs)
        batch = seq[0].shape[0]
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        n_l = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(
            length, seq, states[:n_l], layout="NTC" if axis else "TNC",
            merge_outputs=False, valid_length=valid_length)
        r_out, r_states = self.r_cell.unroll(
            length, list(reversed(seq)), states[n_l:],
            layout="NTC" if axis else "TNC", merge_outputs=False,
            valid_length=None if valid_length is None else valid_length)
        r_out = list(reversed(r_out))
        outputs = [F.concat(l, r, dim=-1) for l, r in zip(l_out, r_out)]
        if merge_outputs is None or merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, l_states + r_states

"""Gluon utilities (ref: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split one batch along ``batch_axis`` into ``num_slice`` pieces
    (ref: gluon/utils.py split_data). On TPU, prefer a sharded batch on a
    Mesh (mxnet_tpu.parallel) over per-device slices — this exists for
    script compatibility."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"batch size {size} not divisible by {num_slice} slices; pass "
            f"even_split=False")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(nd.slice_axis(data, axis=batch_axis, begin=begin,
                                    end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch and load each slice onto one context
    (ref: gluon/utils.py split_and_load)."""
    if not isinstance(data, nd.NDArray):
        data = nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [piece.as_in_context(ctx) for piece, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so their joint L2 norm ≤ max_norm
    (ref: gluon/utils.py clip_global_norm)."""
    from ..ndarray.sparse import RowSparseNDArray
    if not arrays:
        raise MXNetError("clip_global_norm: empty array list")
    total = 0.0
    for arr in arrays:
        if isinstance(arr, RowSparseNDArray):
            # row-sparse grads: only stored rows contribute (ref:
            # gluon/utils.py supports row_sparse grad clipping)
            total += float(np.sum(np.square(arr.data)))
        else:
            total += float(nd.sum(nd.square(arr.reshape(-1))).asscalar())
    norm = float(np.sqrt(total))
    if check_isfinite and not np.isfinite(norm):
        return norm
    scale = max_norm / (norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            if isinstance(arr, RowSparseNDArray):
                arr.data = arr.data * np.asarray(scale, arr.data.dtype)
            else:
                arr *= scale
    return norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise MXNetError("download() requires network access, which this "
                     "environment does not provide; place files locally and "
                     "load them directly")

"""Gluon — the imperative/hybrid high-level API
(ref: python/mxnet/gluon/)."""
from . import contrib, data, loss, model_zoo, nn, rnn, utils
from .block import Block, HybridBlock, SymbolBlock
from .parameter import Constant, Parameter, ParameterDict
from .trainer import Trainer

__all__ = ["nn", "loss", "utils", "data", "rnn", "model_zoo", "Block",
           "HybridBlock", "SymbolBlock", "Parameter", "ParameterDict",
           "Constant", "Trainer"]

"""Vision data (ref: python/mxnet/gluon/data/vision/__init__.py)."""
from . import transforms
from .datasets import *     # noqa: F401,F403

"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py).

Transforms are Blocks (same as the reference) so they compose into
``Compose`` chains and run on host numpy/jnp before batching.
"""
from __future__ import annotations

import numpy as np

from .... import ndarray as nd
from ...block import Block, HybridBlock
from ...nn import Sequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "CropResize"]


class Compose(Sequential):
    """ref: transforms.py Compose — chain of transforms."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1] (ref: ToTensor)."""

    def hybrid_forward(self, F, x):
        x = x.astype("float32") / 255.0
        if x.ndim == 3:
            return F.transpose(x, axes=(2, 0, 1))
        return F.transpose(x, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    """(x - mean) / std per channel on CHW input (ref: Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        mean = np.asarray(self._mean, dtype=np.float32).reshape(-1, 1, 1)
        std = np.asarray(self._std, dtype=np.float32).reshape(-1, 1, 1)
        return (x - nd.array(mean, ctx=x.ctx)) / nd.array(std, ctx=x.ctx)


def _resize_hwc(x, w, h, interp=1):
    import cv2
    arr = x.asnumpy() if isinstance(x, nd.NDArray) else np.asarray(x)
    out = cv2.resize(arr, (w, h), interpolation=interp)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out)


class Resize(Block):
    """Resize HWC image (ref: transforms.py Resize; cv2 backend like the
    reference's src/io/image_aug_default.cc)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        h, w = x.shape[:2]
        if isinstance(self._size, (list, tuple)):
            new_w, new_h = self._size
        elif self._keep:
            short = min(h, w)
            scale = self._size / short
            new_w, new_h = int(round(w * scale)), int(round(h * scale))
        else:
            new_w = new_h = self._size
        return _resize_hwc(x, new_w, new_h, self._interp)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (list, tuple)) else (size, size)
        self._interp = interpolation

    def forward(self, x):
        cw, ch = self._size
        h, w = x.shape[:2]
        if h < ch or w < cw:
            x = _resize_hwc(x, max(cw, w), max(ch, h), self._interp)
            h, w = x.shape[:2]
        y0, x0 = (h - ch) // 2, (w - cw) // 2
        return x[y0:y0 + ch, x0:x0 + cw]


class CropResize(Block):
    def __init__(self, x, y, width, height, interpolation=1):
        super().__init__()
        self._x, self._y, self._w, self._h = x, y, width, height
        self._interp = interpolation

    def forward(self, img):
        out = img[self._y:self._y + self._h, self._x:self._x + self._w]
        return out


class RandomResizedCrop(Block):
    """Random area/aspect crop resized to size (ref: RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (list, tuple)) else (size, size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        h, w = x.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            aspect = np.random.uniform(*self._ratio)
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                x0 = np.random.randint(0, w - cw + 1)
                y0 = np.random.randint(0, h - ch + 1)
                crop = x[y0:y0 + ch, x0:x0 + cw]
                return _resize_hwc(crop, self._size[0], self._size[1],
                                   self._interp)
        return CenterCrop(self._size, self._interp)(x)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return nd.array(x.asnumpy()[:, ::-1].copy())
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return nd.array(x.asnumpy()[::-1].copy())
        return x


class RandomBrightness(Block):
    """ref: transforms.py RandomBrightness — scale by U[max(0,1-b), 1+b]."""

    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        f = np.random.uniform(max(0, 1 - self._b), 1 + self._b)
        return (x.astype("float32") * f)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        f = np.random.uniform(max(0, 1 - self._c), 1 + self._c)
        x = x.astype("float32")
        arr = x.asnumpy()
        gray = arr.mean()
        return nd.array(gray + (arr - gray) * f)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        f = np.random.uniform(max(0, 1 - self._s), 1 + self._s)
        arr = x.astype("float32").asnumpy()
        gray = arr.mean(axis=-1, keepdims=True)
        return nd.array(gray + (arr - gray) * f)


class RandomHue(Block):
    """Approximate hue jitter by channel rotation mixing (the reference
    uses the HSV transform; this keeps the augmentation cheap and
    dependency-free)."""

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        t = np.random.uniform(-self._h, self._h) * np.pi
        arr = x.astype("float32").asnumpy()
        u, w = np.cos(t), np.sin(t)
        m = np.array([[0.299, 0.587, 0.114]] * 3)
        rot = m + u * (np.eye(3) - m) + w * np.array(
            [[0.0, -0.577, 0.577], [0.577, 0.0, -0.577],
             [-0.577, 0.577, 0.0]])
        return nd.array(arr @ rot.T.astype(np.float32))


class RandomColorJitter(Block):
    """ref: transforms.py RandomColorJitter — compose the four jitters in
    random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        order = np.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (ref: transforms.py
    RandomLighting)."""

    _eigval = np.array([55.46, 4.794, 1.148], dtype=np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.814],
                        [-0.5836, -0.6948, 0.4203]], dtype=np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = np.random.normal(0, self._alpha, 3).astype(np.float32)
        noise = (self._eigvec * a * self._eigval).sum(axis=1)
        return x.astype("float32") + nd.array(noise)


__all__ += ["RandomBrightness", "RandomContrast", "RandomSaturation",
            "RandomHue", "RandomColorJitter", "RandomLighting"]

"""gluon.data (ref: python/mxnet/gluon/data/__init__.py)."""
from . import vision
from .dataloader import *   # noqa: F401,F403
from .dataset import *      # noqa: F401,F403
from .sampler import *      # noqa: F401,F403

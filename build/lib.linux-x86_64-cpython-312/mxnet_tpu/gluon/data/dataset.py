"""Datasets (ref: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """ref: dataset.py Dataset."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def shard(self, num_shards, index):
        """Subset for worker ``index`` of ``num_shards`` (ref: shard) —
        larger shards first so lengths differ by at most one."""
        if not 0 <= index < num_shards:
            raise MXNetError(f"shard index {index} out of range")
        n = len(self)
        base = n // num_shards
        extra = n % num_shards
        start = base * index + min(index, extra)
        length = base + (1 if index < extra else 0)
        return SimpleDataset([self[start + i] for i in range(length)])

    def take(self, count):
        count = min(count, len(self))
        return SimpleDataset([self[i] for i in range(count)])

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        def base_fn(x, *args):
            if args:
                return (fn(x),) + args
            return fn(x)
        return self.transform(base_fn, lazy)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class ArrayDataset(Dataset):
    """Zip of equal-length arrays/datasets (ref: ArrayDataset)."""

    def __init__(self, *args):
        if not args:
            raise MXNetError("ArrayDataset needs at least one input")
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            if len(data) != self._length:
                raise MXNetError(f"input {i} has length {len(data)} != "
                                 f"{self._length}")
            self._data.append(data)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Indexed RecordIO-backed dataset of raw bytes (ref:
    RecordFileDataset — the .rec pack is the reference's dataset interchange
    format, kept byte-compatible in mxnet_tpu.recordio)."""

    def __init__(self, filename):
        import os
        self._filename = filename
        idx_file = os.path.splitext(filename)[0] + ".idx"
        from ... import recordio
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)

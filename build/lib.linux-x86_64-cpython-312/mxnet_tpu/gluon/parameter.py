"""Gluon Parameter / ParameterDict.

TPU-native re-design of the reference's parameter container
(ref: python/mxnet/gluon/parameter.py — Parameter, ParameterDict, Constant).
Semantics preserved: deferred shape inference + lazy init, ``grad_req``
write/add/null, per-context replicas (``list_data``/``list_grad``), prefix
scoping, save/load. Differences by design: replicas are only materialised
when multiple contexts are requested — the idiomatic TPU data-parallel path
is a *sharded* parameter on a mesh (see mxnet_tpu.parallel), not N copies.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

import numpy as np

from .. import initializer as _init_mod
from .. import ndarray as nd
from ..base import MXNetError, _as_np_dtype, mx_real_t
from ..context import Context, cpu, current_context

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (nd.NDArray,)


class DeferredInitializationError(MXNetError):
    """Raised when a parameter's data is requested before shape inference."""


class Parameter:
    """A weight/bias/aux tensor of a Block (ref: gluon/parameter.py Parameter).

    Supports deferred initialization: construct with an incomplete shape
    (``None`` or dims of 0); call :meth:`initialize`; the first forward pass
    infers the real shape (``HybridBlock.infer_shape``) and init completes.
    """

    def __init__(self, name, grad_req="write", shape=None, dtype=mx_real_t,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = None
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = _as_np_dtype(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._data: Optional[List[nd.NDArray]] = None
        self._grad: Optional[List[nd.NDArray]] = None
        self._ctx_list: Optional[List[Context]] = None
        self._deferred_init = ()
        self._attrs = {}
        if not differentiable:
            grad_req = "null"
        self.grad_req = grad_req
        if stype != "default":
            raise MXNetError("sparse parameter storage is not supported on "
                             "the TPU build (stype must be 'default'); "
                             "grad_stype='row_sparse' IS supported for "
                             "Embedding-style sparse gradients")
        if grad_stype not in ("default", "row_sparse"):
            raise MXNetError(f"grad_stype {grad_stype!r}: must be "
                             f"'default' or 'row_sparse'")
        self._grad_stype = grad_stype

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self.shape}, "
                f"dtype={np.dtype(self.dtype).name})")

    # -- grad_req -----------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req!r}")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                for arr in self._data:
                    arr._grad = None
                    arr._grad_req = "null"
        elif self._data is not None:
            self._init_grad()

    # -- shape inference ----------------------------------------------------
    def _shape_incomplete(self):
        return self.shape is None or any(s == 0 for s in self.shape)

    def _set_shape(self, new_shape):
        """Called by HybridBlock.infer_shape once input shapes are known."""
        new_shape = tuple(int(s) for s in new_shape)
        if self.shape is not None and not self._shape_incomplete():
            if self.shape != new_shape:
                raise MXNetError(
                    f"inferred shape {new_shape} for {self.name} does not "
                    f"match declared shape {self.shape}")
            return
        if self.shape is not None and len(self.shape) == len(new_shape):
            for declared, inferred in zip(self.shape, new_shape):
                if declared != 0 and declared != inferred:
                    raise MXNetError(
                        f"inferred shape {new_shape} for {self.name} clashes "
                        f"with declared {self.shape}")
        self.shape = new_shape

    # -- init ---------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """ref: Parameter.initialize — allocate and fill on ctx."""
        if self._data is not None and not force_reinit:
            return
        if default_init is None:
            default_init = _init_mod.Uniform()
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if self._shape_incomplete():
            if not self.allow_deferred_init:
                raise MXNetError(
                    f"cannot initialize {self.name}: shape {self.shape} is "
                    f"incomplete and allow_deferred_init=False")
            self._deferred_init = (init, default_init)
            return
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        initializer = self.init if self.init is not None else init
        if initializer is None:
            initializer = default_init
        if isinstance(initializer, str):
            initializer = _init_mod.create(initializer)
        desc = _init_mod.InitDesc(self.name, attrs=dict(self._attrs))
        data = nd.empty(self.shape, dtype=self.dtype, ctx=cpu())
        initializer(desc, data)
        self._data = [nd.NDArray(data._data, ctx=c, dtype=self.dtype)
                      for c in self._ctx_list]
        self._deferred_init = ()
        if self.grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        if self._shape_incomplete():
            raise DeferredInitializationError(
                f"parameter {self.name} shape is still {self.shape} after "
                f"shape inference")
        init, default_init = self._deferred_init
        self._finish_init(init, default_init)

    def _init_grad(self):
        self._grad = [nd.zeros(self.shape, dtype=self.dtype, ctx=c)
                      for c in self._ctx_list]
        for g in self._grad:
            g._zeroed = True     # fresh: sparse add-deposits may stay sparse
        for arr, g in zip(self._data, self._grad):
            arr._grad = g
            arr._grad_req = self.grad_req

    # -- access -------------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is not None:
            return
        if self._deferred_init:
            raise DeferredInitializationError(
                f"parameter {self.name} has deferred initialization pending "
                f"(shape {self.shape}); run a forward pass to infer shapes")
        raise MXNetError(
            f"parameter {self.name} has not been initialized; call "
            f".initialize() (or net.initialize()) first")

    def _ctx_index(self, ctx):
        if ctx is None:
            return 0
        for i, c in enumerate(self._ctx_list):
            if c == ctx:
                return i
        raise MXNetError(f"parameter {self.name} was not initialized on {ctx}; "
                         f"contexts: {self._ctx_list}")

    def data(self, ctx=None) -> nd.NDArray:
        self._check_initialized(ctx)
        return self._data[self._ctx_index(ctx)]

    def list_data(self):
        self._check_initialized()
        return list(self._data)

    def grad(self, ctx=None) -> nd.NDArray:
        self._check_initialized(ctx)
        if self._grad is None:
            raise MXNetError(f"parameter {self.name} has grad_req='null'")
        buf = self._grad[self._ctx_index(ctx)]
        if getattr(self, "_grad_stype", "default") == "row_sparse":
            rs = getattr(buf, "_sparse", None)
            if rs is not None:
                return rs        # RowSparseNDArray: only touched rows
        return buf

    def list_grad(self):
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(f"parameter {self.name} has grad_req='null'")
        return list(self._grad)

    def list_ctx(self):
        if self._ctx_list is None:
            raise MXNetError(f"parameter {self.name} not initialized")
        return list(self._ctx_list)

    def _load_init(self, data, ctx):
        """Initialize directly from a loaded value (ref: Parameter._load_init
        — the load-into-uninitialized-net path)."""
        self._set_shape(tuple(data.shape))
        if self._ctx_list is None:
            self._ctx_list = [ctx] if isinstance(ctx, Context) else list(ctx)
        if self._data is None:
            self._data = [nd.NDArray(data._data, ctx=c, dtype=self.dtype)
                          for c in self._ctx_list]
            self._deferred_init = ()
            if self.grad_req != "null":
                self._init_grad()
        else:
            self.set_data(data)

    def set_data(self, data):
        """Set this parameter's value on every context."""
        if self._data is None and self._deferred_init:
            # adopt the shape from the provided data, finish init, overwrite
            self._set_shape(tuple(data.shape))
            self._finish_deferred_init()
        self._check_initialized()
        src = data._data if isinstance(data, nd.NDArray) else np.asarray(data)
        if tuple(data.shape) != tuple(self.shape):
            raise MXNetError(f"set_data shape {tuple(data.shape)} != parameter "
                             f"shape {self.shape} for {self.name}")
        for i, c in enumerate(self._ctx_list):
            self._data[i]._rebind(
                nd.NDArray(src, ctx=c, dtype=self.dtype)._data)

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._grad:
            g._sparse = None     # drop any stale row-sparse view too
            g._zeroed = True     # fresh buffer: sparse adds may stay sparse
            g._rebind(nd.zeros(self.shape, dtype=self.dtype, ctx=g.ctx)._data)

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            host = self._data[0]
            self._ctx_list = list(ctx)
            self._data = [nd.NDArray(host._data, ctx=c) for c in ctx]
            if self.grad_req != "null":
                self._init_grad()
        elif self._ctx_list is not None:
            self._ctx_list = list(ctx)

    def cast(self, dtype):
        self.dtype = _as_np_dtype(dtype)
        if self._data is None:
            return
        self._data = [nd.NDArray(a._data, ctx=a.ctx, dtype=self.dtype)
                      for a in self._data]
        if self.grad_req != "null":
            self._init_grad()

    def var(self):
        """A symbolic variable bound to this parameter (ref: Parameter.var —
        used when tracing a block into a Symbol graph for export)."""
        from .. import symbol as sym_mod
        return sym_mod.var(self.name,
                           shape=self.shape if not self._shape_incomplete()
                           else None)


class Constant(Parameter):
    """A non-differentiable parameter with a fixed value (ref: gluon Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, nd.NDArray):
            value = nd.array(value)
        self.value = value

        class _CInit(_init_mod.Initializer):
            def __call__(self, desc, arr):  # bypass name-suffix dispatch
                arr._rebind(value._data)

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit(),
                         differentiable=False)


class ParameterDict:
    """Prefix-scoped dict of Parameters (ref: gluon/parameter.py ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, name):
        return name in self._params

    def __getitem__(self, name) -> Parameter:
        return self._params[name]

    def __repr__(self):
        body = "\n".join(f"  {v!r}" for v in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{body}\n)"

    def get(self, name, **kwargs) -> Parameter:
        """Get-or-create ``prefix + name`` (the Block param entry point)."""
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Parameter(full, **kwargs)
            self._params[full] = param
        else:
            for key, val in kwargs.items():
                if key == "shape" and val is not None:
                    if param.shape is None or param._shape_incomplete():
                        param.shape = tuple(val)
                elif val is not None and getattr(param, key, None) not in (val, None):
                    raise MXNetError(
                        f"parameter {full} already exists with "
                        f"{key}={getattr(param, key)!r}, requested {val!r}")
        return param

    def get_constant(self, name, value=None) -> Constant:
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            if value is None:
                raise MXNetError(f"constant {full} does not exist and no "
                                 f"value was given")
            param = Constant(full, value)
            self._params[full] = param
        return param

    def _get_impl(self, full_name):
        if full_name in self._params:
            return self._params[full_name]
        if self._shared is not None and full_name in self._shared:
            self._params[full_name] = self._shared[full_name]
            return self._params[full_name]
        return None

    def update(self, other):
        for key, val in other.items():
            if key in self._params and self._params[key] is not val:
                raise MXNetError(f"duplicate parameter name {key}")
            self._params[key] = val

    # -- bulk ops ------------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = _init_mod.Uniform()
        for param in self.values():
            param.initialize(None, ctx, default_init=init,
                             force_reinit=force_reinit)

    def zero_grad(self):
        for param in self.values():
            param.zero_grad()

    def reset_ctx(self, ctx):
        for param in self.values():
            param.reset_ctx(ctx)

    def setattr(self, name, value):
        for param in self.values():
            setattr(param, name, value)

    def save(self, filename, strip_prefix=""):
        """ref: ParameterDict.save → the NDArray .params container format."""
        arg_dict = {}
        for param in self.values():
            name = param.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = param.data(param.list_ctx()[0])
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = nd.load(filename)
        if not isinstance(loaded, dict):
            raise MXNetError(f"{filename} does not contain a name→array dict")
        # strip arg:/aux: prefixes from export/save_checkpoint artifacts
        # (ref: ParameterDict.load does the same)
        loaded = {(k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                   else k): v for k, v in loaded.items()}
        if restore_prefix:
            loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, param in self.items():
            if name not in loaded:
                if not allow_missing:
                    raise MXNetError(f"parameter {name} missing from "
                                     f"{filename}")
                continue
            param._load_init(loaded[name],
                             ctx if ctx is not None else [current_context()])
        if not ignore_extra:
            extra = set(loaded) - set(self.keys())
            if extra:
                raise MXNetError(f"{filename} contains extra parameters "
                                 f"{sorted(extra)}; pass ignore_extra=True")

"""Gluon neural-network layers (ref: python/mxnet/gluon/nn/)."""
from ..block import Block, HybridBlock, SymbolBlock
from .basic_layers import *
from .binary_layers import *
from .conv_layers import *

from . import basic_layers, binary_layers, conv_layers

__all__ = (["Block", "HybridBlock", "SymbolBlock"]
           + basic_layers.__all__ + conv_layers.__all__
           + binary_layers.__all__)

"""Binary (1-bit) network layers — the BMXNet fork's Gluon surface
(SURVEY §2 #23: yanghaojin/BMXNet adds QDense/QConv2D/QActivation on top of
upstream; smd_hpi binary-ops line).

TPU design: sign() binarization with straight-through gradients (det_sign
/ approx_sign ops); the binary GEMM runs as a ±1 bf16 matmul on the MXU —
on TPU that IS the fast path (no integer XNOR-popcount unit outruns the
systolic array), with XNOR-Net alpha scaling preserved so accuracy math
matches BMXNet.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["QActivation", "QDense", "QConv2D", "pack_binary_weights"]


class QActivation(HybridBlock):
    """BMXNet QActivation: 1-bit sign (or k-bit uniform) activation."""

    def __init__(self, act_bit=1, backward_only=False, **kwargs):
        super().__init__(**kwargs)
        self._act_bit = act_bit
        self._backward_only = backward_only

    def hybrid_forward(self, F, x):
        return F.QActivation(x, act_bit=self._act_bit,
                             backward_only=self._backward_only)


class QDense(HybridBlock):
    """BMXNet QFullyConnected as a Gluon layer: binary weights (and by
    default binary inputs) with alpha scaling."""

    def __init__(self, units, act_bit=1, use_bias=False, in_units=0,
                 binarize_input=True, scaling=True,
                 weight_initializer=None, bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        if act_bit != 1:
            raise MXNetError("QDense supports act_bit=1 (sign) — use "
                             "QActivation for k-bit activations")
        self._units = units
        self._binarize_input = binarize_input
        self._scaling = scaling
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units),
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x):
        import numpy as np
        self.weight._set_shape((self._units,
                                int(np.prod(x.shape[1:]))))

    def hybrid_forward(self, F, x, weight, bias=None):
        args = [x, weight] + ([bias] if bias is not None else [])
        return F.QFullyConnected(*args, num_hidden=self._units,
                                 no_bias=bias is None,
                                 binarize_input=self._binarize_input,
                                 scaling=self._scaling)


class QConv2D(HybridBlock):
    """BMXNet QConvolution as a Gluon layer."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, act_bit=1, use_bias=False,
                 in_channels=0, binarize_input=True, scaling=True,
                 weight_initializer=None, bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        if act_bit != 1:
            raise MXNetError("QConv2D supports act_bit=1 (sign)")

        def pair(v):
            return tuple(v) if isinstance(v, (list, tuple)) else (v, v)
        self._channels = channels
        self._kwargs = dict(kernel=pair(kernel_size), stride=pair(strides),
                            pad=pair(padding), dilate=pair(dilation),
                            num_group=groups, num_filter=channels,
                            binarize_input=binarize_input, scaling=scaling)
        self._groups = groups
        with self.name_scope():
            self.weight = self.params.get(
                "weight",
                shape=(channels, in_channels // groups if in_channels
                       else 0) + pair(kernel_size),
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x):
        self.weight._set_shape(
            (self._channels, x.shape[1] // self._groups)
            + self._kwargs["kernel"])

    def hybrid_forward(self, F, x, weight, bias=None):
        args = [x, weight] + ([bias] if bias is not None else [])
        return F.QConvolution(*args, no_bias=bias is None, **self._kwargs)


def pack_binary_weights(layer):
    """Pre-pack a trained QDense/QConv2D layer's weights for XNOR-popcount
    inference (32x weight compression — the BMXNet deployment flow, where
    binary_word-packed models ship to mobile). Returns:

    - QDense:  (w_packed uint32 [units, W32], alpha or None,
                bias or None)
    - QConv2D: (w_packed uint32 [channels, W32] over C*kh*kw,
                alpha or None, bias or None)

    Use with ``nd.contrib.xnor_fully_connected`` /
    ``nd.contrib.xnor_convolution`` — pass alpha and bias positionally in
    that order (alpha may be a ones-scalar when the layer has
    scaling=False but a bias); outputs then equal the layer's own forward
    for sign-binarized inputs (tests/test_binary.py). Caveat for padded
    convolutions: the float-simulation layer zero-pads (border taps
    contribute 0) while the packed path pads with +1 like BMXNet's
    binary algebra — border outputs differ between the two by design.
    """
    from ... import ndarray as nd_mod
    w = layer.weight.data()
    bias = layer.bias.data() if getattr(layer, "bias", None) is not None \
        else None
    if isinstance(layer, QDense):
        wp = nd_mod.contrib.binary_pack(w)
        alpha = nd_mod.mean(nd_mod.abs(w)) if layer._scaling else None
        if alpha is None and bias is not None:
            alpha = nd_mod.ones((1,))   # keep the positional slots aligned
        return wp, alpha, bias
    if isinstance(layer, QConv2D):
        if layer._kwargs["num_group"] != 1 or \
                tuple(layer._kwargs["dilate"]) != (1, 1):
            raise MXNetError(
                "pack_binary_weights: xnor_convolution supports only "
                "groups=1, dilation=1 — this layer's packed inference "
                "would be silently wrong")
        w2 = w.reshape((w.shape[0], -1))
        wp = nd_mod.contrib.binary_pack(w2)
        alpha = nd_mod.mean(nd_mod.abs(w2), axis=1) \
            if layer._kwargs["scaling"] else None
        if alpha is None and bias is not None:
            alpha = nd_mod.ones((1,))
        return wp, alpha, bias
    raise MXNetError(f"pack_binary_weights: unsupported layer "
                     f"{type(layer).__name__}")

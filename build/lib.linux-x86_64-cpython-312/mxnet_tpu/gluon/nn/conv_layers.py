"""Gluon convolution & pooling layers
(ref: python/mxnet/gluon/nn/conv_layers.py)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tuple(val, n):
    if isinstance(val, (list, tuple)):
        if len(val) != n:
            raise MXNetError(f"expected length-{n} tuple, got {val}")
        return tuple(val)
    return (val,) * n


class _Conv(HybridBlock):
    """Shared conv machinery (ref: gluon/nn/conv_layers.py _Conv)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, **kwargs):
        super().__init__(**kwargs)
        ndim = len(kernel_size)
        if layout not in ("NCW", "NCHW", "NCDHW"):
            raise MXNetError(f"only channel-first layouts are supported, got "
                             f"{layout!r} (TPU/XLA picks the internal layout)")
        self._channels = channels
        self._in_channels = in_channels
        self._groups = groups
        self._op_name = op_name
        self._kwargs = {
            "kernel": kernel_size,
            "stride": _tuple(strides, ndim),
            "dilate": _tuple(dilation, ndim),
            "pad": _tuple(padding, ndim),
            "num_filter": channels,
            "num_group": groups,
            "no_bias": not use_bias,
        }
        if adj is not None:
            self._kwargs["adj"] = _tuple(adj, ndim)
        self._activation = activation
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups
                          if in_channels else 0) + kernel_size
            else:  # Deconvolution: weight is (in, out/groups, *k)
                wshape = (in_channels, channels // groups) + kernel_size
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer,
                                            allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x):
        in_channels = x.shape[1]
        kernel = self._kwargs["kernel"]
        if self._op_name == "Convolution":
            self.weight._set_shape(
                (self._channels, in_channels // self._groups) + kernel)
        else:
            self.weight._set_shape(
                (in_channels, self._channels // self._groups) + kernel)
        self._in_channels = in_channels

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            out = op(x, weight, bias, **self._kwargs)
        if self._activation is not None:
            out = F.Activation(out, act_type=self._activation)
        return out

    def __repr__(self):
        return (f"{self.__class__.__name__}({self._in_channels} -> "
                f"{self._channels}, kernel_size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tuple(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tuple(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tuple(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    """Shared pooling machinery (ref: gluon/nn/conv_layers.py _Pooling)."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size,
            "stride": _tuple(strides, len(pool_size)),
            "pad": _tuple(padding, len(pool_size)),
            "global_pool": global_pool,
            "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
        }
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return (f"{self.__class__.__name__}(size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']}, "
                f"padding={self._kwargs['pad']})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 1), strides, padding, ceil_mode,
                         False, "max", **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 2), strides, padding, ceil_mode,
                         False, "max", **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 3), strides, padding, ceil_mode,
                         False, "max", **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuple(pool_size, 1), strides, padding, ceil_mode,
                         False, "avg", count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuple(pool_size, 2), strides, padding, ceil_mode,
                         False, "avg", count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuple(pool_size, 3), strides, padding, ceil_mode,
                         False, "avg", count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, True, "max", **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, True, "max", **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, True, "max", **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, True, "avg", **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, True, "avg", **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, True, "avg", **kwargs)


class ReflectionPad2D(HybridBlock):
    """ref: nn.ReflectionPad2D → pad op with mode='reflect'."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)

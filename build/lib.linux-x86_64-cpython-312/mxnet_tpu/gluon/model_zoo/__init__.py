"""Model zoo (ref: python/mxnet/gluon/model_zoo/__init__.py; bert adds
GluonNLP-parity language models)."""
from . import bert, ssd, transformer, vision
from .vision import get_model

__all__ = ["vision", "bert", "get_model"]

"""SSD detection family (GluonCV parity: gluoncv.model_zoo.ssd — the
reference ecosystem's SSD-512 config, driver config #5).

TPU-first design notes (SURVEY §7 hard-parts #2): every stage is static
shape — anchors are compile-time constants per feature map, target
assignment (MultiBoxTarget) and NMS (MultiBoxDetection) are vmapped
fixed-size kernels with -1 padding instead of dynamic filtering, so the
whole train/infer step jits cleanly.
"""
from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock
from ..loss import Loss, SoftmaxCrossEntropyLoss

__all__ = ["SSD", "SSDMultiBoxLoss", "get_ssd", "ssd_512_resnet18_v1",
           "ssd_300_resnet18_v1"]


def _conv_block(channels, stride=1):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, 3, strides=stride, padding=1,
                      use_bias=False))
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    return out


class _DownSample(HybridBlock):
    """Feature-map downscaler between detection scales."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv_block(channels))
        self.body.add(_conv_block(channels))
        self.body.add(nn.MaxPool2D(2))

    def hybrid_forward(self, F, x):
        return self.body(x)


class SSD(HybridBlock):
    """Multi-scale single-shot detector.

    forward(x) → (anchors (1, A, 4), cls_preds (N, A, C+1),
    box_preds (N, A*4)); training targets come from
    F.contrib.MultiBoxTarget, inference from F.contrib.MultiBoxDetection
    (ref ecosystem: gluoncv ssd.py SSD.forward).
    """

    def __init__(self, features, classes, sizes, ratios, num_scales=None,
                 scale_channels=128, **kwargs):
        super().__init__(**kwargs)
        num_scales = num_scales or len(sizes)
        if not (len(sizes) == len(ratios) == num_scales):
            raise MXNetError("sizes/ratios must have one entry per scale")
        self._num_classes = classes
        self._sizes = sizes
        self._ratios = ratios
        self._num_scales = num_scales
        with self.name_scope():
            self.features = features
            self.scale_blocks = nn.HybridSequential(prefix="scales_")
            self.cls_preds = nn.HybridSequential(prefix="cls_")
            self.box_preds = nn.HybridSequential(prefix="box_")
            with self.scale_blocks.name_scope():
                for i in range(num_scales - 1):
                    self.scale_blocks.add(_DownSample(scale_channels))
            for i in range(num_scales):
                a = len(sizes[i]) + len(ratios[i]) - 1
                with self.cls_preds.name_scope():
                    self.cls_preds.add(nn.Conv2D(a * (classes + 1), 3,
                                                 padding=1))
                with self.box_preds.name_scope():
                    self.box_preds.add(nn.Conv2D(a * 4, 3, padding=1))

    def hybrid_forward(self, F, x):
        feats = self.features(x)
        anchors, cls_preds, box_preds = [], [], []
        cls_blocks = list(self.cls_preds._children.values())
        box_blocks = list(self.box_preds._children.values())
        scale_blocks = list(self.scale_blocks._children.values())
        for i in range(self._num_scales):
            anchors.append(F.contrib.MultiBoxPrior(
                feats, sizes=self._sizes[i], ratios=self._ratios[i]))
            cp = cls_blocks[i](feats)
            bp = box_blocks[i](feats)
            n = cp.shape[0]
            cls_preds.append(F.reshape(
                F.transpose(cp, axes=(0, 2, 3, 1)),
                (n, -1, self._num_classes + 1)))
            box_preds.append(F.reshape(
                F.transpose(bp, axes=(0, 2, 3, 1)), (n, -1)))
            if i < self._num_scales - 1:
                feats = scale_blocks[i](feats)
        return (F.concat(*anchors, dim=1),
                F.concat(*cls_preds, dim=1),
                F.concat(*box_preds, dim=1))


class SSDMultiBoxLoss(Loss):
    """cls cross-entropy + smooth-L1 localization
    (ref ecosystem: gluoncv SSDMultiBoxLoss; reference ops:
    MultiBoxTarget + SoftmaxOutput + smooth_l1)."""

    def __init__(self, lambd=1.0, **kwargs):
        super().__init__(None, 0, **kwargs)
        self._lambd = lambd
        self._ce = SoftmaxCrossEntropyLoss()

    def hybrid_forward(self, F, cls_preds, box_preds, cls_targets,
                       box_targets, box_masks):
        n = cls_preds.shape[0]
        c = cls_preds.shape[-1]
        valid = (cls_targets >= 0).astype(cls_preds.dtype)
        cls_loss = self._ce(F.reshape(cls_preds, (-1, c)),
                            F.reshape(F.broadcast_maximum(
                                cls_targets,
                                F.zeros_like(cls_targets)), (-1,)))
        cls_loss = F.reshape(cls_loss, (n, -1)) * valid
        cls_loss = cls_loss.sum(axis=1) / F.broadcast_maximum(
            valid.sum(axis=1), F.ones_like(valid.sum(axis=1)))
        box_l = F.smooth_l1((box_preds - box_targets) * box_masks,
                            scalar=1.0)
        box_loss = F.reshape(box_l, (n, -1)).sum(axis=1) / F.broadcast_maximum(
            F.reshape(box_masks, (n, -1)).sum(axis=1),
            F.ones((n,)))
        return cls_loss + self._lambd * box_loss


def _resnet_features(num_layers, thumbnail):
    from .vision.resnet import get_resnet
    net = get_resnet(1, num_layers, thumbnail=thumbnail)
    feats = nn.HybridSequential()
    # everything up to (excluding) global pool
    children = list(net.features._children.values())[:-1]
    for block in children:
        feats.add(block)
    return feats


_DEFAULT_SIZES = [[0.2, 0.272], [0.37, 0.447], [0.54, 0.619],
                  [0.71, 0.79], [0.88, 0.961]]
_DEFAULT_RATIOS = [[1.0, 2.0, 0.5]] * 5


def get_ssd(base="resnet18_v1", classes=20, data_shape=512,
            num_scales=5, pretrained_base=False, thumbnail=False,
            **kwargs):
    if not base.startswith("resnet"):
        raise MXNetError("get_ssd supports resnet bases in this build")
    num_layers = int(base.split("_")[0].replace("resnet", ""))
    features = _resnet_features(num_layers, thumbnail)
    return SSD(features, classes, _DEFAULT_SIZES[:num_scales],
               _DEFAULT_RATIOS[:num_scales], num_scales=num_scales,
               **kwargs)


def ssd_512_resnet18_v1(classes=20, **kwargs):
    return get_ssd("resnet18_v1", classes=classes, data_shape=512, **kwargs)


def ssd_300_resnet18_v1(classes=20, **kwargs):
    return get_ssd("resnet18_v1", classes=classes, data_shape=300,
                   num_scales=4, **kwargs)

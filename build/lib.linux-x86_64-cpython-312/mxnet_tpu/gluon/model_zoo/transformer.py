"""Transformer encoder-decoder for NMT (Sockeye parity — the reference
ecosystem's sockeye.transformer drives driver config #4; MXNet 1.x itself
ships the fused attention ops it uses, src/operator/contrib/transformer.cc).

TPU-first: self/cross attention run through the blockwise flash-attention
op; the decoder trains teacher-forced with causal masking in ONE jitted
step (no BucketingModule needed — but Module+bucketing works too via the
shape-keyed jit cache); greedy decode keeps static shapes by scanning to
max_length.
"""
from __future__ import annotations

import math

import numpy as np

from .. import nn
from ..block import HybridBlock
from .bert import MultiHeadAttention, PositionwiseFFN

__all__ = ["TransformerEncoder", "TransformerDecoder", "TransformerModel",
           "transformer_base", "CrossAttention"]


class CrossAttention(HybridBlock):
    """Attention with separate query and memory inputs (decoder→encoder)."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._heads = num_heads
        with self.name_scope():
            self.q_proj = nn.Dense(units, flatten=False, prefix="q_")
            self.kv_proj = nn.Dense(2 * units, flatten=False, prefix="kv_")
            self.proj = nn.Dense(units, flatten=False, prefix="out_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mem):
        # shape-free (exports symbolically): the fused op splits heads and
        # K/V internally off the concrete trace shapes
        out = F.contrib.fused_cross_attention(
            self.q_proj(x), self.kv_proj(mem), heads=self._heads)
        out = self.proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class _EncoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attn = MultiHeadAttention(units, num_heads, dropout=dropout,
                                           prefix="attn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                       activation="relu", prefix="ffn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")

    def hybrid_forward(self, F, x):
        x = self.ln1(x + self.attn(x))
        return self.ln2(x + self.ffn(x))


class _DecoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attn = MultiHeadAttention(units, num_heads,
                                                dropout=dropout, causal=True,
                                                prefix="self_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.cross_attn = CrossAttention(units, num_heads,
                                             dropout=dropout,
                                             prefix="cross_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                       activation="relu", prefix="ffn_")
            self.ln3 = nn.LayerNorm(prefix="ln3_")

    def hybrid_forward(self, F, x, mem):
        x = self.ln1(x + self.self_attn(x))
        x = self.ln2(x + self.cross_attn(x, mem))
        return self.ln3(x + self.ffn(x))


def _positions(max_length, units):
    pos = np.arange(max_length)[:, None]
    dim = np.arange(0, units, 2)[None, :]
    angle = pos / np.power(10000.0, dim / units)
    enc = np.zeros((max_length, units), dtype=np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.cells = nn.HybridSequential(prefix="cells_")
            with self.cells.name_scope():
                for _ in range(num_layers):
                    self.cells.add(_EncoderCell(units, hidden_size,
                                                num_heads, dropout))

    def hybrid_forward(self, F, x):
        return self.cells(x)


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        self._cells = []
        with self.name_scope():
            for i in range(num_layers):
                cell = _DecoderCell(units, hidden_size, num_heads, dropout,
                                    prefix=f"cell{i}_")
                self.register_child(cell, f"cell{i}")
                self._cells.append(cell)

    def hybrid_forward(self, F, x, mem):
        for cell in self._cells:
            x = cell(x, mem)
        return x


class TransformerModel(HybridBlock):
    """Sockeye-parity seq2seq transformer: forward(src, tgt) → logits
    (teacher forcing); ``translate`` runs greedy decode."""

    def __init__(self, src_vocab, tgt_vocab, num_layers=6, units=512,
                 hidden_size=2048, num_heads=8, max_length=512,
                 dropout=0.1, tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab, units,
                                          prefix="src_embed_")
            self.tgt_embed = nn.Embedding(tgt_vocab, units,
                                          prefix="tgt_embed_")
            self.encoder = TransformerEncoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="enc_")
            self.decoder = TransformerDecoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="dec_")
            self.output = nn.Dense(tgt_vocab, flatten=False, prefix="out_")
            self.dropout = nn.Dropout(dropout) if dropout else None
            # sinusoidal table as a Constant parameter: exports with the
            # model and keeps the embed path shape-free (slice_like)
            self.pos_weight = self.params.get_constant(
                "pos_embed", _positions(max_length, units))

    def _embed(self, F, tokens, embed, pos_weight):
        x = embed(tokens) * math.sqrt(self._units)
        pos = F.slice_like(F.expand_dims(pos_weight, axis=0), x, axes=(1,))
        x = F.broadcast_add(x, pos)
        if self.dropout is not None:
            x = self.dropout(x)
        return x

    def encode(self, src):
        from ... import ndarray as F
        return self.encoder(self._embed(F, src, self.src_embed,
                                        self.pos_weight.data()))

    def hybrid_forward(self, F, src, tgt, pos_weight=None):
        pos = pos_weight if pos_weight is not None else \
            self.pos_weight.data()
        mem = self.encoder(self._embed(F, src, self.src_embed, pos))
        dec = self.decoder(self._embed(F, tgt, self.tgt_embed, pos), mem)
        return self.output(dec)

    def translate(self, src, bos_id=1, eos_id=2, max_steps=None):
        """Greedy decode (static shapes: fixed max_steps loop)."""
        from ... import ndarray as nd
        import numpy as onp
        max_steps = max_steps or min(self._max_length, 64)
        mem = self.encode(src)
        b = src.shape[0]
        tokens = onp.full((b, 1), bos_id, dtype=onp.int32)
        finished = onp.zeros(b, bool)
        for _ in range(max_steps):
            tgt = nd.array(tokens)
            dec = self.decoder(self._embed(nd, tgt, self.tgt_embed,
                                           self.pos_weight.data()), mem)
            logits = self.output(dec)
            nxt = logits.asnumpy()[:, -1].argmax(axis=-1)
            nxt = onp.where(finished, eos_id, nxt)
            tokens = onp.concatenate([tokens, nxt[:, None].astype(onp.int32)],
                                     axis=1)
            finished |= nxt == eos_id
            if finished.all():
                break
        return tokens[:, 1:]


def transformer_base(src_vocab, tgt_vocab, **kwargs):
    """The Sockeye/`Attention is All You Need` base config."""
    cfg = dict(num_layers=6, units=512, hidden_size=2048, num_heads=8,
               dropout=0.1)
    cfg.update(kwargs)
    return TransformerModel(src_vocab, tgt_vocab, **cfg)

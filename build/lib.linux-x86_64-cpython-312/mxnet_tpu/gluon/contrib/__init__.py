"""gluon.contrib (ref: python/mxnet/gluon/contrib/__init__.py)."""
from . import nn
from .nn import Concurrent, HybridConcurrent, Identity

__all__ = ["nn", "Concurrent", "HybridConcurrent", "Identity"]

"""gluon.contrib.nn (ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import HybridSequential, Sequential, SyncBatchNorm

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SyncBatchNorm"]


class HybridConcurrent(HybridSequential):
    """Parallel children concatenated on ``axis``
    (ref: contrib/nn HybridConcurrent — Inception-style branches)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)

    def forward(self, x):
        from ... import nn as _nn  # noqa: F401
        from .... import ndarray as F
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Concurrent(Sequential):
    """Eager variant (ref: contrib/nn Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as F
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """ref: contrib/nn Identity."""

    def hybrid_forward(self, F, x):
        return x

"""``mx.image`` — image decode & augmentation
(ref: python/mxnet/image/image.py; cv2 backend matches the reference's
src/io/image_aug_default.cc OpenCV augmenters)."""
from __future__ import annotations

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize", "HorizontalFlipAug",
           "CastAug", "ColorNormalizeAug", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "CenterCropAug", "CreateAugmenter", "Augmenter"]


def _cv2():
    import cv2
    return cv2


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """ref: image.py imdecode (cv2 path)."""
    cv2 = _cv2()
    img = cv2.imdecode(np.frombuffer(bytes(buf), dtype=np.uint8),
                       cv2.IMREAD_COLOR if flag else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("imdecode failed")
    if flag and to_rgb:
        img = img[:, :, ::-1]
    if img.ndim == 2:
        img = img[:, :, None]
    arr = nd.array(np.ascontiguousarray(img))
    if out is not None:
        out._rebind(arr._data)
        return out
    return arr


def imread(filename, flag=1, to_rgb=True):
    cv2 = _cv2()
    img = cv2.imread(filename, cv2.IMREAD_COLOR if flag
                     else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError(f"imread failed for {filename}")
    if flag and to_rgb:
        img = img[:, :, ::-1]
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(np.ascontiguousarray(img))


def imresize(src, w, h, interp=1):
    cv2 = _cv2()
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = cv2.resize(arr, (w, h), interpolation=interp)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out)


def resize_short(src, size, interp=1):
    """Resize so the short side equals size (ref: image.py resize_short)."""
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    out = nd.array(src.asnumpy()[y0:y0 + h, x0:x0 + w])
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=1):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = np.random.randint(0, w - new_w + 1)
    y0 = np.random.randint(0, h - new_h + 1)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=1):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype("float32") if isinstance(src, nd.NDArray) else \
        nd.array(src, dtype="float32")
    out = src - (mean if isinstance(mean, nd.NDArray) else nd.array(mean))
    if std is not None:
        out = out / (std if isinstance(std, nd.NDArray) else nd.array(std))
    return out


class Augmenter:
    """ref: image.py Augmenter."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            return nd.array(src.asnumpy()[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=list(np.ravel(mean)), std=list(np.ravel(std)))
        self.mean = nd.array(mean)
        self.std = nd.array(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """ref: image.py CreateAugmenter — the common aug pipeline factory."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and np.any(np.asarray(mean)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist

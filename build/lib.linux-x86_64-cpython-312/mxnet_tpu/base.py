"""Base utilities for mxnet_tpu.

TPU-native re-design of the reference's base layer. The reference routes
everything through a C ABI (ref: include/mxnet/base.h, include/mxnet/c_api.h);
here the "runtime" is JAX/XLA, so the base layer is dtype/string plumbing,
error types, and the environment-variable knobs the reference exposes as
``MXNET_*`` (ref: docs env_var.md catalog, read via dmlc::GetEnv).
"""
from __future__ import annotations

import os

import numpy as _np

__all__ = [
    "MXNetError",
    "string_types",
    "numeric_types",
    "integer_types",
    "mx_real_t",
    "_as_np_dtype",
    "_dtype_name",
    "getenv",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (ref: MXGetLastError carries C++ errors
    across the C ABI; here plain Python exceptions)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# Default real dtype (ref: mshadow::default_real_t = float32).
mx_real_t = _np.float32

_DTYPE_ALIASES = {
    "float": _np.float32,
    "double": _np.float64,
    "half": _np.float16,
    "bfloat16": None,  # resolved lazily via ml_dtypes below
}


def _as_np_dtype(dtype):
    """Normalize a user dtype (string/np.dtype/type) to a numpy dtype object.

    Supports 'bfloat16' through ml_dtypes (what JAX uses on TPU).
    """
    if dtype is None:
        return _np.dtype(mx_real_t)
    if isinstance(dtype, str):
        if dtype in ("bfloat16", "bf16"):
            import ml_dtypes

            return _np.dtype(ml_dtypes.bfloat16)
        if dtype in _DTYPE_ALIASES and _DTYPE_ALIASES[dtype] is not None:
            return _np.dtype(_DTYPE_ALIASES[dtype])
    try:
        return _np.dtype(dtype)
    except TypeError:
        import ml_dtypes

        if dtype in (ml_dtypes.bfloat16,):
            return _np.dtype(dtype)
        raise


def _dtype_name(dtype) -> str:
    """Canonical string name for a dtype ('float32', 'bfloat16', ...)."""
    return _as_np_dtype(dtype).name


def getenv(name: str, default=None, typ=str):
    """Read an ``MXNET_*`` env knob (ref: dmlc::GetEnv use sites)."""
    val = os.environ.get(name)
    if val is None:
        return default
    if typ is bool:
        return val not in ("0", "false", "False", "")
    return typ(val)

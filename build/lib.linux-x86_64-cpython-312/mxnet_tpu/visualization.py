"""``mx.viz`` — network visualization (ref: python/mxnet/visualization.py).

``print_summary`` walks the Symbol DAG and prints the reference's layer
table (name, shape, params, connections); ``plot_network`` emits graphviz
dot source (rendering gated on the graphviz binary being installed).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """ref: visualization.py print_summary."""
    arg_shapes = {}
    if shape is not None:
        arg_names = symbol.list_arguments()
        shapes, _, aux = symbol.infer_shape(**shape)
        arg_shapes = dict(zip(arg_names, shapes))
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields):
        line = ""
        for f, pos in zip(fields, positions):
            line = (line + str(f))[:pos - 1].ljust(pos)
        print(line)

    print("=" * line_length)
    print_row(headers)
    print("=" * line_length)
    total = 0
    topo = symbol._topo()
    for node in topo:
        if node.op is None:
            continue
        inputs = [s._node.name for s in node.inputs]
        params = 0
        for s in node.inputs:
            if s._node.op is None and s._node.name in arg_shapes and \
                    arg_shapes[s._node.name] is not None and \
                    not s._node.name.endswith(("data", "label")):
                params += int(np.prod(arg_shapes[s._node.name]))
        total += params
        print_row([f"{node.name} ({node.op})", "", params,
                   ", ".join(inputs[:2])])
    print("=" * line_length)
    print(f"Total params: {total}")
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """ref: visualization.py plot_network → graphviz Digraph source."""
    lines = [f'digraph "{title}" {{', "  rankdir=BT;"]
    topo = symbol._topo()
    idx = {}
    for i, node in enumerate(topo):
        idx[id(node)] = i
        if node.op is None:
            if hide_weights and not node.name.endswith(("data", "label")):
                continue
            lines.append(f'  n{i} [label="{node.name}" shape=oval];')
        else:
            lines.append(f'  n{i} [label="{node.name}\\n{node.op}" '
                         f'shape=box];')
    drawn = {i for i, node in enumerate(topo)
             if node.op is not None or not hide_weights
             or node.name.endswith(("data", "label"))}
    for node in topo:
        if node.op is None:
            continue
        for s in node.inputs:
            j = idx[id(s._node)]
            if j in drawn:
                lines.append(f"  n{j} -> n{idx[id(node)]};")
    lines.append("}")
    source = "\n".join(lines)

    class _Dot:
        def __init__(self, src):
            self.source = src

        def render(self, filename=None, **kwargs):
            raise MXNetError("graphviz rendering is not available in this "
                             "environment; use .source for the dot text")

        def _repr_svg_(self):
            return None
    return _Dot(source)

"""Dynamic op-library loading (ref: include/mxnet/lib_api.h MXLoadLib —
the reference's header-only plugin ABI that registers CustomOp/CustomPass
from an external .so at runtime; SURVEY §2 #6).

Two plugin formats:

- **Python plugin** (``.py``): executed as a module; it calls
  ``mxnet_tpu.ops.register`` (or ``mx.operator.register``) itself. The
  open-registry equivalent of lib_api.h's REGISTER_OP, with full access
  to jnp/lax/Pallas.
- **Native plugin** (``.so``): a C shared library exporting the flat ABI
  below, loaded with ctypes; each exported op becomes a registered
  operator whose compute runs through ``jax.pure_callback`` (host
  callback — the same engine-integration point as mx.operator.CustomOp):

      int         mxtpu_plugin_op_count(void);
      const char* mxtpu_plugin_op_name(int i);
      // y[0..n) = f(x[0..n)); same-shape unary contract
      int         mxtpu_plugin_op_compute(int i, const float* x,
                                          float* y, long n);

  (The reference's lib_api.h is likewise a C ABI over flat tensors; the
  same-shape unary contract covers the elementwise custom kernels its
  examples ship. Richer signatures belong in Python plugins.)
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

from .base import MXNetError

__all__ = ["load", "loaded_libraries"]

_LOADED = {}
_HANDLES = []      # keep native CDLLs alive without polluting _LOADED


def loaded_libraries():
    return dict(_LOADED)


def load(path, verbose=True):
    """Load an op library (.py or .so) and register its operators
    (ref: mx.library.load -> MXLoadLib)."""
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise MXNetError(f"library.load: {path} does not exist")
    if path in _LOADED:
        return _LOADED[path]
    if path.endswith(".py"):
        names = _load_python(path)
    elif path.endswith((".so", ".dylib")):
        names = _load_native(path)
    else:
        raise MXNetError(f"library.load: {path}: expected a .py or .so "
                         f"op library")
    # regenerate the nd/sym wrapper namespaces so the new ops appear
    # (the reference's MXLoadLib similarly re-lists atomic symbol
    # creators after loading)
    from . import ndarray as _nd_ns
    from . import symbol as _sym_ns
    _nd_ns._expose()
    _sym_ns._expose()
    _LOADED[path] = names
    if verbose:
        print(f"loaded library {os.path.basename(path)}: "
              f"registered {names}")
    return names


def _load_python(path):
    import importlib.util

    from .ops import registry
    before = set(registry.list_ops())
    spec = importlib.util.spec_from_file_location(
        f"mxtpu_plugin_{os.path.basename(path)[:-3]}", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return sorted(set(registry.list_ops()) - before)


def _load_native(path):
    import jax

    from .ops.registry import register
    lib = ctypes.CDLL(path)
    try:
        lib.mxtpu_plugin_op_count.restype = ctypes.c_int
        lib.mxtpu_plugin_op_name.restype = ctypes.c_char_p
        lib.mxtpu_plugin_op_name.argtypes = [ctypes.c_int]
        lib.mxtpu_plugin_op_compute.restype = ctypes.c_int
        lib.mxtpu_plugin_op_compute.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_long]
        n_ops = lib.mxtpu_plugin_op_count()
    except AttributeError as e:
        raise MXNetError(
            f"library.load: {path} does not export the mxtpu_plugin_* "
            f"ABI (see mxnet_tpu/library.py docstring)") from e

    names = []
    for i in range(n_ops):
        op_name = lib.mxtpu_plugin_op_name(i).decode()

        def make_fn(idx, nm):
            def host_compute(x):
                x = np.ascontiguousarray(x, dtype=np.float32)
                y = np.empty_like(x)
                rc = lib.mxtpu_plugin_op_compute(
                    idx, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    x.size)
                if rc != 0:
                    raise MXNetError(f"plugin op {nm} failed rc={rc}")
                return y

            def fn(x):
                return jax.pure_callback(
                    host_compute,
                    jax.ShapeDtypeStruct(x.shape, np.float32),
                    x, vmap_method="sequential")
            return fn

        register(op_name, differentiable=False,
                 doc=f"plugin op from {os.path.basename(path)} "
                     f"(lib_api.h-style dynamic registration)")(
            make_fn(i, op_name))
        names.append(op_name)
    _HANDLES.append(lib)     # keep the CDLL alive for process lifetime
    return names

"""Sequence ops (ref: src/operator/sequence_mask.cc, sequence_last.cc,
sequence_reverse.cc) — variable-length handling used by the RNN/NMT stack
(SURVEY §5.7). Data layout follows the reference: time-major (T, N, ...) by
default, `axis` selects the time axis."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import OpParam, register


def _len_mask(x, seq_len, axis):
    """(T, N, ...) bool mask of valid steps along `axis` given lengths (N,)."""
    T = x.shape[axis]
    steps = jnp.arange(T)
    mask = steps[:, None] < seq_len[None, :].astype(jnp.int32)  # (T, N)
    if axis == 1:
        mask = mask.T
    extra = x.ndim - 2
    return mask.reshape(mask.shape + (1,) * extra)


@register("SequenceMask", num_inputs=-1,
          params=[OpParam("use_sequence_length", bool, False),
                  OpParam("value", float, 0.0),
                  OpParam("axis", int, 0)],
          doc="Zero/fill steps beyond each sequence's length "
              "(ref: src/operator/sequence_mask.cc)")
def _sequence_mask(data, *rest, use_sequence_length=False, value=0.0, axis=0):
    if not use_sequence_length:
        return data
    seq_len = rest[0]
    mask = _len_mask(data, seq_len, axis)
    return jnp.where(mask, data, jnp.full_like(data, value))


@register("SequenceLast", num_inputs=-1,
          params=[OpParam("use_sequence_length", bool, False),
                  OpParam("axis", int, 0)],
          doc="Select the last valid step per sequence "
              "(ref: src/operator/sequence_last.cc)")
def _sequence_last(data, *rest, use_sequence_length=False, axis=0):
    if not use_sequence_length:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    seq_len = rest[0].astype(jnp.int32) - 1
    if axis == 0:
        gathered = jnp.take_along_axis(
            data, seq_len.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)
        return jnp.squeeze(gathered, axis=0)
    gathered = jnp.take_along_axis(
        data, seq_len.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1)
    return jnp.squeeze(gathered, axis=1)


@register("SequenceReverse", num_inputs=-1,
          params=[OpParam("use_sequence_length", bool, False),
                  OpParam("axis", int, 0)],
          doc="Reverse each sequence up to its length "
              "(ref: src/operator/sequence_reverse.cc)")
def _sequence_reverse(data, *rest, use_sequence_length=False, axis=0):
    assert axis == 0, "SequenceReverse supports time-major (axis=0) only"
    if not use_sequence_length:
        return jnp.flip(data, axis=0)
    seq_len = rest[0].astype(jnp.int32)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]                      # (T, 1)
    src = jnp.where(steps < seq_len[None, :], seq_len[None, :] - 1 - steps, steps)
    src = src.reshape((T, data.shape[1]) + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, src, axis=0)

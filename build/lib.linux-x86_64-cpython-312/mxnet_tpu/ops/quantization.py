"""INT8 quantized inference ops (ref: src/operator/quantization/ —
quantize_v2, dequantize, quantized_conv, quantized_fully_connected,
quantized_pooling).

Design divergence from the reference (documented in docs/divergences.md):
the reference threads (min, max) range pairs through every quantized op;
here quantized tensors travel with a *scale* (fp32, per-tensor for
activations, per-output-channel for weights) and the integer compute is a
real int8 ``lax.dot_general`` / ``lax.conv_general_dilated`` with
``preferred_element_type=int32`` — the MXU's native int8 path on TPU.
Symmetric (zero-point-free) quantization, matching the reference's
``quantized_dtype='int8'`` mode.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import OpParam, register


def _symmetric_scale(amax):
    return jnp.maximum(amax, 1e-12) / 127.0


def quantize_array(x, amax=None, channel_axis=None):
    """fp -> (int8, fp32 scale). Per-tensor, or per-channel along
    ``channel_axis`` (weights)."""
    x = jnp.asarray(x)
    if amax is None:
        if channel_axis is None:
            amax = jnp.max(jnp.abs(x))
        else:
            axes = tuple(i for i in range(x.ndim) if i != channel_axis)
            amax = jnp.max(jnp.abs(x), axis=axes)
    scale = _symmetric_scale(jnp.asarray(amax, jnp.float32))
    if channel_axis is None:
        q = x / scale
    else:
        bshape = [1] * x.ndim
        bshape[channel_axis] = -1
        q = x / scale.reshape(bshape)
    q = jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8)
    return q, scale


@register("_contrib_quantize_v2", num_outputs=2,
          params=[OpParam("min_calib_range", float, None),
                  OpParam("max_calib_range", float, None)],
          differentiable=False,
          doc="fp32 -> (int8, scale). With calib ranges: static scale "
              "(ref: quantization/quantize_v2.cc); without: dynamic "
              "per-batch amax.")
def _quantize_v2(x, min_calib_range=None, max_calib_range=None):
    if min_calib_range is not None and max_calib_range is not None:
        amax = max(abs(float(min_calib_range)),
                   abs(float(max_calib_range)))
    else:
        amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return quantize_array(x.astype(jnp.float32), amax=amax)


@register("_contrib_dequantize", num_inputs=2, differentiable=False,
          doc="(int8, scale) -> fp32 (ref: quantization/dequantize.cc)")
def _dequantize(q, scale):
    return q.astype(jnp.float32) * scale


def _requantize(y, min_calib_range=None, max_calib_range=None):
    """fp32 -> (int8, scale): static scale from output calib ranges when
    given, else dynamic per-batch amax (ref: quantization/requantize.cc)."""
    if min_calib_range is not None and max_calib_range is not None:
        amax = max(abs(float(min_calib_range)), abs(float(max_calib_range)))
    else:
        amax = jnp.max(jnp.abs(y))
    return quantize_array(y, amax=amax)


def _n_out_from_type(params):
    return 2 if params.get("out_type") == "int8" else 1


@register("_contrib_quantized_fully_connected", num_inputs=-1,
          num_outputs=_n_out_from_type,
          params=[OpParam("num_hidden", int, None, required=True),
                  OpParam("no_bias", bool, False),
                  OpParam("flatten", bool, True),
                  OpParam("out_type", str, "float32"),
                  OpParam("min_calib_range", float, None),
                  OpParam("max_calib_range", float, None)],
          differentiable=False,
          doc="int8 x int8 -> int32 GEMM, rescaled to fp32 — or, with "
              "out_type='int8', requantized to (int8, scale) so chains "
              "stay int8 (ref: quantization/quantized_fully_connected.cc "
              "+ the mkldnn int8 subgraph fusion). Inputs: x_q int8, "
              "w_q int8 [num_hidden, K], x_scale, w_scale [num_hidden], "
              "(bias fp32)")
def _quantized_fc(xq, wq, x_scale, w_scale, *bias, num_hidden=None,
                  no_bias=False, flatten=True, out_type="float32",
                  min_calib_range=None, max_calib_range=None):
    if flatten:
        xq = xq.reshape(xq.shape[0], -1)
    y32 = lax.dot_general(xq, wq, (((xq.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    y = y32.astype(jnp.float32) * (x_scale * w_scale)
    if not no_bias and bias:
        y = y + bias[0]
    if out_type == "int8":
        return _requantize(y, min_calib_range, max_calib_range)
    return y


@register("_contrib_quantized_conv", num_inputs=-1,
          num_outputs=_n_out_from_type,
          params=[OpParam("kernel", tuple, None, required=True),
                  OpParam("stride", tuple, None),
                  OpParam("dilate", tuple, None),
                  OpParam("pad", tuple, None),
                  OpParam("num_filter", int, None, required=True),
                  OpParam("num_group", int, 1),
                  OpParam("no_bias", bool, False),
                  OpParam("layout", str, None),
                  OpParam("out_type", str, "float32"),
                  OpParam("min_calib_range", float, None),
                  OpParam("max_calib_range", float, None)],
          differentiable=False,
          doc="int8 conv accumulated in int32, rescaled to fp32 — or, "
              "with out_type='int8', requantized to (int8, scale) so "
              "residual chains stay int8 (ref: quantization/"
              "quantized_conv.cc + mkldnn int8 subgraphs). Inputs: x_q, "
              "w_q, x_scale, w_scale [num_filter], (bias fp32)")
def _quantized_conv(xq, wq, x_scale, w_scale, *bias, kernel=None,
                    stride=None, dilate=None, pad=None, num_filter=None,
                    num_group=1, no_bias=False, layout=None,
                    out_type="float32", min_calib_range=None,
                    max_calib_range=None):
    nd_ = len(kernel)
    stride = tuple(stride or (1,) * nd_)
    dilate = tuple(dilate or (1,) * nd_)
    pad = tuple(pad or (0,) * nd_)
    dims = {3: ("NCW", "OIW", "NCW"), 4: ("NCHW", "OIHW", "NCHW"),
            5: ("NCDHW", "OIDHW", "NCDHW")}[xq.ndim]
    dn = lax.conv_dimension_numbers(xq.shape, wq.shape, dims)
    y32 = lax.conv_general_dilated(
        xq, wq, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    bshape = (1, -1) + (1,) * nd_
    y = y32.astype(jnp.float32) * (x_scale
                                   * w_scale.reshape(bshape))
    if not no_bias and bias:
        y = y + bias[0].reshape(bshape)
    if out_type == "int8":
        return _requantize(y, min_calib_range, max_calib_range)
    return y


@register("_contrib_quantized_elemwise_add", num_inputs=4, num_outputs=2,
          differentiable=False,
          doc="(a_q, a_scale, b_q, b_scale) -> (int8, scale): the "
              "residual add of an int8 chain. Output scale a_s + b_s is "
              "clip-free by construction (|sum| <= 127(a_s+b_s)); one "
              "int16 add + rescale, no fp32 tensor materialized "
              "(ref: mkldnn quantized_elemwise_add)")
def _quantized_elemwise_add(aq, a_scale, bq, b_scale):
    out_scale = a_scale + b_scale
    af = aq.astype(jnp.float32) * (a_scale / out_scale)
    bf = bq.astype(jnp.float32) * (b_scale / out_scale)
    q = jnp.clip(jnp.round(af + bf), -127, 127).astype(jnp.int8)
    return q, out_scale


@register("_contrib_quantized_act", num_inputs=2, num_outputs=2,
          params=[OpParam("act_type", str, "relu")],
          differentiable=False,
          doc="ReLU directly on int8 (symmetric zero point: max(q, 0)); "
              "scale passes through (ref: mkldnn int8 conv+relu fusion)")
def _quantized_act(xq, scale, act_type="relu"):
    if act_type != "relu":
        raise MXNetError(f"quantized_act supports relu only, "
                         f"got {act_type!r}")
    return jnp.maximum(xq, jnp.int8(0)), scale


@register("_contrib_quantized_concat", num_inputs=-1, num_outputs=2,
          params=[OpParam("num_args", int, None, required=True),
                  OpParam("dim", int, 1)],
          differentiable=False,
          doc="Concat int8 tensors: (q1..qn, s1..sn) -> (int8, scale). "
              "Common scale = max(s_i); inputs requantized onto it "
              "(ref: quantization/quantized_concat.cc)")
def _quantized_concat(*args, num_args=None, dim=1):
    qs, scales = args[:num_args], args[num_args:]
    out_scale = scales[0]
    for s in scales[1:]:
        out_scale = jnp.maximum(out_scale, s)
    parts = []
    for q, s in zip(qs, scales):
        ratio = s / out_scale
        parts.append(jnp.clip(jnp.round(q.astype(jnp.float32) * ratio),
                              -127, 127).astype(jnp.int8))
    return jnp.concatenate(parts, axis=dim), out_scale


@register("_contrib_quantized_pooling", num_inputs=2, num_outputs=2,
          params=[OpParam("kernel", tuple, ()),
                  OpParam("pool_type", str, "max"),
                  OpParam("global_pool", bool, False),
                  OpParam("stride", tuple, None),
                  OpParam("pad", tuple, None),
                  OpParam("pooling_convention", str, "valid")],
          differentiable=False,
          doc="Pooling directly on int8 data; scale passes through "
              "(ref: quantization/quantized_pooling.cc)")
def _quantized_pooling(xq, scale, kernel=(), pool_type="max",
                       global_pool=False, stride=None, pad=None,
                       pooling_convention="valid"):
    nd_ = xq.ndim - 2
    if global_pool:
        axes = tuple(range(2, xq.ndim))
        if pool_type == "max":
            return jnp.max(xq, axis=axes, keepdims=True), scale
        s = jnp.mean(xq.astype(jnp.int32), axis=axes, keepdims=True)
        return jnp.clip(jnp.round(s), -127, 127).astype(jnp.int8), scale
    stride = tuple(stride or (1,) * nd_)
    pad = tuple(pad or (0,) * nd_)
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + stride
    pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]
    if pool_type == "max":
        out = lax.reduce_window(xq, jnp.int8(-128), lax.max, window,
                                strides, pads)
        return out, scale
    if pool_type != "avg":
        raise MXNetError(f"quantized_pooling: pool_type {pool_type!r}")
    s = lax.reduce_window(xq.astype(jnp.int32), jnp.int32(0), lax.add,
                          window, strides, pads)
    import numpy as _np
    denom = int(_np.prod(kernel))
    out = jnp.clip(jnp.round(s / denom), -127, 127).astype(jnp.int8)
    return out, scale

"""Fused optimizer-update operators.

TPU-native equivalent of ``src/operator/optimizer_op.cc`` — the reference's
in-place fused updates (`sgd_update`, `adam_update`, `lamb_*`, `mp_*` mixed
precision). Here each update is a pure function; "in-place" happens through
handle rebinding at the NDArray layer and buffer donation under jit, so XLA
emits a true in-place fused kernel (SURVEY §7 translation table row 4).

All ops mirror the reference's semantics: `rescale_grad`, `clip_gradient`,
`wd` applied as in MXNet (wd couples into the gradient for SGD/Adam;
`adamw`/`lamb` decouple it).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import OpParam, register


def _common_params():
    return [OpParam("lr", float, None, required=True),
            OpParam("wd", float, 0.0),
            OpParam("rescale_grad", float, 1.0),
            OpParam("clip_gradient", float, -1.0)]


def _prep_grad(weight, grad, rescale_grad, clip_gradient, wd=None):
    grad = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    if wd:
        grad = grad + wd * weight.astype(jnp.float32)
    return grad


@register("sgd_update", num_inputs=2, params=_common_params(),
          differentiable=False,
          doc="w -= lr * (rescale*clip(grad) + wd*w) (ref: optimizer_op.cc)")
def _sgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient, wd)
    return (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)


@register("sgd_mom_update", num_inputs=3, num_outputs=2,
          params=_common_params() + [OpParam("momentum", float, 0.0),
                                     OpParam("lazy_update", bool, True)],
          differentiable=False,
          doc="Momentum SGD; returns (weight, mom) — the reference mutates "
              "mom in place (ref: optimizer_op.cc sgd_mom_update)")
def _sgd_mom_update(weight, grad, mom, lr=None, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, momentum=0.0, lazy_update=True):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient, wd)
    mom_new = momentum * mom.astype(jnp.float32) - lr * g
    w_new = weight.astype(jnp.float32) + mom_new
    return w_new.astype(weight.dtype), mom_new.astype(mom.dtype)


@register("nag_mom_update", num_inputs=3, num_outputs=2,
          params=_common_params() + [OpParam("momentum", float, 0.0)],
          differentiable=False,
          doc="Nesterov momentum (ref: optimizer_op.cc nag_mom_update)")
def _nag_mom_update(weight, grad, mom, lr=None, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, momentum=0.0):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient, wd)
    mom_new = momentum * mom.astype(jnp.float32) + g
    w_new = weight.astype(jnp.float32) - lr * (g + momentum * mom_new)
    return w_new.astype(weight.dtype), mom_new.astype(mom.dtype)


@register("adam_update", num_inputs=4, num_outputs=3,
          params=_common_params() + [OpParam("beta1", float, 0.9),
                                     OpParam("beta2", float, 0.999),
                                     OpParam("epsilon", float, 1e-8),
                                     OpParam("lazy_update", bool, True)],
          differentiable=False,
          doc="Adam; returns (weight, mean, var) "
              "(ref: optimizer_op.cc adam_update). Note: like the reference, "
              "bias correction is folded into lr by the Optimizer class.")
def _adam_update(weight, grad, mean, var, lr=None, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient, wd)
    mean_new = beta1 * mean.astype(jnp.float32) + (1 - beta1) * g
    var_new = beta2 * var.astype(jnp.float32) + (1 - beta2) * jnp.square(g)
    w_new = weight.astype(jnp.float32) - lr * mean_new / (jnp.sqrt(var_new) + epsilon)
    return (w_new.astype(weight.dtype), mean_new.astype(mean.dtype),
            var_new.astype(var.dtype))


@register("adamw_update", num_inputs=4, num_outputs=3,
          params=_common_params() + [OpParam("beta1", float, 0.9),
                                     OpParam("beta2", float, 0.999),
                                     OpParam("epsilon", float, 1e-8),
                                     OpParam("eta", float, 1.0)],
          differentiable=False,
          doc="AdamW: decoupled weight decay "
              "(ref: src/operator/contrib/adamw.cc)")
def _adamw_update(weight, grad, mean, var, lr=None, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, beta1=0.9, beta2=0.999, epsilon=1e-8,
                  eta=1.0):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient, wd=None)
    mean_new = beta1 * mean.astype(jnp.float32) + (1 - beta1) * g
    var_new = beta2 * var.astype(jnp.float32) + (1 - beta2) * jnp.square(g)
    w32 = weight.astype(jnp.float32)
    upd = mean_new / (jnp.sqrt(var_new) + epsilon) + wd * w32
    w_new = w32 - eta * lr * upd
    return (w_new.astype(weight.dtype), mean_new.astype(mean.dtype),
            var_new.astype(var.dtype))


@register("lamb_update_phase1", num_inputs=4, num_outputs=3,
          params=[OpParam("beta1", float, 0.9), OpParam("beta2", float, 0.999),
                  OpParam("epsilon", float, 1e-6), OpParam("t", int, 1),
                  OpParam("bias_correction", bool, True),
                  OpParam("wd", float, 0.0),
                  OpParam("rescale_grad", float, 1.0),
                  OpParam("clip_gradient", float, -1.0)],
          differentiable=False,
          doc="LAMB phase 1: raw update direction g' "
              "(ref: optimizer_op.cc lamb_update_phase1)")
def _lamb_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 t=1, bias_correction=True, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient, wd=None)
    mean_new = beta1 * mean.astype(jnp.float32) + (1 - beta1) * g
    var_new = beta2 * var.astype(jnp.float32) + (1 - beta2) * jnp.square(g)
    m_hat, v_hat = mean_new, var_new
    if bias_correction:
        m_hat = mean_new / (1 - beta1 ** t)
        v_hat = var_new / (1 - beta2 ** t)
    gp = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * weight.astype(jnp.float32)
    return gp, mean_new.astype(mean.dtype), var_new.astype(var.dtype)


@register("lamb_update_phase2", num_inputs=4,
          params=[OpParam("lr", float, None, required=True),
                  OpParam("lower_bound", float, -1.0),
                  OpParam("upper_bound", float, -1.0)],
          differentiable=False,
          doc="LAMB phase 2: trust-ratio scaling "
              "(ref: optimizer_op.cc lamb_update_phase2)")
def _lamb_phase2(weight, g, r1, r2, lr=None, lower_bound=-1.0, upper_bound=-1.0):
    r1 = jnp.where(lower_bound > 0, jnp.maximum(r1, lower_bound), r1)
    r1 = jnp.where(upper_bound > 0, jnp.minimum(r1, upper_bound), r1)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2, 1.0)
    w_new = weight.astype(jnp.float32) - lr * ratio * g
    return w_new.astype(weight.dtype)


@register("rmsprop_update", num_inputs=3, num_outputs=2,
          params=_common_params() + [OpParam("gamma1", float, 0.95),
                                     OpParam("epsilon", float, 1e-8)],
          differentiable=False, doc="ref: optimizer_op.cc rmsprop_update")
def _rmsprop_update(weight, grad, n, lr=None, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, gamma1=0.95, epsilon=1e-8):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient, wd)
    n_new = gamma1 * n.astype(jnp.float32) + (1 - gamma1) * jnp.square(g)
    w_new = weight.astype(jnp.float32) - lr * g / (jnp.sqrt(n_new) + epsilon)
    return w_new.astype(weight.dtype), n_new.astype(n.dtype)


@register("ftrl_update", num_inputs=4, num_outputs=3,
          params=_common_params() + [OpParam("lamda1", float, 0.01),
                                     OpParam("beta", float, 1.0)],
          differentiable=False, doc="ref: optimizer_op.cc ftrl_update")
def _ftrl_update(weight, grad, z, n, lr=None, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0, lamda1=0.01, beta=1.0):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient)
    n32, z32 = n.astype(jnp.float32), z.astype(jnp.float32)
    n_new = n32 + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n32)) / lr
    z_new = z32 + g - sigma * weight.astype(jnp.float32)
    w_new = jnp.where(
        jnp.abs(z_new) <= lamda1, jnp.zeros_like(z_new),
        -(z_new - jnp.sign(z_new) * lamda1)
        / ((beta + jnp.sqrt(n_new)) / lr + wd))
    return (w_new.astype(weight.dtype), z_new.astype(z.dtype),
            n_new.astype(n.dtype))


@register("adagrad_update", num_inputs=3, num_outputs=2,
          params=_common_params() + [OpParam("epsilon", float, 1e-7)],
          differentiable=False,
          doc="ref: src/operator/optimizer_op.cc / contrib _sparse_adagrad")
def _adagrad_update(weight, grad, history, lr=None, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, epsilon=1e-7):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient, wd)
    h_new = history.astype(jnp.float32) + jnp.square(g)
    w_new = weight.astype(jnp.float32) - lr * g / (jnp.sqrt(h_new) + epsilon)
    return w_new.astype(weight.dtype), h_new.astype(history.dtype)


@register("signsgd_update", num_inputs=2, params=_common_params(),
          differentiable=False, doc="ref: optimizer_op.cc signsgd_update")
def _signsgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = _prep_grad(weight, grad, rescale_grad, clip_gradient, wd)
    return (weight.astype(jnp.float32) - lr * jnp.sign(g)).astype(weight.dtype)


# Mixed-precision (mp_*) variants: bf16/fp16 weights with fp32 master copy
# (ref: optimizer_op.cc mp_sgd_update / mp_sgd_mom_update / mp_adam-like)
@register("mp_sgd_update", num_inputs=3, num_outputs=2,
          params=_common_params(), differentiable=False,
          doc="Low-precision weight + fp32 master (ref: mp_sgd_update)")
def _mp_sgd_update(weight, grad, weight32, lr=None, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep_grad(weight32, grad, rescale_grad, clip_gradient, wd)
    w32_new = weight32 - lr * g
    return w32_new.astype(weight.dtype), w32_new


@register("mp_sgd_mom_update", num_inputs=4, num_outputs=3,
          params=_common_params() + [OpParam("momentum", float, 0.0)],
          differentiable=False, doc="ref: mp_sgd_mom_update")
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=None, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, momentum=0.0):
    g = _prep_grad(weight32, grad, rescale_grad, clip_gradient, wd)
    mom_new = momentum * mom - lr * g
    w32_new = weight32 + mom_new
    return w32_new.astype(weight.dtype), mom_new, w32_new


# multi-tensor fused updates (ref: optimizer_op.cc multi_sgd_update etc.) are
# realized at the Trainer level: all per-parameter updates execute inside one
# jitted step, which XLA fuses — the explicit multi_* ops become unnecessary.

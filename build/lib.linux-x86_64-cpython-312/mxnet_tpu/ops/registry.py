"""Typed operator registry.

Replaces two reference mechanisms with one TPU-native one:

- the NNVM op registry (``nnvm::Op`` with FCompute/FInferShape/FInferType
  attributes, ref: include/mxnet/op_attr_types.h): here an ``Operator`` holds a
  pure jax function; shape/dtype inference falls out of ``jax.eval_shape`` so
  no per-op inference rules are needed;
- ``dmlc::Parameter`` CRTP hyperparameter structs (ref:
  3rdparty/dmlc-core/include/dmlc/parameter.h), whose introspection the
  reference uses to code-generate Python signatures/docstrings (SURVEY §5.6
  calls this load-bearing): here ``OpParam`` rows serve the same role and
  drive wrapper generation for both ``mx.nd`` and ``mx.sym``.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..base import MXNetError

__all__ = ["OpParam", "Operator", "register", "alias", "get", "list_ops"]

_REGISTRY: Dict[str, "Operator"] = {}


@dataclass
class OpParam:
    """One hyperparameter of an op (dmlc::Parameter field analog)."""
    name: str
    type: Any = None            # python type or callable coercer
    default: Any = None
    required: bool = False
    doc: str = ""

    def coerce(self, value):
        if value is None:
            return None
        typ = self.type
        if typ is None or isinstance(value, bool) and typ is bool:
            return value
        if typ is tuple:
            return _as_tuple(value)
        if typ is bool:
            if isinstance(value, str):
                return value.lower() in ("1", "true", "yes")
            return bool(value)
        if typ in (int, float, str):
            return typ(value)
        if callable(typ):
            return typ(value)
        return value


def _as_tuple(value):
    """Accept tuples, lists, ints, and the reference's string shapes '(2, 2)'."""
    if isinstance(value, str):
        value = ast.literal_eval(value)
    if isinstance(value, (int,)):
        return (value,)
    return tuple(value)


@dataclass
class Operator:
    """A registered operator: a pure function on jax arrays.

    ``fn(*arrays, **params) -> array | tuple`` must be jax-traceable
    (no data-dependent Python control flow), which makes every op usable
    eagerly (mx.nd), under jit (hybridize/CachedOp), and in symbolic graphs
    (mx.sym) from a single definition.
    """
    name: str
    fn: Callable
    num_inputs: int = 1          # -1 = variadic
    num_outputs: int = 1
    params: List[OpParam] = field(default_factory=list)
    doc: str = ""
    differentiable: bool = True
    aliases: List[str] = field(default_factory=list)
    ref: str = ""                # reference file/symbol this op mirrors
    needs_rng: bool = False      # dispatch passes a PRNG key as `rng=` kwarg
                                 # (replaces the reference's ResourceRequest::kRandom)
    needs_mode: bool = False     # dispatch passes `training=` from autograd state
    allow_unknown_params: bool = False   # Custom op forwards user kwargs

    def coerce_params(self, kwargs: dict) -> dict:
        spec = {p.name: p for p in self.params}
        out = {}
        for key, val in kwargs.items():
            if key in spec:
                out[key] = spec[key].coerce(val)
            elif self.allow_unknown_params:
                out[key] = val
            else:
                # tolerate unknown kwargs the way generated wrappers do not:
                # raise, to catch typos early
                raise MXNetError(f"op {self.name!r}: unknown parameter {key!r}. "
                                 f"Known: {sorted(spec)}")
        for p in self.params:
            if p.required and p.name not in out:
                raise MXNetError(f"op {self.name!r}: missing required "
                                 f"parameter {p.name!r}")
            if p.name not in out:
                out[p.name] = p.default
        return out

    def signature_doc(self) -> str:
        lines = [self.doc or self.name, "", "Parameters", "----------"]
        for p in self.params:
            typename = getattr(p.type, "__name__", str(p.type))
            dflt = "required" if p.required else f"default={p.default!r}"
            lines.append(f"{p.name} : {typename}, {dflt}")
            if p.doc:
                lines.append(f"    {p.doc}")
        if self.ref:
            lines += ["", f"Reference: {self.ref}"]
        return "\n".join(lines)


def register(name: str, *, num_inputs: int = 1, num_outputs: int = 1,
             params: Optional[Sequence[OpParam]] = None, doc: str = "",
             differentiable: bool = True, aliases: Sequence[str] = (),
             ref: str = "", needs_rng: bool = False, needs_mode: bool = False):
    """Decorator registering ``fn`` as operator ``name``."""
    def deco(fn):
        op = Operator(name=name, fn=fn, num_inputs=num_inputs,
                      num_outputs=num_outputs, params=list(params or []),
                      doc=doc or (fn.__doc__ or ""), differentiable=differentiable,
                      aliases=list(aliases), ref=ref,
                      needs_rng=needs_rng, needs_mode=needs_mode)
        if name in _REGISTRY:
            raise MXNetError(f"duplicate op registration: {name}")
        _REGISTRY[name] = op
        for a in op.aliases:
            _REGISTRY[a] = op
        return fn
    return deco


def alias(existing: str, *names: str):
    op = get(existing)
    for n in names:
        _REGISTRY[n] = op
        op.aliases.append(n)


def get(name: str) -> Operator:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered "
                         f"({len(_REGISTRY)} ops known)") from None


def list_ops() -> List[str]:
    """ref: MXListAllOpNames — drives wrapper generation."""
    return sorted(set(_REGISTRY))

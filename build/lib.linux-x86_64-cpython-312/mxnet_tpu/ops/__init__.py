"""Operator library.

The TPU-native equivalent of the reference's ``src/operator/`` (~1000 C++/CUDA
ops registered through NNVM, ref: include/mxnet/op_attr_types.h NNVM_REGISTER_OP)
plus mshadow. Here each operator is a *pure function on jax arrays* registered
in a typed registry (``registry.py``); XLA plays the role of mshadow's
expression compiler and of the cuDNN dispatch layer, and Pallas kernels slot in
for the few genuinely custom kernels. Python-facing namespaces (``mx.nd``,
``mx.sym``) are generated from this registry exactly like the reference
generates them from the C op registry (ref: python/mxnet/ndarray/register.py).
"""
from . import registry
from .registry import register, get, list_ops, Operator, OpParam

# Import op definition modules for their registration side effects, mirroring
# the reference's static registration of src/operator/** at library load.
from . import tensor          # ref: src/operator/tensor/
from . import elemwise        # ref: src/operator/tensor/elemwise_*
from . import nn              # ref: src/operator/nn/
from . import random          # ref: src/operator/random/
from . import optimizer_op    # ref: src/operator/optimizer_op.cc
from . import contrib         # ref: src/operator/contrib/
from . import quantization    # ref: src/operator/quantization/
from . import sequence        # ref: src/operator/sequence_*.cc

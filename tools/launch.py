#!/usr/bin/env python
"""Wrapper: the implementation lives in mxnet_tpu.tools.launch (installed as a
console script); this file keeps the reference's `python tools/launch.py ...`
invocation shape."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from mxnet_tpu.tools.launch import main

if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Wrapper: the implementation lives in mxnet_tpu.tools.im2rec (installed as a
console script); this file keeps the reference's `python tools/im2rec.py ...`
invocation shape."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from mxnet_tpu.tools.im2rec import main

if __name__ == "__main__":
    sys.exit(main())

"""mxnet_tpu.serving — the dynamic-batching inference subsystem.

Covers the acceptance criteria of the serving story (docs/serving.md):
bounded compiles under mixed-shape traffic (bucket grid + predictor
cache), explicit load-shedding under a flooded queue, deadlines honored
at dequeue and post-batch, transient-device retry, hot-reload from the
newest *valid* committed checkpoint step with a chaos-injected torn
checkpoint falling back cleanly (zero corrupted responses), legacy
flag-0 ``.params`` hot-reload parity, the journal/doctor reporting
surface, and the stdlib building blocks (BucketGrid, batcher,
PredictorCache LRU, metric.LatencySummary).

The ``smoke`` tests run in CI tier 0.5 (ci/run_tests.sh); the soak and
subprocess CLI tests are marked ``slow``.
"""
import json
import os
import struct
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.diagnostics.journal import reset_journal
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.block import HybridBlock
from mxnet_tpu.metric import LatencySummary
from mxnet_tpu.resilience import commit
from mxnet_tpu.serving import (BucketGrid, DeadlineExceeded, ParamStore,
                               PredictorCache, RequestCancelled,
                               RequestError, Server, ServerConfig,
                               ServerOverloaded, ServerStopped,
                               serving_report)
from mxnet_tpu.serving.batcher import Request, drop_expired, take_batch
from mxnet_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def journal_file(tmp_path):
    """Route the process journal to a file for the duration (serving
    records are asserted against it), restoring stderr after."""
    path = str(tmp_path / "journal.jsonl")
    reset_journal(path)
    try:
        yield path
    finally:
        reset_journal("stderr")


def _records(path, kind=None):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


class Scale(HybridBlock):
    """y = x * w with a scalar weight — shape-agnostic, so one block
    serves every bucket; padding-exact (pad rows/dims come back as
    pad * w and are cropped)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.w = self.params.get("w", shape=(1,), init="ones")

    def hybrid_forward(self, F, x, w):
        return x * w


class Gated(HybridBlock):
    """Blocks its (host-side) trace until the test releases the gate —
    the deterministic stand-in for a slow compile / slow device."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.gate = threading.Event()
        self.entered = threading.Event()

    def hybrid_forward(self, F, x):
        self.entered.set()
        assert self.gate.wait(timeout=60), "test never released the gate"
        return x * 2.0


def _commit_scale(root, step, value, fname="net.params"):
    stage = commit.prepare_stage(root, step)
    nd.save(os.path.join(stage, fname),
            {"w": nd.array(np.asarray([value], np.float32))})
    return commit.finalize(root, step)


# -- stdlib building blocks --------------------------------------------------

def test_bucket_grid_rounding_reject_and_bound():
    g = BucketGrid(max_batch=8, dim_buckets={0: (4, 8, 12)})
    assert g.batch_buckets == (1, 2, 4, 8)
    assert g.batch_bucket(3) == 4 and g.batch_bucket(8) == 8
    assert g.batch_bucket(9) is None
    assert g.feature_key((3,)) == (4,)
    assert g.feature_key((12,)) == (12,)
    assert g.feature_key((13,)) is None          # oversized: reject
    assert g.feature_key((5, 7)) == (8, 7)       # axis 1 unbucketed
    assert g.grid_bound() == 4 * 3
    waste = BucketGrid.pad_waste(1, 4, [(4,)], (4,))
    assert waste == 0.75                          # 3 of 4 rows are pad


def test_bucket_grid_validation():
    with pytest.raises(ValueError):
        BucketGrid(batch_buckets=(0, 2))
    with pytest.raises(ValueError):
        BucketGrid(dim_buckets={0: ()})


def test_take_batch_groups_by_key_fifo():
    g = BucketGrid(max_batch=2)
    reqs = [Request(None, (4,), (4,)), Request(None, (8,), (8,)),
            Request(None, (3,), (4,)), Request(None, (2,), (4,))]
    pending = list(reqs)
    batch, bucket, key = take_batch(pending, g)
    assert batch == [reqs[0], reqs[2]] and bucket == 2 and key == (4,)
    assert pending == [reqs[1], reqs[3]]          # FIFO preserved
    batch, bucket, key = take_batch(pending, g)
    assert batch == [reqs[1]] and bucket == 1 and key == (8,)


def test_drop_expired_reports_and_keeps_order():
    fresh = Request(None, (4,), (4,), deadline_s=100)
    stale = Request(None, (4,), (4,), deadline_s=0.0001)
    time.sleep(0.01)
    dropped = []
    pending = [stale, fresh]
    drop_expired(pending, dropped.append)
    assert pending == [fresh] and dropped == [stale]


def test_latency_summary_exact_and_bounded():
    s = LatencySummary(reservoir_size=64)
    for v in range(1, 101):
        s.observe(float(v))
    out = s.summary()
    assert out["count"] == 100 and out["min"] == 1.0 and out["max"] == 100.0
    assert out["mean"] == 50.5
    assert len(s._buf) == 64                      # bounded reservoir
    # exact percentiles when the stream fits the reservoir
    s2 = LatencySummary(reservoir_size=1000)
    for v in range(1, 101):
        s2.observe(float(v))
    assert s2.percentile(50) == 50.0
    assert s2.percentile(95) == 95.0
    assert s2.percentile(99) == 99.0
    empty = LatencySummary().summary()
    assert empty["count"] == 0 and empty["p99"] is None


def test_predictor_cache_lru_bound_and_counters():
    cache = PredictorCache(max_entries=2)
    built = []
    for key in ("a", "b", "a", "c", "a"):
        cache.get(key, lambda k=key: built.append(k) or k)
    st = cache.stats()
    # a,b,c built once each ('a' stays hot); 'b' evicted by 'c'
    assert built == ["a", "b", "c"]
    assert st["misses"] == 3 and st["hits"] == 2 and st["evictions"] == 1
    assert len(cache) == 2


# -- the serving smoke (CI tier 0.5) -----------------------------------------

def test_serving_smoke_50_requests_reject_and_clean_shutdown(journal_file):
    """50 mixed requests through a real server thread, one oversized-
    shape reject, compile count within the grid bound, clean drain."""
    net = nn.Dense(3, in_units=4)
    net.initialize()
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    cfg = ServerConfig(max_batch=4, window_ms=2.0, max_queue=64,
                       dim_buckets={0: (4,)})
    server = Server(net, config=cfg).start()
    try:
        with pytest.raises(RequestError, match="exceeds the bucket grid"):
            server.submit(np.zeros(9, np.float32))   # oversized: reject

        xs = [np.random.randn(4).astype(np.float32) for _ in range(50)]
        resps = {}

        def client(lo, hi):
            for i in range(lo, hi):
                resps[i] = server.submit(xs[i])

        threads = [threading.Thread(target=client, args=(lo, lo + 10))
                   for lo in range(0, 50, 10)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        for i in range(50):
            got = np.asarray(resps[i].result(timeout_s=30))
            np.testing.assert_allclose(got, xs[i] @ w.T + b, atol=1e-5)
    finally:
        server.stop(timeout_s=30)
    st = server.stats()
    assert st["served"] == 50 and st["rejected_shape"] == 1
    assert st["cache"]["misses"] <= server.grid.grid_bound() == 3
    assert not server._worker                     # joined and cleared
    kinds = {r["kind"] for r in _records(journal_file)}
    assert {"serving_start", "serving_batch", "serving_reject",
            "serving_stop"} <= kinds


def test_serving_smoke_compile_count_bounded_100_mixed_shapes(journal_file):
    """The tentpole bound: 100 requests over 12 distinct feature shapes
    and mixed coalescing — compiles (cache misses) never exceed the
    bucket-grid size."""
    net = Scale()
    net.initialize()
    cfg = ServerConfig(max_batch=4, window_ms=1.0, max_queue=256,
                       dim_buckets={0: (4, 8, 12)})
    server = Server(net, config=cfg).start()
    try:
        resps = []
        for i in range(100):
            d = (i % 12) + 1
            x = np.arange(d, dtype=np.float32)
            resps.append((x, server.submit(x)))
        for x, r in resps:
            got = np.asarray(r.result(timeout_s=30))
            np.testing.assert_allclose(got, x, atol=1e-6)  # w == 1, cropped
    finally:
        server.stop(timeout_s=30)
    st = server.stats()
    assert st["served"] == 100
    assert st["cache"]["misses"] <= server.grid.grid_bound() == 9
    fills = [r["fill"] for r in _records(journal_file, "serving_batch")]
    assert fills and all(0 < f <= 1 for f in fills)


# -- backpressure + deadlines ------------------------------------------------

def test_flooded_queue_sheds_with_server_overloaded(journal_file):
    """While the device is busy (gated build), the bounded queue fills
    and the NEXT submit sheds immediately — bounded latency, explicit
    signal, and the server recovers once the device frees up."""
    net = Gated()
    cfg = ServerConfig(max_batch=1, window_ms=1.0, max_queue=4)
    server = Server(net, config=cfg).start()
    try:
        first = server.submit(np.ones(4, np.float32))
        assert net.entered.wait(timeout=30)       # worker wedged in build
        backlog = [server.submit(np.ones(4, np.float32))
                   for _ in range(4)]             # fills the bounded queue
        with pytest.raises(ServerOverloaded):
            server.submit(np.ones(4, np.float32))
        assert server.stats()["shed"] == 1
    finally:
        net.gate.set()
        server.stop(timeout_s=30)
    for r in [first] + backlog:
        np.testing.assert_allclose(np.asarray(r.result(timeout_s=30)),
                                   np.ones(4) * 2.0)
    shed = _records(journal_file, "serving_shed")
    assert len(shed) == 1 and shed[0]["limit"] == 4


def test_deadline_honored_at_dequeue(journal_file):
    """A request whose deadline passed while queued is dropped at
    dequeue — it must not waste a batch slot."""
    net = Gated()
    cfg = ServerConfig(max_batch=1, window_ms=1.0, max_queue=8)
    server = Server(net, config=cfg).start()
    try:
        first = server.submit(np.ones(2, np.float32))     # wedges worker
        assert net.entered.wait(timeout=30)
        doomed = server.submit(np.ones(2, np.float32), deadline_ms=30)
        time.sleep(0.1)                                   # deadline passes
        net.gate.set()
        np.testing.assert_allclose(np.asarray(first.result(timeout_s=30)),
                                   np.ones(2) * 2.0)
        with pytest.raises(DeadlineExceeded) as exc:
            doomed.result(timeout_s=30)
        assert exc.value.stage == "dequeue"
    finally:
        net.gate.set()
        server.stop(timeout_s=30)
    assert server.stats()["deadline_miss_dequeue"] == 1
    recs = _records(journal_file, "serving_deadline_miss")
    assert recs and recs[0]["stage"] == "dequeue"


def test_deadline_honored_post_batch(journal_file):
    """A request that was fresh at dequeue but missed its deadline while
    the batch executed gets a post_batch DeadlineExceeded, not a
    silently-late success."""
    net = Gated()
    cfg = ServerConfig(max_batch=1, window_ms=1.0, max_queue=8)
    server = Server(net, config=cfg).start()
    try:
        resp = server.submit(np.ones(2, np.float32), deadline_ms=80)
        assert net.entered.wait(timeout=30)       # in-batch, pre-deadline
        time.sleep(0.2)                           # deadline passes mid-exec
        net.gate.set()
        with pytest.raises(DeadlineExceeded) as exc:
            resp.result(timeout_s=30)
        assert exc.value.stage == "post_batch"
    finally:
        net.gate.set()
        server.stop(timeout_s=30)
    assert server.stats()["deadline_miss_post_batch"] == 1


def test_transient_device_error_retried_then_fatal_is_structured():
    """OSError-class predictor failures ride resilience.retry; a
    non-transient failure fails the batch with a structured error and
    the server keeps serving."""
    net = Scale()
    net.initialize()
    cfg = ServerConfig(max_batch=2, window_ms=1.0, max_queue=8,
                       device_retries=2)
    server = Server(net, config=cfg).start()
    try:
        x = np.ones(3, np.float32)
        np.testing.assert_allclose(np.asarray(server.predict(x)), x)

        key = next(iter(server.cache._lru))
        real = server.cache._lru[key]
        calls = {"n": 0}

        class Flaky:
            def __call__(self, padded):
                calls["n"] += 1
                if calls["n"] <= 2:
                    raise OSError(5, "injected transient EIO")
                return real(padded)
        server.cache._lru[key] = Flaky()
        np.testing.assert_allclose(np.asarray(server.predict(x)), x)
        assert calls["n"] == 3                    # 2 transient + 1 success

        class Broken:
            def __call__(self, padded):
                raise ValueError("not transient")
        server.cache._lru[key] = Broken()
        with pytest.raises(RequestError, match="predictor failed"):
            server.predict(x)
        server.cache._lru[key] = real             # server still alive
        np.testing.assert_allclose(np.asarray(server.predict(x)), x)
    finally:
        server.stop(timeout_s=30)
    assert server.stats()["errors"] == 1


def test_stop_closes_admission_and_fails_stragglers_structured(
        journal_file):
    """The stop() drain race (docs/serving.md): a submit racing stop()
    must either be served, shed, or fail with a structured
    ServerStopped — NEVER ride out the caller's result timeout as a
    silently dropped request.  Admission closes before the drain
    deadline starts; stragglers are swept after the worker exits."""
    net = Scale()
    net.initialize()
    cfg = ServerConfig(max_batch=4, window_ms=1.0, max_queue=64)
    server = Server(net, config=cfg).start()
    x = np.ones(3, np.float32)
    server.predict(x)                    # warm: the race window is tight
    outcomes, bad = [], []
    go = threading.Event()

    def hammer():
        go.wait(timeout=10)
        while True:
            try:
                resp = server.submit(x)
            except ServerStopped:
                outcomes.append("stopped_at_submit")
                return
            except ServerOverloaded:
                outcomes.append("shed")
                continue
            try:
                # MUST resolve promptly: served or structured error —
                # a generic result-timeout here is the dropped-request
                # bug this test pins
                resp.result(timeout_s=5)
                outcomes.append("served")
            except ServerStopped:
                outcomes.append("stopped_in_queue")
            except DeadlineExceeded:
                outcomes.append("deadline")
            except RequestError as e:
                bad.append(repr(e))
                return

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    go.set()
    time.sleep(0.05)                     # overlap stop() with admission
    server.stop(timeout_s=30)
    for t in threads:
        t.join(timeout=30)
    assert not bad, f"unstructured outcomes: {bad[:3]}"
    assert "served" in outcomes
    assert any(o.startswith("stopped") for o in outcomes)
    # post-stop: admission stays closed, structurally
    with pytest.raises(ServerStopped):
        server.submit(x)
    assert server.stats()["rejected_stopped"] >= 1
    recs = _records(journal_file, "serving_stopped_reject")
    assert recs and all(r["stage"] in ("admission", "straggler", "stopped")
                        for r in recs)
    # and a restart reopens admission cleanly
    server.start()
    try:
        np.testing.assert_allclose(np.asarray(server.predict(x)), x)
    finally:
        server.stop(timeout_s=30)


def test_cancel_event_drops_request_at_dequeue(journal_file):
    """A request whose cancel event is set before dequeue resolves with
    RequestCancelled and never spends a batch slot (the hedging loser
    contract, serving/router.py)."""
    net = Gated()
    cfg = ServerConfig(max_batch=1, window_ms=1.0, max_queue=8)
    server = Server(net, config=cfg).start()
    try:
        first = server.submit(np.ones(2, np.float32))  # wedges worker
        assert net.entered.wait(timeout=30)
        cancel = threading.Event()
        loser = server.submit(np.ones(2, np.float32), cancel=cancel)
        cancel.set()
        net.gate.set()
        np.testing.assert_allclose(np.asarray(first.result(timeout_s=30)),
                                   np.ones(2) * 2.0)
        with pytest.raises(RequestCancelled):
            loser.result(timeout_s=30)
    finally:
        net.gate.set()
        server.stop(timeout_s=30)
    assert server.stats()["cancelled"] == 1
    assert _records(journal_file, "serving_cancelled")


# -- predictor-cache keying ---------------------------------------------------

def test_cache_keying_same_bucket_reuses_one_executable():
    """Two requests whose shapes fall in the same bucket must reuse ONE
    executable — proven via the cache counters."""
    net = Scale()
    net.initialize()
    cfg = ServerConfig(max_batch=1, window_ms=1.0,
                       dim_buckets={0: (4, 8)})
    server = Server(net, config=cfg).start()
    try:
        a = np.arange(3, dtype=np.float32)
        b = np.arange(4, dtype=np.float32)
        np.testing.assert_allclose(np.asarray(server.predict(a)), a)
        np.testing.assert_allclose(np.asarray(server.predict(b)), b)
    finally:
        server.stop(timeout_s=30)
    st = server.stats()["cache"]
    assert st["misses"] == 1 and st["hits"] == 1   # one compile, reused


# -- hot-reload ---------------------------------------------------------------

def test_hot_reload_mid_traffic_torn_checkpoint_falls_back(tmp_path,
                                                           journal_file):
    """The acceptance drill: traffic flows while a producer commits a
    torn checkpoint (chaos crash at the publish rename — the SIGTERM'd
    writer shape) and a committed-but-corrupt step; the server stays on
    the previous valid step with ZERO corrupted responses, then lands on
    the next valid step without draining."""
    root = str(tmp_path / "ckpt")
    _commit_scale(root, 1, 2.0)
    net = Scale()
    net.initialize()
    cfg = ServerConfig(max_batch=4, window_ms=1.0, max_queue=64,
                       reload_poll_s=0.0)
    server = Server(net, config=cfg, param_store=ParamStore(root)).start()
    x = np.ones(4, np.float32)
    seen, bad, stop = [], [], threading.Event()

    def client():
        while not stop.is_set():
            v = float(np.asarray(server.predict(x))[0])
            seen.append(v)
            if abs(v - 2.0) > 1e-6 and abs(v - 5.0) > 1e-6:
                bad.append(v)
            time.sleep(0.002)

    t = threading.Thread(target=client, daemon=True)
    try:
        assert server.stats()["params_step"] == 1
        t.start()
        # torn commit: the writer dies at the publish rename
        with faults.inject(faults.crash("publish")):
            with pytest.raises(faults.SimulatedCrash):
                _commit_scale(root, 2, 999.0)
        # committed-but-corrupt: bytes flipped between manifest and the
        # publish rename, so the step is NEVER visible in a valid state
        stage = commit.prepare_stage(root, 3)
        p = os.path.join(stage, "net.params")
        nd.save(p, {"w": nd.array(np.asarray([999.0], np.float32))})
        commit.write_manifest(stage, 3)
        raw = bytearray(open(p, "rb").read())
        raw[40] ^= 0xFF
        with open(p, "wb") as f:
            f.write(bytes(raw))
        os.rename(stage, commit.step_dir(root, 3))
        time.sleep(0.3)
        assert server.stats()["params_step"] == 1    # held the line
        _commit_scale(root, 4, 5.0)                  # next valid step
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                server.stats()["params_step"] != 4:
            time.sleep(0.02)
        assert server.stats()["params_step"] == 4
        time.sleep(0.1)
    finally:
        stop.set()
        t.join(timeout=10)
        server.stop(timeout_s=30)
    assert not bad, f"corrupted responses: {bad[:5]}"
    assert 2.0 in seen and 5.0 in seen               # both versions served
    fallbacks = _records(journal_file, "ckpt_fallback")
    assert {r["step"] for r in fallbacks} == {3}     # step 2 never visible
    reloads = _records(journal_file, "serving_reload")
    assert [r["step"] for r in reloads] == [1, 4]


def _write_legacy_params(path, name, arr):
    """Reference-era flag-0 container: no CRCs, no footer (the layout
    tests/test_checkpoint_atomic.py proves nd.load still accepts)."""
    from mxnet_tpu.ndarray.ndarray import _LIST_MAGIC, _ND_MAGIC
    with open(path, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", 1))
        f.write(struct.pack("<I", _ND_MAGIC))
        f.write(struct.pack("<I", arr.ndim))
        for s in arr.shape:
            f.write(struct.pack("<q", s))
        f.write(struct.pack("<ii", 1, 0))
        f.write(struct.pack("<i", 0))                # float32
        f.write(arr.tobytes())
        f.write(struct.pack("<Q", 1))
        b = name.encode()
        f.write(struct.pack("<Q", len(b)))
        f.write(b)


def test_legacy_flag0_params_hot_reload_identical_to_v3(tmp_path):
    """A legacy flag-0 .params checkpoint must hot-reload bit-identically
    to the v3 (CRC) container holding the same weights."""
    w = np.asarray([7.0], np.float32)
    x = np.arange(4, dtype=np.float32)
    outs = {}
    for fmt in ("v3", "legacy"):
        root = str(tmp_path / f"root_{fmt}")
        stage = commit.prepare_stage(root, 1)
        path = os.path.join(stage, "net.params")
        if fmt == "v3":
            nd.save(path, {"w": nd.array(w)})
        else:
            _write_legacy_params(path, "w", w)
        commit.finalize(root, 1)
        net = Scale()
        net.initialize()
        cfg = ServerConfig(max_batch=1, window_ms=1.0, reload_poll_s=0.0)
        server = Server(net, config=cfg,
                        param_store=ParamStore(root)).start()
        try:
            assert server.stats()["params_step"] == 1
            outs[fmt] = np.asarray(server.predict(x))
        finally:
            server.stop(timeout_s=30)
    np.testing.assert_array_equal(outs["v3"], outs["legacy"])
    np.testing.assert_allclose(outs["v3"], x * 7.0)


def test_reload_rejects_architecture_drift(tmp_path, journal_file):
    """A valid checkpoint whose shapes don't match the live block is
    refused atomically (no half-applied swap) and journaled."""
    root = str(tmp_path / "ckpt")
    stage = commit.prepare_stage(root, 1)
    nd.save(os.path.join(stage, "net.params"),
            {"w": nd.array(np.zeros((2, 2), np.float32))})
    commit.finalize(root, 1)
    net = Scale()
    net.initialize()
    cfg = ServerConfig(max_batch=1, window_ms=1.0, reload_poll_s=0.0)
    server = Server(net, config=cfg, param_store=ParamStore(root)).start()
    try:
        assert server.stats()["params_step"] is None
        x = np.ones(3, np.float32)
        np.testing.assert_allclose(np.asarray(server.predict(x)), x)
    finally:
        server.stop(timeout_s=30)
    recs = _records(journal_file, "serving_reload_failed")
    assert recs and recs[0]["step"] == 1


# -- reporting surface --------------------------------------------------------

def test_serving_report_summarizes_last_run(tmp_path, journal_file):
    net = Scale()
    net.initialize()
    cfg = ServerConfig(max_batch=2, window_ms=1.0, max_queue=2,
                       dim_buckets={0: (4,)})
    server = Server(net, config=cfg).start()
    try:
        for _ in range(6):
            server.predict(np.ones(4, np.float32))
        with pytest.raises(RequestError):
            server.submit(np.zeros(9, np.float32))    # reject record
    finally:
        server.stop(timeout_s=30)
    rep = serving_report(journal_file)
    assert rep["ok"] and rep["served"] == 6
    assert rep["batches"] >= 1 and rep["shed"] == 0
    assert rep["shed_rate"] == 0.0
    assert rep["rejected_shape"] == 1
    assert rep["compiles"] >= 1
    assert rep["cache_hit_rate"] is not None
    assert rep["deadline_miss_total"] == 0
    assert rep["clean_stop"] is True
    assert rep["last_batch"]["p50_ms"] is not None


def test_serving_report_excludes_post_batch_misses_from_served(tmp_path):
    """`served` counts delivered responses only: a post_batch deadline
    miss is inside the batch but got an error, and shed_rate is over
    everything offered."""
    path = str(tmp_path / "j.jsonl")
    recs = [
        {"kind": "serving_start"},
        {"kind": "serving_batch", "batch": 3, "delivered": 2,
         "fill": 0.75, "hits": 1, "misses": 1},
        {"kind": "serving_deadline_miss", "stage": "post_batch"},
        {"kind": "serving_deadline_miss", "stage": "dequeue"},
        {"kind": "serving_shed"},
        {"kind": "serving_stop", "stuck": False},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rep = serving_report(path)
    assert rep["served"] == 2
    assert rep["deadline_miss"] == {"dequeue": 1, "post_batch": 1}
    # offered = batch(3) + dequeue miss(1) + shed(1) = 5
    assert rep["shed_rate"] == round(1 / 5, 4)
    assert rep["clean_stop"] is True


def test_load_dict_handles_bare_arg_aux_named_params():
    """A parameter literally named 'aux' must survive the arg:/aux:
    prefix strip when mixed with prefixed keys (the hot-reload
    no-half-apply contract depends on it)."""
    class Odd(HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.w = self.params.get("w", shape=(1,), init="ones")
                self.aux = self.params.get("aux", shape=(1,), init="ones")

        def hybrid_forward(self, F, x, w, aux):
            return x * w + aux

    net = Odd()
    net.initialize()
    net.load_dict({"arg:w": nd.array(np.asarray([4.0], np.float32)),
                   "aux": nd.array(np.asarray([9.0], np.float32))})
    assert float(net.w.data().asnumpy()[0]) == 4.0
    assert float(net.aux.data().asnumpy()[0]) == 9.0


def test_serving_report_tolerates_junk_and_missing():
    assert serving_report("/nonexistent/journal.jsonl")["ok"] is False
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        f.write("not json\n{\"kind\": \"heartbeat\"}\n{tor")
        path = f.name
    try:
        rep = serving_report(path)
        assert rep["ok"] is False and "no serving records" in rep["error"]
    finally:
        os.unlink(path)


@pytest.mark.slow
def test_doctor_cli_serving_journal_section(tmp_path, journal_file):
    """End-to-end: a serving run's journal summarized by
    ``python -m mxnet_tpu.diagnostics doctor --serving-journal``."""
    import subprocess
    import sys
    net = Scale()
    net.initialize()
    server = Server(net, config=ServerConfig(max_batch=2,
                                             window_ms=1.0)).start()
    try:
        for _ in range(4):
            server.predict(np.ones(4, np.float32))
    finally:
        server.stop(timeout_s=30)
    reset_journal("stderr")          # release the file for the child
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.diagnostics", "doctor",
         "--serving-journal", journal_file, "--deadline", "120"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "MXNET_TPU_JOURNAL": "off"})
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    rep = json.loads(line)["serving"]
    assert rep["ok"] and rep["served"] == 4
    assert "shed-rate" in out.stderr


@pytest.mark.slow
def test_bench_cli_emits_artifact(tmp_path):
    """``python -m mxnet_tpu.serving bench`` drives the closed loop and
    emits the one-JSON-line + BENCH_serving artifact contract."""
    import subprocess
    import sys
    artifact = str(tmp_path / "BENCH_serving.json")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.serving", "bench",
         "--seconds", "1", "--clients", "2", "--dim", "8",
         "--out", artifact],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "MXNET_TPU_JOURNAL": "off"})
    assert out.returncode == 0, out.stderr[-800:]
    line = [l for l in out.stdout.splitlines()
            if l.startswith("{") and '"metric"' in l][-1]
    doc = json.loads(line)
    assert doc["metric"] == "serving_requests_per_sec"
    assert doc["value"] and doc["value"] > 0
    assert doc["compile_bound_ok"] is True
    assert doc["latency_ms"]["p99"] is not None
    with open(artifact, encoding="utf-8") as f:
        assert json.load(f)["metric"] == "serving_requests_per_sec"


@pytest.mark.slow
def test_serving_soak_sustained_mixed_load(journal_file):
    """Longer soak: sustained mixed-shape closed-loop traffic; the
    server neither leaks queue depth nor exceeds the compile bound, and
    shuts down clean."""
    net = Scale()
    net.initialize()
    cfg = ServerConfig(max_batch=8, window_ms=2.0, max_queue=64,
                       dim_buckets={0: (4, 8)})
    server = Server(net, config=cfg).start()
    stop_at = time.monotonic() + 8.0
    errors = []

    def client(idx):
        rng = np.random.default_rng(idx)
        while time.monotonic() < stop_at:
            d = int(rng.integers(1, 9))
            x = rng.standard_normal(d).astype(np.float32)
            try:
                got = np.asarray(server.predict(x))
                np.testing.assert_allclose(got, x, atol=1e-6)
            except ServerOverloaded:
                time.sleep(0.005)
            except Exception as e:        # pragma: no cover - fail loudly
                errors.append(e)
                return

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    server.stop(timeout_s=30)
    assert not errors, errors[:3]
    st = server.stats()
    assert st["served"] > 100
    assert st["cache"]["misses"] <= server.grid.grid_bound()
    assert st["queue_depth"] == 0
    rep = serving_report(journal_file)
    assert rep["ok"] and rep["clean_stop"]

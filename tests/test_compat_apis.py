"""Round-4 API-parity tail: gluon.contrib.estimator, the legacy mx.rnn
module, mx.util, nd.batch_take (ref: python/mxnet/gluon/contrib/
estimator/, python/mxnet/rnn/, python/mxnet/util.py,
src/operator/tensor/indexing_op.cc batch_take)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, io, nd
from mxnet_tpu.gluon.contrib.estimator import (CheckpointHandler,
                                               EarlyStoppingHandler,
                                               Estimator)


def test_batch_take():
    a = nd.array(np.arange(12.0).reshape(3, 4))
    out = nd.batch_take(a, nd.array(np.array([0, 2, 3])))
    np.testing.assert_allclose(out.asnumpy(), [0.0, 6.0, 11.0])


def test_util_np_array_scope():
    assert not mx.util.is_np_array()
    with mx.util.np_array():
        assert mx.util.is_np_array()
    assert not mx.util.is_np_array()

    @mx.util.use_np
    def inner():
        return mx.util.is_np_array()
    assert inner() and not mx.util.is_np_array()


def test_rnn_cells_are_gluon_cells():
    cell = mx.rnn.LSTMCell(8)
    assert isinstance(cell, gluon.rnn.LSTMCell)
    cell.initialize()
    x = [nd.array(np.random.rand(2, 4).astype(np.float32))
         for _ in range(3)]
    outs, states = cell.unroll(3, x, layout="TNC", merge_outputs=False)
    assert len(outs) == 3 and outs[0].shape == (2, 8)


def test_bucket_sentence_iter():
    rng = np.random.RandomState(0)
    sents = [list(rng.randint(1, 20, rng.randint(2, 8)))
             for _ in range(40)]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=4, buckets=[4, 8],
                                   invalid_label=0)
    total = 0
    for b in it:
        assert b.bucket_key in (4, 8)
        assert b.data[0].shape == (4, b.bucket_key)
        d = b.data[0].asnumpy()
        lab = b.label[0].asnumpy()
        # labels are the next-token shift of data
        np.testing.assert_array_equal(lab[:, :-1], d[:, 1:])
        total += 1
    assert total >= 2
    it.reset()
    assert sum(1 for _ in it) == total


def test_encode_sentences_vocab():
    coded, vocab = mx.rnn.encode_sentences([["a", "b"], ["b", "c"]])
    assert coded == [[0, 1], [1, 2]]
    with pytest.raises(mx.base.MXNetError):
        mx.rnn.encode_sentences([["zzz"]], vocab=vocab)


def _toy_task(n=256):
    rng = np.random.RandomState(0)
    x = rng.rand(n, 16).astype(np.float32)
    w = rng.randn(16, 5)
    y = (x @ w).argmax(1).astype(np.float32)
    return x, y


def test_estimator_fit_and_handlers(tmp_path):
    x, y = _toy_task()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(5))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    train = io.NDArrayIter(x[:192], y[:192], batch_size=32, shuffle=True)
    val = io.NDArrayIter(x[192:], y[192:], batch_size=32)
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    trainer=gluon.Trainer(net.collect_params(), "adam",
                                          {"learning_rate": 5e-3}))
    est.fit(train, val, epochs=12, event_handlers=[
        CheckpointHandler(str(tmp_path), save_best=True,
                          monitor=est.val_metrics[0], mode="max")])
    name, acc = est.train_metrics[0].get()
    assert name == "accuracy" and acc > 0.7, (name, acc)
    files = {p.name for p in tmp_path.iterdir()}
    assert "model-final.params" in files and "model-best.params" in files
    # the checkpoint loads back
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(5))
    net2.load_parameters(str(tmp_path / "model-final.params"))


def test_estimator_early_stopping():
    x, y = _toy_task(128)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(5))
    net.initialize(mx.init.Xavier())
    train = io.NDArrayIter(x, y, batch_size=32)
    val = io.NDArrayIter(x, y, batch_size=32)
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    trainer=gluon.Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.0}))
    epochs_seen = []

    class Counter(EarlyStoppingHandler):
        def epoch_end(self, estimator, epoch=None, **kw):
            epochs_seen.append(epoch)
            super().epoch_end(estimator, epoch=epoch, **kw)

    # lr=0: metric never improves after epoch 0 → stops at patience+1
    est.fit(train, val, epochs=50, event_handlers=[
        Counter(est.val_metrics[0], mode="max", patience=2)])
    assert len(epochs_seen) <= 5, epochs_seen


def test_bucketing_word_lm_pipeline():
    """The legacy bucketing word-LM recipe end-to-end (ref:
    example/rnn/bucketing/lstm_bucketing.py): BucketSentenceIter feeds a
    BucketingModule whose sym_gen unrolls the fused RNN op per bucket;
    loss decreases across a few epochs."""
    from mxnet_tpu import sym
    rng = np.random.RandomState(0)
    vocab = 16
    # learnable corpus: deterministic successor chains
    perm = rng.permutation(vocab)
    sents = []
    for _ in range(60):
        start = rng.randint(1, vocab)
        length = rng.randint(3, 9)
        s = [start]
        for _ in range(length - 1):
            s.append(int(perm[s[-1]]))
        sents.append(s)
    it = mx.rnn.BucketSentenceIter(sents, batch_size=4, buckets=[4, 8],
                                   invalid_label=0)

    def sym_gen(seq_len):
        data = sym.var("data")                       # (N, T)
        label = sym.var("softmax_label")
        emb = sym.Embedding(data, input_dim=vocab, output_dim=8,
                            name="embed")
        emb_t = sym.transpose(emb, axes=(1, 0, 2))   # (T, N, E)
        w = sym.var("rnn_weight")
        h0 = sym.var("rnn_h0")
        c0 = sym.var("rnn_c0")
        out = sym.RNN(emb_t, w, h0, c0, state_size=16, num_layers=1,
                      mode="lstm", name="rnn")
        out = sym.transpose(out, axes=(1, 0, 2))     # (N, T, H)
        flat = sym.Reshape(out, shape=(-1, 16))
        fc = sym.FullyConnected(flat, num_hidden=vocab, name="fc")
        net = sym.SoftmaxOutput(fc, sym.Reshape(label, shape=(-1,)),
                                name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    first = next(iter(it))
    it.reset()
    mod.bind(first.provide_data, first.provide_label)
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-2})
    metric = mx.metric.Perplexity(ignore_label=0)
    ppls = []
    for epoch in range(4):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        ppls.append(metric.get()[1])
    assert ppls[-1] < 0.5 * ppls[0], ppls


def test_use_np_on_class_keeps_class():
    @mx.util.use_np
    class Probe(gluon.nn.HybridSequential):
        pass
    assert isinstance(Probe, type)
    assert issubclass(Probe, gluon.nn.HybridSequential)
    assert isinstance(Probe(), Probe)


def test_encode_sentences_frozen_vocab_unknown():
    coded, vocab = mx.rnn.encode_sentences([["a", "b"]])
    with pytest.raises(mx.base.MXNetError):
        # unknown_token must already be IN the frozen vocab
        mx.rnn.encode_sentences([["x"]], vocab=vocab,
                                unknown_token="<unk>")
    vocab["<unk>"] = max(vocab.values()) + 1
    out, _ = mx.rnn.encode_sentences([["x"]], vocab=vocab,
                                     unknown_token="<unk>")
    assert out == [[vocab["<unk>"]]]

"""Round-4 API-parity tail: gluon.contrib.estimator, the legacy mx.rnn
module, mx.util, nd.batch_take (ref: python/mxnet/gluon/contrib/
estimator/, python/mxnet/rnn/, python/mxnet/util.py,
src/operator/tensor/indexing_op.cc batch_take)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, io, nd
from mxnet_tpu.gluon.contrib.estimator import (CheckpointHandler,
                                               EarlyStoppingHandler,
                                               Estimator)


def test_batch_take():
    a = nd.array(np.arange(12.0).reshape(3, 4))
    out = nd.batch_take(a, nd.array(np.array([0, 2, 3])))
    np.testing.assert_allclose(out.asnumpy(), [0.0, 6.0, 11.0])


def test_util_np_array_scope():
    assert not mx.util.is_np_array()
    with mx.util.np_array():
        assert mx.util.is_np_array()
    assert not mx.util.is_np_array()

    @mx.util.use_np
    def inner():
        return mx.util.is_np_array()
    assert inner() and not mx.util.is_np_array()


def test_rnn_cells_are_gluon_cells():
    cell = mx.rnn.LSTMCell(8)
    assert isinstance(cell, gluon.rnn.LSTMCell)
    cell.initialize()
    x = [nd.array(np.random.rand(2, 4).astype(np.float32))
         for _ in range(3)]
    outs, states = cell.unroll(3, x, layout="TNC", merge_outputs=False)
    assert len(outs) == 3 and outs[0].shape == (2, 8)


def test_bucket_sentence_iter():
    rng = np.random.RandomState(0)
    sents = [list(rng.randint(1, 20, rng.randint(2, 8)))
             for _ in range(40)]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=4, buckets=[4, 8],
                                   invalid_label=0)
    total = 0
    for b in it:
        assert b.bucket_key in (4, 8)
        assert b.data[0].shape == (4, b.bucket_key)
        d = b.data[0].asnumpy()
        lab = b.label[0].asnumpy()
        # labels are the next-token shift of data
        np.testing.assert_array_equal(lab[:, :-1], d[:, 1:])
        total += 1
    assert total >= 2
    it.reset()
    assert sum(1 for _ in it) == total


def test_encode_sentences_vocab():
    coded, vocab = mx.rnn.encode_sentences([["a", "b"], ["b", "c"]])
    assert coded == [[0, 1], [1, 2]]
    with pytest.raises(mx.base.MXNetError):
        mx.rnn.encode_sentences([["zzz"]], vocab=vocab)


def _toy_task(n=256):
    rng = np.random.RandomState(0)
    x = rng.rand(n, 16).astype(np.float32)
    w = rng.randn(16, 5)
    y = (x @ w).argmax(1).astype(np.float32)
    return x, y


def test_estimator_fit_and_handlers(tmp_path):
    x, y = _toy_task()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(5))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    train = io.NDArrayIter(x[:192], y[:192], batch_size=32, shuffle=True)
    val = io.NDArrayIter(x[192:], y[192:], batch_size=32)
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    trainer=gluon.Trainer(net.collect_params(), "adam",
                                          {"learning_rate": 5e-3}))
    est.fit(train, val, epochs=12, event_handlers=[
        CheckpointHandler(str(tmp_path), save_best=True,
                          monitor=est.val_metrics[0], mode="max")])
    name, acc = est.train_metrics[0].get()
    assert name == "accuracy" and acc > 0.7, (name, acc)
    files = {p.name for p in tmp_path.iterdir()}
    assert "model-final.params" in files and "model-best.params" in files
    # the checkpoint loads back
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(5))
    net2.load_parameters(str(tmp_path / "model-final.params"))


def test_estimator_early_stopping():
    x, y = _toy_task(128)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(5))
    net.initialize(mx.init.Xavier())
    train = io.NDArrayIter(x, y, batch_size=32)
    val = io.NDArrayIter(x, y, batch_size=32)
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    trainer=gluon.Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.0}))
    epochs_seen = []

    class Counter(EarlyStoppingHandler):
        def epoch_end(self, estimator, epoch=None, **kw):
            epochs_seen.append(epoch)
            super().epoch_end(estimator, epoch=epoch, **kw)

    # lr=0: metric never improves after epoch 0 → stops at patience+1
    est.fit(train, val, epochs=50, event_handlers=[
        Counter(est.val_metrics[0], mode="max", patience=2)])
    assert len(epochs_seen) <= 5, epochs_seen

"""Numeric-gradient sweep over representative ops — the reference's
primary per-op test method (ref: tests/python/unittest/test_operator.py's
check_numeric_gradient usage, SURVEY §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_numeric_gradient


def _rand(*shape, scale=1.0, seed=0):
    return (np.random.RandomState(seed).randn(*shape) * scale) \
        .astype(np.float32)


CASES = {
    "fully_connected": (
        lambda x, w, b: mx.nd.FullyConnected(x, w, b, num_hidden=3),
        [_rand(2, 4), _rand(3, 4), _rand(3)]),
    "convolution": (
        lambda x, w, b: mx.nd.Convolution(x, w, b, kernel=(3, 3),
                                          num_filter=2, pad=(1, 1)),
        [_rand(1, 2, 5, 5), _rand(2, 2, 3, 3), _rand(2)]),
    "softmax": (lambda x: mx.nd.softmax(x, axis=-1), [_rand(3, 5)]),
    "log_softmax": (lambda x: mx.nd.log_softmax(x, axis=-1),
                    [_rand(3, 5)]),
    "tanh": (lambda x: mx.nd.tanh(x), [_rand(3, 4)]),
    "sigmoid": (lambda x: mx.nd.sigmoid(x), [_rand(3, 4)]),
    "exp": (lambda x: mx.nd.exp(x), [_rand(3, 4, scale=0.5)]),
    "layer_norm": (
        lambda x, g, b: mx.nd.LayerNorm(x, g, b, axis=-1),
        [_rand(3, 6), _rand(6, scale=0.5, seed=1) + 1.0, _rand(6, seed=2)]),
    "pooling_avg": (
        lambda x: mx.nd.Pooling(x, pool_type="avg", kernel=(2, 2),
                                stride=(2, 2)),
        [_rand(1, 2, 4, 4)]),
    "broadcast_mul": (lambda a, b: mx.nd.broadcast_mul(a, b),
                      [_rand(3, 4), _rand(1, 4, seed=3)]),
    "dot": (lambda a, b: mx.nd.dot(a, b), [_rand(3, 4), _rand(4, 2)]),
    "batch_dot": (lambda a, b: mx.nd.batch_dot(a, b),
                  [_rand(2, 3, 4), _rand(2, 4, 2)]),
    "embedding": (
        lambda w: mx.nd.Embedding(mx.nd.array([[0, 2], [1, 3]]), w,
                                  input_dim=4, output_dim=3),
        [_rand(4, 3)]),
    "concat": (lambda a, b: mx.nd.concat(a, b, dim=1),
               [_rand(2, 3), _rand(2, 4, seed=4)]),
    "transpose": (lambda x: mx.nd.transpose(x, axes=(1, 0)),
                  [_rand(3, 4)]),
    "sum_axis": (lambda x: mx.nd.sum(x, axis=1), [_rand(3, 4)]),
    "mean": (lambda x: mx.nd.mean(x, axis=0), [_rand(3, 4)]),
    "smooth_l1": (lambda x: mx.nd.smooth_l1(x, scalar=1.0),
                  [_rand(3, 4, scale=2.0)]),
    "slice": (lambda x: mx.nd.slice(x, begin=(1, 0), end=(3, 2)),
              [_rand(4, 3)]),
    "reshape": (lambda x: mx.nd.reshape(x, (6, 2)), [_rand(3, 4)]),
    "leaky_relu": (lambda x: mx.nd.LeakyReLU(x, act_type="leaky",
                                             slope=0.25),
                   [_rand(3, 4) + 0.05]),
    "gelu_npx": (lambda x: mx.npx.gelu(x), [_rand(3, 4)]),
    "where": (lambda a, b: mx.nd.where(
        mx.nd.array([[1, 0], [0, 1]]), a, b),
        [_rand(2, 2), _rand(2, 2, seed=5)]),
    "batchnorm_inference": (
        lambda x, g, b: mx.nd.BatchNorm(
            x, g, b, mx.nd.zeros((3,)), mx.nd.ones((3,)),
            use_global_stats=True, fix_gamma=False)[0],
        [_rand(2, 3, 4), _rand(3, seed=6) + 1.0, _rand(3, seed=7)]),
    # data, offset AND weight gradients of the bilinear-sampled conv
    # (the reference hand-writes all three backward CUDA kernels,
    # ref: src/operator/contrib/deformable_convolution.cc)
    # offsets kept strictly inside a bilinear cell (0.3..0.5): the sample
    # gradient is discontinuous at integer offsets, where central
    # differences straddle the kink
    "deformable_conv": (
        lambda x, off, w, b: mx.nd.contrib.DeformableConvolution(
            x, off, w, b, kernel=(3, 3), pad=(1, 1), num_filter=2),
        [_rand(1, 2, 5, 5),
         _rand(1, 18, 5, 5, scale=0.05, seed=8) + 0.4,
         _rand(2, 2, 3, 3, seed=9), _rand(2, seed=10)]),
    "modulated_deformable_conv": (
        lambda x, off, m, w: mx.nd.contrib.ModulatedDeformableConvolution(
            x, off, m, w, kernel=(3, 3), pad=(1, 1), num_filter=2,
            no_bias=True),
        [_rand(1, 2, 5, 5),
         _rand(1, 18, 5, 5, scale=0.05, seed=11) + 0.4,
         _rand(1, 9, 5, 5, seed=12) * 0.5 + 1.0,
         _rand(2, 2, 3, 3, seed=13)]),
    "count_sketch": (
        lambda x: mx.nd.contrib.count_sketch(
            x, mx.nd.array([[0, 2, 1, 2, 0]]),
            mx.nd.array([[1, -1, 1, 1, -1]]), out_dim=3),
        [_rand(2, 5)]),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_numeric_gradient(name):
    fn, inputs = CASES[name]
    check_numeric_gradient(fn, [mx.nd.array(x) for x in inputs],
                           rtol=2e-2, atol=2e-3, eps=1e-3)

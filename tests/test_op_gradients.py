"""Numeric-gradient sweep over representative ops — the reference's
primary per-op test method (ref: tests/python/unittest/test_operator.py's
check_numeric_gradient usage, SURVEY §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_numeric_gradient


def _rand(*shape, scale=1.0, seed=0):
    return (np.random.RandomState(seed).randn(*shape) * scale) \
        .astype(np.float32)


CASES = {
    "fully_connected": (
        lambda x, w, b: mx.nd.FullyConnected(x, w, b, num_hidden=3),
        [_rand(2, 4), _rand(3, 4), _rand(3)]),
    "convolution": (
        lambda x, w, b: mx.nd.Convolution(x, w, b, kernel=(3, 3),
                                          num_filter=2, pad=(1, 1)),
        [_rand(1, 2, 5, 5), _rand(2, 2, 3, 3), _rand(2)]),
    "softmax": (lambda x: mx.nd.softmax(x, axis=-1), [_rand(3, 5)]),
    "log_softmax": (lambda x: mx.nd.log_softmax(x, axis=-1),
                    [_rand(3, 5)]),
    "tanh": (lambda x: mx.nd.tanh(x), [_rand(3, 4)]),
    "sigmoid": (lambda x: mx.nd.sigmoid(x), [_rand(3, 4)]),
    "exp": (lambda x: mx.nd.exp(x), [_rand(3, 4, scale=0.5)]),
    "layer_norm": (
        lambda x, g, b: mx.nd.LayerNorm(x, g, b, axis=-1),
        [_rand(3, 6), _rand(6, scale=0.5, seed=1) + 1.0, _rand(6, seed=2)]),
    "pooling_avg": (
        lambda x: mx.nd.Pooling(x, pool_type="avg", kernel=(2, 2),
                                stride=(2, 2)),
        [_rand(1, 2, 4, 4)]),
    "broadcast_mul": (lambda a, b: mx.nd.broadcast_mul(a, b),
                      [_rand(3, 4), _rand(1, 4, seed=3)]),
    "dot": (lambda a, b: mx.nd.dot(a, b), [_rand(3, 4), _rand(4, 2)]),
    "batch_dot": (lambda a, b: mx.nd.batch_dot(a, b),
                  [_rand(2, 3, 4), _rand(2, 4, 2)]),
    "embedding": (
        lambda w: mx.nd.Embedding(mx.nd.array([[0, 2], [1, 3]]), w,
                                  input_dim=4, output_dim=3),
        [_rand(4, 3)]),
    "concat": (lambda a, b: mx.nd.concat(a, b, dim=1),
               [_rand(2, 3), _rand(2, 4, seed=4)]),
    "transpose": (lambda x: mx.nd.transpose(x, axes=(1, 0)),
                  [_rand(3, 4)]),
    "sum_axis": (lambda x: mx.nd.sum(x, axis=1), [_rand(3, 4)]),
    "mean": (lambda x: mx.nd.mean(x, axis=0), [_rand(3, 4)]),
    "smooth_l1": (lambda x: mx.nd.smooth_l1(x, scalar=1.0),
                  [_rand(3, 4, scale=2.0)]),
    "slice": (lambda x: mx.nd.slice(x, begin=(1, 0), end=(3, 2)),
              [_rand(4, 3)]),
    "reshape": (lambda x: mx.nd.reshape(x, (6, 2)), [_rand(3, 4)]),
    "leaky_relu": (lambda x: mx.nd.LeakyReLU(x, act_type="leaky",
                                             slope=0.25),
                   [_rand(3, 4) + 0.05]),
    "gelu_npx": (lambda x: mx.npx.gelu(x), [_rand(3, 4)]),
    "where": (lambda a, b: mx.nd.where(
        mx.nd.array([[1, 0], [0, 1]]), a, b),
        [_rand(2, 2), _rand(2, 2, seed=5)]),
    "batchnorm_inference": (
        lambda x, g, b: mx.nd.BatchNorm(
            x, g, b, mx.nd.zeros((3,)), mx.nd.ones((3,)),
            use_global_stats=True, fix_gamma=False)[0],
        [_rand(2, 3, 4), _rand(3, seed=6) + 1.0, _rand(3, seed=7)]),
    # data, offset AND weight gradients of the bilinear-sampled conv
    # (the reference hand-writes all three backward CUDA kernels,
    # ref: src/operator/contrib/deformable_convolution.cc)
    # offsets kept strictly inside a bilinear cell (0.3..0.5): the sample
    # gradient is discontinuous at integer offsets, where central
    # differences straddle the kink
    "deformable_conv": (
        lambda x, off, w, b: mx.nd.contrib.DeformableConvolution(
            x, off, w, b, kernel=(3, 3), pad=(1, 1), num_filter=2),
        [_rand(1, 2, 5, 5),
         _rand(1, 18, 5, 5, scale=0.05, seed=8) + 0.4,
         _rand(2, 2, 3, 3, seed=9), _rand(2, seed=10)]),
    "modulated_deformable_conv": (
        lambda x, off, m, w: mx.nd.contrib.ModulatedDeformableConvolution(
            x, off, m, w, kernel=(3, 3), pad=(1, 1), num_filter=2,
            no_bias=True),
        [_rand(1, 2, 5, 5),
         _rand(1, 18, 5, 5, scale=0.05, seed=11) + 0.4,
         _rand(1, 9, 5, 5, seed=12) * 0.5 + 1.0,
         _rand(2, 2, 3, 3, seed=13)]),
    "count_sketch": (
        lambda x: mx.nd.contrib.count_sketch(
            x, mx.nd.array([[0, 2, 1, 2, 0]]),
            mx.nd.array([[1, -1, 1, 1, -1]]), out_dim=3),
        [_rand(2, 5)]),
    # round-3 breadth: norms, attention, conv variants, indexing,
    # elemwise families — one case per backward code path
    "group_norm": (
        lambda x, g, b: mx.nd.GroupNorm(x, g, b, num_groups=2),
        [_rand(2, 4, 3, 3), _rand(4, seed=20) + 1.0, _rand(4, seed=21)]),
    "instance_norm": (
        lambda x, g, b: mx.nd.InstanceNorm(x, g, b),
        [_rand(2, 3, 4, 4), _rand(3, seed=22) + 1.0, _rand(3, seed=23)]),
    "deconvolution": (
        lambda x, w: mx.nd.Deconvolution(x, w, kernel=(3, 3),
                                         num_filter=2, no_bias=True),
        [_rand(1, 3, 4, 4), _rand(3, 2, 3, 3, seed=24)]),
    "depthwise_conv": (
        lambda x, w: mx.nd.Convolution(x, w, kernel=(3, 3), num_filter=4,
                                       num_group=4, pad=(1, 1),
                                       no_bias=True),
        [_rand(1, 4, 5, 5), _rand(4, 1, 3, 3, seed=25)]),
    "fused_self_attention": (
        lambda qkv: mx.nd.contrib.fused_self_attention(qkv, heads=2,
                                                       causal=True),
        [_rand(1, 4, 12, scale=0.5)]),
    "fused_cross_attention": (
        lambda q, kv: mx.nd.contrib.fused_cross_attention(q, kv, heads=2),
        [_rand(1, 3, 6, scale=0.5), _rand(1, 5, 12, scale=0.5, seed=26)]),
    "logsumexp": (lambda x: mx.nd.logsumexp(x, axis=-1), [_rand(3, 5)]),
    "take": (
        lambda w: mx.nd.take(w, mx.nd.array([0, 2, 1]), axis=0),
        [_rand(4, 3)]),
    "gather_nd": (
        lambda x: mx.nd.gather_nd(x, mx.nd.array([[0, 1], [1, 0]])),
        [_rand(2, 2, 3)]),
    "pick": (
        lambda x: mx.nd.pick(x, mx.nd.array([1, 0, 2]), axis=1),
        [_rand(3, 4)]),
    "norm_l2": (lambda x: mx.nd.norm(x, ord=2, axis=1),
                [_rand(3, 4) + 2.0]),
    "elemwise_div": (lambda a, b: a / b,
                     [_rand(3, 4), _rand(3, 4, seed=27) + 3.0]),
    "power": (lambda a, b: mx.nd.broadcast_power(a, b),
              [np.abs(_rand(3, 4)) + 0.5, _rand(1, 4, seed=28)]),
    "log1p": (lambda x: mx.nd.log1p(x), [np.abs(_rand(3, 4)) + 0.1]),
    "expm1": (lambda x: mx.nd.expm1(x), [_rand(3, 4, scale=0.5)]),
    "rsqrt": (lambda x: mx.nd.rsqrt(x), [np.abs(_rand(3, 4)) + 0.5]),
    "elu": (lambda x: mx.nd.LeakyReLU(x, act_type="elu", slope=1.0),
            [_rand(3, 4) + 0.05]),
    "selu": (lambda x: mx.nd.LeakyReLU(x, act_type="selu"),
             [_rand(3, 4) + 0.05]),
    "prelu": (
        lambda x, g: mx.nd.LeakyReLU(x, g, act_type="prelu"),
        [_rand(3, 4) + 0.05, np.abs(_rand(4, seed=29)) * 0.3 + 0.1]),
    "softsign": (lambda x: mx.nd.Activation(x, act_type="softsign"),
                 [_rand(3, 4)]),
    "stack": (lambda a, b: mx.nd.stack(a, b, axis=1),
              [_rand(2, 3), _rand(2, 3, seed=30)]),
    "tile": (lambda x: mx.nd.tile(x, reps=(2, 1)), [_rand(2, 3)]),
    "dot_transpose_b": (
        lambda a, b: mx.nd.dot(a, b, transpose_b=True),
        [_rand(3, 4), _rand(2, 4, seed=31)]),
    "linalg_gemm2": (
        lambda a, b: mx.nd.linalg_gemm2(a, b, transpose_a=True),
        [_rand(4, 3), _rand(4, 2, seed=32)]),
    "sequence_mask": (
        lambda x: mx.nd.SequenceMask(
            x, mx.nd.array([1, 3]), use_sequence_length=True,
            value=0.0),
        [_rand(3, 2, 4)]),
    "bilinear_resize": (
        lambda x: mx.nd.contrib.BilinearResize2D(x, height=6, width=6),
        [_rand(1, 2, 3, 3)]),
    "roi_align": (
        lambda x: mx.nd.contrib.ROIAlign(
            x, mx.nd.array([[0, 0.31, 0.32, 3.33, 3.34]]),
            pooled_size=(2, 2), spatial_scale=1.0),
        [_rand(1, 2, 5, 5)]),
    "batchnorm_train": (
        lambda x, g, b: mx.nd.BatchNorm(
            x, g, b, mx.nd.zeros((3,)), mx.nd.ones((3,)),
            fix_gamma=False)[0],
        [_rand(4, 3, 4), _rand(3, seed=33) + 1.0, _rand(3, seed=34)]),
    # round-4 tail sweep (VERDICT r3 #4): fft, spatial sampling trio,
    # linalg additions — each exercises a distinct backward path
    "fft": (lambda x: mx.nd.contrib.fft(x), [_rand(2, 8)]),
    "ifft": (lambda x: mx.nd.contrib.ifft(x), [_rand(2, 8)]),
    # grid offsets kept strictly inside bilinear cells (like the
    # deformable cases): the sample gradient kinks at integer coords
    "bilinear_sampler": (
        lambda d, g: mx.nd.BilinearSampler(d, g),
        [_rand(1, 2, 5, 5),
         _rand(1, 2, 3, 3, scale=0.04, seed=40) + 0.25]),
    "spatial_transformer": (
        lambda d, t: mx.nd.SpatialTransformer(
            d, t, transform_type="affine", sampler_type="bilinear",
            target_shape=(4, 4)),
        [_rand(1, 2, 5, 5),
         np.array([[0.77, 0.06, 0.03, -0.04, 0.81, 0.07]],
                  dtype=np.float32)]),
    "grid_generator_affine": (
        lambda t: mx.nd.GridGenerator(t, transform_type="affine",
                                      target_shape=(3, 4)),
        [np.array([[0.9, 0.1, 0.0, -0.1, 0.8, 0.05]], dtype=np.float32)]),
    "grid_generator_warp": (
        lambda f: mx.nd.GridGenerator(f, transform_type="warp"),
        [_rand(1, 2, 3, 4, scale=0.3)]),
    "linalg_trmm": (
        lambda a, b: mx.nd.linalg_trmm(a, b, alpha=1.5),
        [_rand(3, 3), _rand(3, 2, seed=41)]),
    "linalg_trmm_rightside": (
        lambda a, b: mx.nd.linalg_trmm(a, b, rightside=True,
                                       transpose=True, lower=False),
        [_rand(3, 3), _rand(2, 3, seed=42)]),
    "linalg_slogdet": (
        lambda a: mx.nd.linalg_slogdet(a)[1],
        [_rand(3, 3, seed=43) + 3.0 * np.eye(3, dtype=np.float32)]),
    "linalg_det": (
        lambda a: mx.nd.linalg_det(a),
        [_rand(3, 3, seed=44) + 3.0 * np.eye(3, dtype=np.float32)]),
    "linalg_inverse": (
        lambda a: mx.nd.linalg_inverse(a),
        [_rand(3, 3, seed=45) + 3.0 * np.eye(3, dtype=np.float32)]),
    "linalg_makediag": (
        lambda v: mx.nd.linalg_makediag(v, offset=1), [_rand(4)]),
    "linalg_extractdiag": (
        lambda a: mx.nd.linalg_extractdiag(a, offset=-1), [_rand(4, 4)]),
    "linalg_maketrian": (
        lambda v: mx.nd.linalg_maketrian(v), [_rand(6)]),
    "linalg_extracttrian": (
        lambda a: mx.nd.linalg_extracttrian(a, lower=False, offset=1),
        [_rand(4, 4)]),
    "linalg_potrf": (
        lambda a: mx.nd.linalg_potrf(
            mx.nd.linalg_syrk(a) + 3.0 * mx.nd.array(np.eye(3, dtype=np.float32))),
        [_rand(3, 3, seed=46)]),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_numeric_gradient(name):
    fn, inputs = CASES[name]
    check_numeric_gradient(fn, [mx.nd.array(x) for x in inputs],
                           rtol=2e-2, atol=2e-3, eps=1e-3)

"""Distribution tests for the sampler op families
(ref: tests/python/unittest/test_random.py — the reference checks
moments of each `_random_*`/`_sample_*` distribution against the
analytic mean/variance; same method here, tolerances scaled to n).
"""
import numpy as np
import pytest

import mxnet_tpu as mx

N = 4000


def _moments(arr):
    a = arr.asnumpy().astype(np.float64)
    return a.mean(), a.var()


@pytest.fixture(autouse=True)
def _seed():
    mx.random.seed(42)


def test_random_negative_binomial_moments():
    k, p = 5, 0.4
    x = mx.nd.random.negative_binomial(k=k, p=p, shape=(N,))
    mean, var = _moments(x)
    # NB(k, p): mean k(1-p)/p, var k(1-p)/p^2
    assert abs(mean - k * (1 - p) / p) < 0.4
    assert abs(var - k * (1 - p) / p ** 2) < 2.5
    assert float(x.min().asnumpy()) >= 0


def test_random_generalized_negative_binomial_moments():
    mu, alpha = 3.0, 0.5
    x = mx.nd.random.generalized_negative_binomial(mu=mu, alpha=alpha,
                                                   shape=(N,))
    mean, var = _moments(x)
    assert abs(mean - mu) < 0.3
    # var = mu + alpha * mu^2
    assert abs(var - (mu + alpha * mu * mu)) < 1.5


@pytest.mark.parametrize("dist,params,expect_mean,expect_var,tol", [
    ("sample_gamma", (np.full((N,), 3.0, np.float32),
                      np.full((N,), 2.0, np.float32)), 6.0, 12.0, 0.6),
    ("sample_exponential", (np.full((N,), 4.0, np.float32),), 0.25,
     1 / 16.0, 0.05),
    ("sample_poisson", (np.full((N,), 5.0, np.float32),), 5.0, 5.0, 0.5),
    ("sample_negative_binomial", (np.full((N,), 5.0, np.float32),
                                  np.full((N,), 0.4, np.float32)),
     7.5, 18.75, 1.5),
    ("sample_generalized_negative_binomial",
     (np.full((N,), 3.0, np.float32), np.full((N,), 0.5, np.float32)),
     3.0, 7.5, 1.0),
])
def test_sample_family_moments(dist, params, expect_mean, expect_var, tol):
    fn = getattr(mx.nd, dist)
    out = fn(*[mx.nd.array(p) for p in params])
    assert out.shape == params[0].shape
    mean, var = _moments(out)
    assert abs(mean - expect_mean) < tol, (dist, mean)
    assert abs(var - expect_var) < max(6 * tol, 0.12 * expect_var), (dist, var)


def test_sample_family_per_element_params():
    """Each output element draws from ITS row's parameters — the defining
    property of the per-element family (ref: multisample_op.cc)."""
    lam = mx.nd.array(np.array([0.5, 50.0], np.float32))
    draws = mx.nd.sample_poisson(lam, shape=(2000,))
    assert draws.shape == (2, 2000)
    m = draws.asnumpy().mean(axis=1)
    assert abs(m[0] - 0.5) < 0.2 and abs(m[1] - 50.0) < 2.0
    # gamma with per-row alpha
    alpha = mx.nd.array(np.array([1.0, 20.0], np.float32))
    beta = mx.nd.array(np.array([1.0, 1.0], np.float32))
    g = mx.nd.sample_gamma(alpha, beta, shape=(2000,))
    gm = g.asnumpy().mean(axis=1)
    assert abs(gm[0] - 1.0) < 0.25 and abs(gm[1] - 20.0) < 2.0


def test_sample_dirichlet():
    alpha = mx.nd.array(np.array([[1.0, 2.0, 3.0],
                                  [10.0, 10.0, 10.0]], np.float32))
    d = mx.nd.sample_dirichlet(alpha, shape=(500,))
    assert d.shape == (2, 500, 3)
    a = d.asnumpy()
    np.testing.assert_allclose(a.sum(-1), 1.0, atol=1e-5)
    assert (a >= 0).all()
    # E[x_i] = alpha_i / sum(alpha)
    np.testing.assert_allclose(a[0].mean(0), [1 / 6, 2 / 6, 3 / 6],
                               atol=0.06)
    np.testing.assert_allclose(a[1].mean(0), [1 / 3, 1 / 3, 1 / 3],
                               atol=0.03)


def test_samplers_under_jit_and_seed_reproducibility():
    """Samplers draw through the dispatch-threaded PRNG: reseeding
    reproduces the stream (the reference's @with_seed contract)."""
    mx.random.seed(7)
    a = mx.nd.sample_gamma(mx.nd.array([2.0]), mx.nd.array([1.0]),
                           shape=(8,)).asnumpy()
    mx.random.seed(7)
    b = mx.nd.sample_gamma(mx.nd.array([2.0]), mx.nd.array([1.0]),
                           shape=(8,)).asnumpy()
    np.testing.assert_array_equal(a, b)


def test_fft_matches_numpy():
    x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
    f = mx.nd.contrib.fft(mx.nd.array(x)).asnumpy()
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(f[:, 0::2], ref.real, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(f[:, 1::2], ref.imag, rtol=1e-4, atol=1e-4)
    # reference wire format: ifft(fft(x)) == d * x (cuFFT unnormalized)
    r = mx.nd.contrib.ifft(mx.nd.array(f)).asnumpy()
    np.testing.assert_allclose(r, 16 * x, rtol=1e-4, atol=1e-3)


def test_bilinear_sampler_matches_torch_grid_sample():
    """BilinearSampler vs torch.nn.functional.grid_sample (zero padding,
    align_corners=True) — an independent oracle for the sampling
    convention (ref: src/operator/bilinear_sampler.cc docstring example)."""
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(3)
    data = rng.randn(2, 3, 6, 5).astype(np.float32)
    grid = (rng.rand(2, 2, 4, 4).astype(np.float32) * 2.2 - 1.1)
    out = mx.nd.BilinearSampler(mx.nd.array(data),
                                mx.nd.array(grid)).asnumpy()
    tgrid = torch.from_numpy(np.moveaxis(grid, 1, -1))   # (B, Ho, Wo, 2)
    tout = torch.nn.functional.grid_sample(
        torch.from_numpy(data), tgrid, mode="bilinear",
        padding_mode="zeros", align_corners=True).numpy()
    np.testing.assert_allclose(out, tout, rtol=1e-4, atol=1e-5)


def test_spatial_transformer_identity_and_zoom():
    d = mx.nd.array(np.random.RandomState(1).randn(2, 3, 5, 5)
                    .astype(np.float32))
    ident = mx.nd.array(np.tile([1, 0, 0, 0, 1, 0], (2, 1))
                        .astype(np.float32))
    out = mx.nd.SpatialTransformer(d, ident, transform_type="affine",
                                   sampler_type="bilinear",
                                   target_shape=(5, 5))
    np.testing.assert_allclose(out.asnumpy(), d.asnumpy(), rtol=1e-5,
                               atol=1e-5)
    # 2x zoom-in samples the central half
    zoom = mx.nd.array(np.tile([0.5, 0, 0, 0, 0.5, 0], (2, 1))
                       .astype(np.float32))
    out2 = mx.nd.SpatialTransformer(d, zoom, transform_type="affine",
                                    sampler_type="bilinear",
                                    target_shape=(5, 5))
    center = out2.asnumpy()[:, :, 2, 2]
    np.testing.assert_allclose(center, d.asnumpy()[:, :, 2, 2], atol=1e-5)


def test_np_random_distribution_tail():
    """mx.np.random exponential/gamma/beta/dirichlet (ref: numpy-compat
    random namespace) — shapes, moments, and simplex constraint."""
    mx.np.random.seed(0)
    n = 4000
    e = mx.np.random.exponential(2.0, size=(n,)).asnumpy()
    assert abs(e.mean() - 2.0) < 0.15 and (e >= 0).all()
    g = mx.np.random.gamma(3.0, 2.0, size=(n,)).asnumpy()
    assert abs(g.mean() - 6.0) < 0.4 and (g >= 0).all()   # k*theta
    b = mx.np.random.beta(2.0, 5.0, size=(n,)).asnumpy()
    assert abs(b.mean() - 2.0 / 7.0) < 0.03
    assert (b >= 0).all() and (b <= 1).all()
    d = mx.np.random.dirichlet(np.array([1.0, 2.0, 3.0]), size=(n,))
    d = d.asnumpy()
    assert d.shape == (n, 3)
    np.testing.assert_allclose(d.sum(-1), 1.0, atol=1e-5)
    np.testing.assert_allclose(d.mean(0), [1 / 6, 2 / 6, 3 / 6],
                               atol=0.03)


def test_np_random_distribution_tail_moments():
    """Round-5 np.random tail: every new distribution matches its
    theoretical mean/variance (numpy parameterizations: pareto = Lomax,
    geometric counts trials >= 1, power = U^(1/a))."""
    import numpy as onp
    r = mx.np.random
    mx.random.seed(3)
    N = 30000

    def stats(name, arr, mean, var):
        a = arr.asnumpy()
        assert abs(a.mean() - mean) < max(0.08 * abs(mean), 0.05), \
            (name, a.mean(), mean)
        assert abs(a.var() - var) < max(0.15 * var, 0.1), \
            (name, a.var(), var)

    stats("gumbel", r.gumbel(0.0, 1.0, size=N), 0.5772, onp.pi ** 2 / 6)
    stats("laplace", r.laplace(1.0, 2.0, size=N), 1.0, 8.0)
    stats("logistic", r.logistic(0.0, 1.0, size=N), 0.0, onp.pi ** 2 / 3)
    stats("lognormal", r.lognormal(0.0, 0.5, size=N),
          onp.exp(0.125), (onp.exp(0.25) - 1) * onp.exp(0.25))
    stats("poisson", r.poisson(4.0, size=N), 4.0, 4.0)
    stats("chisquare", r.chisquare(3.0, size=(N,)), 3.0, 6.0)
    stats("geometric", r.geometric(0.3, size=(N,)), 1 / 0.3, 0.7 / 0.09)
    stats("pareto", r.pareto(4.0, size=(N,)), 1 / 3, 4 / 18)
    stats("power", r.power(3.0, size=(N,)), 0.75, 3 / 80)
    stats("rayleigh", r.rayleigh(2.0, size=N),
          2 * onp.sqrt(onp.pi / 2), (4 - onp.pi) * 2)
    stats("weibull", r.weibull(2.0, size=(N,)), 0.8862, 1 - onp.pi / 4)
    stats("binomial", r.binomial(10, 0.3, size=N), 3.0, 2.1)
    stats("negative_binomial", r.negative_binomial(5, 0.5, size=(N,)),
          5.0, 10.0)
    f = r.f(5.0, 20.0, size=(N,)).asnumpy()
    assert abs(f.mean() - 20 / 18) < 0.1
    mvn = r.multivariate_normal([1.0, -1.0],
                                [[1.0, 0.5], [0.5, 2.0]], size=N).asnumpy()
    assert mvn.shape == (N, 2)
    cov = onp.cov(mvn.T)
    assert abs(cov[0, 1] - 0.5) < 0.1
    mn = r.multinomial(100, [0.2, 0.3, 0.5], size=4).asnumpy()
    assert mn.shape == (4, 3) and (mn.sum(1) == 100).all()


def test_np_random_tail_array_params_and_int_dtypes():
    """Review-pinned contracts: array distribution parameters broadcast
    with size omitted (numpy semantics), geometric returns ints, and
    'double'-spelled casts stay warning-free."""
    import warnings
    import numpy as onp
    r = mx.np.random
    mx.random.seed(9)
    assert r.chisquare(mx.np.array([1.0, 2.0])).shape == (2,)
    assert r.negative_binomial(mx.np.array([5.0, 3.0]), 0.5).shape == (2,)
    assert r.f(mx.np.array([5.0, 7.0]), 20.0).shape == (2,)
    g = r.geometric(0.3, size=(8,)).asnumpy()
    assert g.dtype.kind == "i" and (g >= 1).all()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = mx.nd.cast(mx.nd.ones((2,)), dtype="double")
    assert out.dtype == onp.float32  # x64 off: effective dtype

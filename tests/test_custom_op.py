"""Custom Python operators (ref: tests/python/unittest/test_operator.py
test_custom_op): numpy forward/backward via host callback, composing with
autograd and jit."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.operator import CustomOp, CustomOpProp, register


@register("scaled_square")
class ScaledSquareProp(CustomOpProp):
    def __init__(self, scale=1.0):
        super().__init__(need_top_grad=True)
        self._scale = float(scale)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        scale = self._scale

        class _Op(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0],
                            scale * in_data[0] ** 2)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                self.assign(in_grad[0], req[0],
                            2.0 * scale * in_data[0] * out_grad[0])
        return _Op()


def test_custom_forward():
    x = mx.nd.array([1.0, 2.0, 3.0])
    y = mx.nd.Custom(x, op_type="scaled_square", scale=2.0)
    np.testing.assert_allclose(y.asnumpy(), [2.0, 8.0, 18.0])


def test_custom_backward():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="scaled_square", scale=3.0)
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 6.0 * np.array([1, 2, 3]))


def test_custom_composes_with_ops():
    x = mx.nd.array([0.5, -0.5])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.tanh(mx.nd.Custom(x, op_type="scaled_square"))
        loss = y.sum()
    loss.backward()
    want = (1 - np.tanh(np.array([0.25, 0.25])) ** 2) * 2 * \
        np.array([0.5, -0.5])
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)


def test_custom_unregistered_raises():
    with pytest.raises(mx.MXNetError):
        mx.nd.Custom(mx.nd.ones((2,)), op_type="no_such_op")

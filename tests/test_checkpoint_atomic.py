"""The hardened .params container + atomic writer (ISSUE 3 tentpole,
docs/checkpointing.md): CRC round trips across dtypes (incl. bfloat16),
structured MXNetError — never struct.error or silent garbage — on every
truncation/corruption shape, legacy-format compatibility, and the
single-process crash matrix: kill nd.save at every write phase and
prove a reader always sees the old or the new file, fully intact."""
import json
import os
import struct

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.diagnostics import journal
from mxnet_tpu.testing import faults

_ND_MAGIC = 0xF993FAC9
_LIST_MAGIC = 0x112


def _params(seed=0):
    import ml_dtypes
    rng = np.random.RandomState(seed)
    return {
        "w": nd.NDArray(rng.randn(3, 4).astype(np.float32)),
        "bf": nd.NDArray(rng.randn(5).astype(ml_dtypes.bfloat16)),
        "i": nd.NDArray(rng.randint(-9, 9, (2, 2)).astype(np.int64)),
        "m": nd.NDArray((rng.randn(4) > 0)),
        "scalar": nd.NDArray(np.float64(seed + 0.5)),
    }


def _bits(d):
    return {k: (str(v.asnumpy().dtype),
                v.asnumpy().view(np.uint8).tobytes()
                if v.asnumpy().ndim else v.asnumpy().tobytes())
            for k, v in d.items()}


def test_crc_roundtrip_all_dtypes(tmp_path):
    """Bit-exact round trip through the CRC format, bfloat16 included
    (stored as raw uint16 bits, no fp32 detour)."""
    p = str(tmp_path / "x.params")
    data = _params()
    nd.save(p, data)
    back = nd.load(p)
    assert _bits(back) == _bits(data)


def test_list_roundtrip_and_empty(tmp_path):
    p = str(tmp_path / "l.params")
    nd.save(p, [nd.NDArray(np.arange(6, dtype=np.float32))])
    (arr,) = nd.load(p)
    assert np.array_equal(arr.asnumpy(), np.arange(6, dtype=np.float32))
    nd.save(p, {})
    assert nd.load(p) == []


def test_truncation_always_structured_error(tmp_path):
    """Any prefix of a .params file — header, entry, names, footer —
    raises MXNetError naming truncation/corruption; struct.error and
    silent partial loads are format violations."""
    p = str(tmp_path / "t.params")
    nd.save(p, _params())
    raw = open(p, "rb").read()
    cuts = sorted({0, 1, 8, 15, 16, 17, 24, 40, len(raw) // 3,
                   len(raw) // 2, len(raw) - 17, len(raw) - 16,
                   len(raw) - 8, len(raw) - 1})
    for cut in cuts:
        with open(p, "wb") as f:
            f.write(raw[:cut])
        with pytest.raises(MXNetError):
            nd.load(p)


def test_bitflip_corruption_caught_by_crc(tmp_path):
    """A single flipped payload byte fails the per-entry CRC — the
    silent-garbage class the checksums exist for."""
    p = str(tmp_path / "c.params")
    nd.save(p, _params())
    raw = bytearray(open(p, "rb").read())
    for pos in (30, len(raw) // 2, len(raw) - 40):
        bad = bytearray(raw)
        bad[pos] ^= 0xFF
        with open(p, "wb") as f:
            f.write(bytes(bad))
        with pytest.raises(MXNetError):
            nd.load(p)


def _write_legacy(path, arrays, names):
    """Reference-era layout: no CRCs, no footer, flag word 0."""
    with open(path, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            f.write(struct.pack("<I", _ND_MAGIC))
            f.write(struct.pack("<I", a.ndim))
            for s in a.shape:
                f.write(struct.pack("<q", s))
            f.write(struct.pack("<ii", 1, 0))
            f.write(struct.pack("<i", {"float32": 0, "int64": 6}[
                a.dtype.name]))
            f.write(a.tobytes())
        f.write(struct.pack("<Q", len(names)))
        for n in names:
            b = n.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def test_legacy_format_still_loads(tmp_path):
    p = str(tmp_path / "leg.params")
    a = np.arange(8, dtype=np.float32).reshape(2, 4)
    _write_legacy(p, [a], ["w"])
    got = nd.load(p)
    assert np.array_equal(got["w"].asnumpy(), a)


def test_legacy_truncation_still_structured(tmp_path):
    p = str(tmp_path / "leg.params")
    a = np.arange(8, dtype=np.float32).reshape(2, 4)
    _write_legacy(p, [a], ["w"])
    raw = open(p, "rb").read()
    for cut in (20, 30, len(raw) - 3):
        with open(p, "wb") as f:
            f.write(raw[:cut])
        with pytest.raises(MXNetError):
            nd.load(p)


def test_unknown_dtype_code_rejected(tmp_path):
    """An unknown dtype code must raise, not decode as float32 garbage
    (the pre-hardening fallback this PR removes)."""
    p = str(tmp_path / "dt.params")
    with open(p, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", 1))
        f.write(struct.pack("<I", _ND_MAGIC))
        f.write(struct.pack("<I", 1))
        f.write(struct.pack("<q", 2))
        f.write(struct.pack("<ii", 1, 0))
        f.write(struct.pack("<i", 99))          # no such dtype
        f.write(b"\x00" * 8)
        f.write(struct.pack("<Q", 0))
    with pytest.raises(MXNetError, match="dtype code 99"):
        nd.load(p)


def test_save_rejects_unsupported_dtype(tmp_path):
    """save() must refuse dtypes with no .params code instead of
    stamping them float32 — CRC-certified garbage is still garbage."""
    arr = nd.NDArray(np.arange(3, dtype=np.uint16))
    if arr.asnumpy().dtype != np.uint16:
        pytest.skip("backend does not preserve uint16")
    with pytest.raises(MXNetError, match="no .params dtype code"):
        nd.save(str(tmp_path / "u.params"), {"x": arr})


def test_bad_magic_and_bad_format_flag(tmp_path):
    p = str(tmp_path / "m.params")
    with open(p, "wb") as f:
        f.write(struct.pack("<QQQ", 0xDEAD, 0, 0))
    with pytest.raises(MXNetError, match="bad magic"):
        nd.load(p)
    with open(p, "wb") as f:
        f.write(struct.pack("<QQQ", _LIST_MAGIC, 7, 0))
    with pytest.raises(MXNetError, match="format flag"):
        nd.load(p)


# -- the single-process crash matrix -----------------------------------------

def _crash_rules(total_bytes):
    rules = [faults.crash("open"), faults.crash("fsync"),
             faults.crash("replace"), faults.crash("after_replace"),
             faults.crash("dir_fsync")]
    rules += [faults.crash("write", after_bytes=n)
              for n in faults.write_offsets(total_bytes)]
    return rules


def test_crash_matrix_old_or_new_every_phase(tmp_path):
    """Kill nd.save at every phase of the atomic write: the file on disk
    afterwards is bit-for-bit the old save (phases before the rename)
    or the new one (after it) — and always loads clean."""
    p = str(tmp_path / "m.params")
    old_data, new_data = _params(0), _params(1)
    nd.save(p, new_data)
    total = os.path.getsize(p)
    committed = _bits(new_data)
    for rule in _crash_rules(total):
        nd.save(p, old_data)
        old_raw = open(p, "rb").read()
        with faults.inject(rule) as plan:
            with pytest.raises(faults.SimulatedCrash):
                nd.save(p, new_data)
        assert plan.log, f"fault at {rule.point} never armed"
        after = open(p, "rb").read()
        if rule.point in ("after_replace", "dir_fsync"):
            assert _bits(nd.load(p)) == committed, rule.point
        else:
            assert after == old_raw, f"torn file after {rule.point}"
        _ = nd.load(p)                       # always parseable


def test_crash_with_no_previous_file_leaves_nothing(tmp_path):
    p = str(tmp_path / "fresh.params")
    with faults.inject(faults.crash("write", after_bytes=10)):
        with pytest.raises(faults.SimulatedCrash):
            nd.save(p, _params())
    assert not os.path.exists(p)
    with pytest.raises((MXNetError, OSError)):
        nd.load(p)
    nd.save(p, _params())                    # retry over the litter works
    assert _bits(nd.load(p)) == _bits(_params())


def test_transient_io_error_retried_and_journaled(tmp_path):
    """One injected EIO at the rename is absorbed by the bounded retry
    (with a journal record); a persistent one surfaces as OSError and
    cleans its temp file."""
    jf = str(tmp_path / "j.jsonl")
    journal.reset_journal(jf)
    try:
        p = str(tmp_path / "r.params")
        with faults.inject(faults.io_error("replace", times=1)):
            nd.save(p, _params())
        assert _bits(nd.load(p)) == _bits(_params())
        recs = [json.loads(line) for line in open(jf)]
        assert any(r["kind"] == "retry" and "replace" in r["what"]
                   for r in recs)
        with faults.inject(faults.io_error("replace", times=99)):
            with pytest.raises(OSError):
                nd.save(str(tmp_path / "q.params"), _params())
        assert not os.path.exists(str(tmp_path / "q.params"))
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith("q.params")]
    finally:
        journal.reset_journal()


def test_sweep_tmp_collects_crash_litter(tmp_path):
    p = str(tmp_path / "s.params")
    nd.save(p, _params())
    with faults.inject(faults.crash("fsync")):
        with pytest.raises(faults.SimulatedCrash):
            nd.save(p, _params(1))
    litter = [n for n in os.listdir(tmp_path) if ".tmp." in n]
    assert litter, "a simulated crash must leave the torn tmp, like a kill"
    from mxnet_tpu.resilience.atomic import sweep_tmp
    removed = sweep_tmp(str(tmp_path))
    assert sorted(removed) == sorted(litter)
    assert _bits(nd.load(p)) == _bits(_params())

"""Fault-tolerant replica-pool serving (docs/serving.md failure matrix).

The headline chaos drill (CI tier 0.5, ``-k smoke``): SIGKILL one of
three REAL replica worker processes under closed-loop load and prove the
router detects it within the heartbeat deadline, in-flight requests are
retried on survivors inside their deadline budget, zero corrupt
responses escape, shed-rate stays under the ceiling, and the respawned
replica is re-admitted through a half-open breaker probe — every
transition trace-correlated in the journal and summarized by
``doctor --serving-journal``.

Around it: router placement/retry/breaker/half-open drills on cheap
in-process replicas, tail-latency hedging with loser-cancelled-at-
dequeue, capacity-floor degradation by admission class, the rolling
``pool.reload()`` version-stamp contract while a new commit root lands
mid-roll, and the ``slow_call``/``torn_heartbeat`` fault hooks.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.diagnostics.journal import reset_journal
from mxnet_tpu.elastic.membership import Heartbeat, LivenessReader
from mxnet_tpu.gluon.block import HybridBlock
from mxnet_tpu.resilience import commit
from mxnet_tpu.serving import (ParamStore, PoolConfig, ReplicaPool,
                               Router, RouterConfig, Server, ServerConfig,
                               ServerOverloaded, serving_report)
from mxnet_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def journal_file(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    reset_journal(path)
    try:
        yield path
    finally:
        reset_journal("stderr")


def _records(path, kind=None):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


class Scale(HybridBlock):
    """y = x * w: shape-agnostic, and the weight value IS the served
    checkpoint's fingerprint (version-stamp assertions ride it)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.w = self.params.get("w", shape=(1,), init="ones")

    def hybrid_forward(self, F, x, w):
        return x * w


def _commit_scale(root, step, value):
    stage = commit.prepare_stage(root, step)
    nd.save(os.path.join(stage, "net.params"),
            {"w": nd.array(np.asarray([value], np.float32))})
    return commit.finalize(root, step)


def _local_pool(root, n=3, ckpt_root=None, heartbeat_s=0.1,
                deadline_s=0.6, **server_kw):
    server_kw.setdefault("max_batch", 4)
    server_kw.setdefault("window_ms", 1.0)

    def factory():
        net = Scale()
        net.initialize()
        store = ParamStore(ckpt_root) if ckpt_root else None
        return Server(net, config=ServerConfig(**server_kw),
                      param_store=store)

    pool = ReplicaPool(root, PoolConfig(heartbeat_s=heartbeat_s,
                                        deadline_s=deadline_s))
    for i in range(n):
        pool.add_local(f"r{i}", factory)
    return pool


# -- fault hooks (satellite: testing/faults) ---------------------------------

def test_torn_heartbeat_reader_degrades_then_revives(tmp_path):
    """A torn (partially written) heartbeat file must read as a stale
    member — never a reader crash, never a fresh liveness grant — and
    the next whole beat revives it."""
    hb = Heartbeat(str(tmp_path), "r0", 0.05,
                   payload=lambda: {"ready": True}, prefix="replica")
    rd = LivenessReader(str(tmp_path), deadline_s=0.25, prefix="replica")
    hb.beat()
    assert rd.alive("r0") and rd.payload("r0")["ready"] is True
    with faults.inject(faults.torn_heartbeat(
            path_part="replica-r0")) as plan:
        hb.beat()
    assert plan.log, "torn-heartbeat rule never fired"
    raw = open(hb.path, "rb").read()
    assert len(raw) == 7              # a real partial-write prefix
    assert rd.alive("r0")             # first torn read: grace, not crash
    # stale payload survives a torn write (degrade, don't forget) ...
    assert rd.payload("r0")["ready"] is True
    time.sleep(0.4)
    # ... but no whole record lands: the member goes stale
    assert not rd.alive("r0")
    hb.beat()
    assert rd.alive("r0")


def test_concurrent_beat_never_loses_payload_flip(tmp_path):
    """A lifecycle ``beat()`` (drain publishing not-ready) racing the
    daemon's timer beat must never lose: the published record always
    reflects a payload sample taken at-or-after the LAST beat.  The
    G15 audit moved the ledger write outside the beat lock; the
    single-in-flight-writer protocol (dirty flag + re-sample loop) is
    what keeps a stale concurrent sample from landing last — this
    hammers it."""
    state = {"ready": True}
    hb = Heartbeat(str(tmp_path), "r9", 999,     # no daemon: we drive
                   payload=lambda: dict(state), prefix="replica")

    for _ in range(50):
        state["ready"] = True
        hb.beat()
        flip = threading.Thread(target=hb.beat)   # the racing "daemon"
        flip.start()
        state["ready"] = False                    # lifecycle flip ...
        hb.beat()                                 # ... published now
        flip.join()
        with open(hb.path) as f:
            doc = json.load(f)
        assert doc["ready"] is False, \
            "stale ready=True sample overwrote the not-ready flip"
    assert json.load(open(hb.path))["seq"] == 150


def test_torn_heartbeat_resignation_drops_stale_payload(tmp_path):
    """A resigned member (file unlinked) must not keep advertising its
    last beacon — the stale-port bug class."""
    hb = Heartbeat(str(tmp_path), "r1", 0.05,
                   payload=lambda: {"port": 1234}, prefix="replica")
    rd = LivenessReader(str(tmp_path), deadline_s=0.25, prefix="replica")
    hb.beat()
    rd.observe("r1")
    assert rd.payload("r1")["port"] == 1234
    hb.stop(resign=True)
    rd.observe("r1")
    assert rd.payload("r1") is None


def test_slow_call_injects_latency_at_trip_site():
    from mxnet_tpu.resilience import atomic
    t0 = time.monotonic()
    with faults.inject(faults.slow_call("router_attempt", 0.2,
                                        path_part="rX")):
        atomic.trip("router_attempt", "rX")       # matches: sleeps
        atomic.trip("router_attempt", "rY")       # no match: instant
        atomic.trip("serving_predict", "rX")      # other site: instant
    assert 0.2 <= time.monotonic() - t0 < 1.0


def test_pool_config_validation():
    with pytest.raises(MXNetError):
        PoolConfig(heartbeat_s=2.0, deadline_s=1.0)
    with pytest.raises(MXNetError):
        PoolConfig(surge=0)


# -- router over in-process replicas -----------------------------------------

def test_router_routes_live_ready_least_loaded(tmp_path, journal_file):
    pool = _local_pool(str(tmp_path / "pool"), n=3).start()
    router = Router(pool, RouterConfig(retries=2))
    x = np.arange(4, dtype=np.float32)
    try:
        for _ in range(24):
            resp = router.call(x)
            np.testing.assert_allclose(resp.value, x, atol=1e-6)
            assert resp.replica in pool.replicas
            assert resp.attempts == 1
    finally:
        router.stop()
        pool.stop()
    st = router.stats()
    assert st["served"] == 24 and st["failures"] == 0
    # placement spread: ledger-derived least-loaded + rotation must not
    # pin every request to one replica
    used = [r for r, row in st["replicas"].items() if row["attempts"]]
    assert len(used) >= 2


def test_router_retries_breaker_opens_and_halfopen_readmits(
        tmp_path, journal_file):
    """The in-process twin of the chaos headline: one replica starts
    failing every request -> retries land on survivors within budget,
    K consecutive failures open its breaker (requests stop routing
    there), and after the cooldown a half-open probe re-admits it."""
    pool = _local_pool(str(tmp_path / "pool"), n=2).start()
    cfg = RouterConfig(retries=2, breaker_k=2, breaker_cooldown_s=0.4)
    router = Router(pool, cfg)
    x = np.arange(3, dtype=np.float32)
    r0 = pool.replicas["r0"]
    real_get = r0.server.cache.get

    class Broken:
        def __call__(self, padded):
            raise ValueError("injected permanent predictor fault")

    r0.server.cache.get = lambda key, builder: (Broken(), True)
    try:
        # drive until r0's breaker opens; every request still succeeds
        # via the survivor within its own deadline
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            resp = router.call(x, deadline_ms=5000)
            np.testing.assert_allclose(resp.value, x, atol=1e-6)
            assert resp.replica == "r1"
            if router.stats()["replicas"]["r0"]["breaker"] == "open":
                break
        st = router.stats()
        assert st["replicas"]["r0"]["breaker"] == "open"
        assert st["retries"] >= 1
        # while open, traffic does not touch r0
        before = st["replicas"]["r0"]["attempts"]
        for _ in range(6):
            router.call(x)
        assert router.stats()["replicas"]["r0"]["attempts"] == before
        # heal the replica, wait out the cooldown: half-open probe
        # re-admits it
        r0.server.cache.get = real_get
        time.sleep(cfg.breaker_cooldown_s + 0.1)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            router.call(x)
            if router.stats()["replicas"]["r0"]["breaker"] == "closed":
                break
        assert router.stats()["replicas"]["r0"]["breaker"] == "closed"
        assert router.stats()["readmissions"] == 1
    finally:
        r0.server.cache.get = real_get
        router.stop()
        pool.stop()
    # journaled transition trail: closed -> open -> half_open -> closed
    trans = [(r["frm"], r["to"], r["reason"])
             for r in _records(journal_file, "router_breaker")
             if r["replica"] == "r0"]
    assert ("closed", "open", "consecutive_failures") in trans
    assert ("open", "half_open", "cooldown_elapsed") in trans
    assert ("half_open", "closed", "probe_succeeded") in trans
    assert _records(journal_file, "router_retry")


def test_router_hedges_slow_replica_and_cancels_loser(
        tmp_path, journal_file):
    """Tail-latency hedging: a slow replica's attempt is hedged on a
    fast one after the configured delay; the first response wins and
    the loser is cancelled at dequeue (serving_cancelled journaled)."""
    pool = _local_pool(str(tmp_path / "pool"), n=2).start()
    router = Router(pool, RouterConfig(retries=1, hedge_ms=60.0))
    x = np.arange(4, dtype=np.float32)
    try:
        with faults.inject(faults.slow_call("router_attempt", 0.5,
                                            path_part="r0", times=None)):
            for _ in range(8):
                resp = router.call(x, deadline_ms=5000)
                np.testing.assert_allclose(resp.value, x, atol=1e-6)
        st = router.stats()
        assert st["hedges"] >= 1
        assert st["hedge_wins"] >= 1
        time.sleep(0.7)                # let cancelled losers dequeue
        cancelled = pool.replicas["r0"].server.stats()["cancelled"]
        assert cancelled >= 1
    finally:
        router.stop()
        pool.stop()
    hedges = _records(journal_file, "router_hedge")
    assert hedges and hedges[0]["primary"] == "r0" \
        and hedges[0]["hedge"] == "r1"
    assert _records(journal_file, "serving_cancelled")


def test_router_routes_around_torn_heartbeat_replica(
        tmp_path, journal_file):
    """Torn-heartbeat chaos in the router matrix: when every beacon
    write for one replica tears (non-atomic writer / full disk shape),
    its seq never advances — the router treats it exactly like a
    stalled replica (breaker opens on heartbeat_stall, traffic routes
    to the survivor) and recovers once whole beats land again."""
    pool = _local_pool(str(tmp_path / "pool"), n=2, heartbeat_s=0.05,
                       deadline_s=0.3).start()
    router = Router(pool, RouterConfig(retries=2,
                                       breaker_cooldown_s=0.2))
    x = np.arange(3, dtype=np.float32)
    try:
        with faults.inject(faults.torn_heartbeat(
                path_part="replica-r0", times=None)):
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                resp = router.call(x, deadline_ms=4000)
                np.testing.assert_allclose(resp.value, x, atol=1e-6)
                if router.stats()["replicas"]["r0"]["breaker"] == "open":
                    break
                time.sleep(0.05)
            assert router.stats()["replicas"]["r0"]["breaker"] == "open"
            for _ in range(4):           # degraded: survivor-only
                assert router.call(x).replica == "r1"
        # whole beats resume: r0 revives through the half-open probe
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            router.call(x)
            if router.stats()["replicas"]["r0"]["breaker"] == "closed":
                break
            time.sleep(0.05)
        assert router.stats()["replicas"]["r0"]["breaker"] == "closed"
    finally:
        router.stop()
        pool.stop()
    opens = [r for r in _records(journal_file, "router_breaker")
             if r["replica"] == "r0" and r["to"] == "open"]
    assert opens and opens[0]["reason"] == "heartbeat_stall"


def test_hedge_loser_releases_halfopen_probe_slot(tmp_path, journal_file):
    """A half-open replica whose probe attempt LOSES a hedge race must
    get its probe slot back — otherwise a healthy replica is silently
    out of rotation forever (no transition, no timeout)."""
    pool = _local_pool(str(tmp_path / "pool"), n=2).start()
    router = Router(pool, RouterConfig(retries=1, hedge_ms=40.0,
                                       breaker_cooldown_s=0.0))
    x = np.arange(3, dtype=np.float32)
    try:
        router.predict(x)                  # warm both paths
        # force r0 into open; cooldown 0 -> next pick goes half-open and
        # its dispatch is the probe — which we make lose the hedge race
        from mxnet_tpu.serving.router import OPEN
        br = router._breaker("r0")
        with router._lock:
            router._transition("r0", br, OPEN, "test_forced")
        with faults.inject(faults.slow_call("router_attempt", 0.5,
                                            path_part="r0", times=None)):
            deadline = time.monotonic() + 10
            probed = False
            while time.monotonic() < deadline and not probed:
                resp = router.call(x, deadline_ms=5000)
                np.testing.assert_allclose(resp.value, x, atol=1e-6)
                probed = router.stats()["replicas"]["r0"]["breaker"] \
                    != "open"
            assert probed                  # half_open reached
        # the slow probe lost (or will lose) its race; once its loser
        # thread resolves, the slot must be free so r0 can be probed
        # again and re-admitted
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                router.stats()["replicas"]["r0"]["breaker"] != "closed":
            router.call(x, deadline_ms=5000)
            time.sleep(0.05)
        assert router.stats()["replicas"]["r0"]["breaker"] == "closed"
        assert not router._breaker("r0").probing
    finally:
        router.stop()
        pool.stop()


def test_capacity_floor_sheds_lowest_priority_first(
        tmp_path, journal_file):
    """Degradation tier: with half the fleet dead and a 0.9 floor,
    priority-1 traffic sheds with the tier named on the error while
    priority-0 traffic still serves."""
    pool = _local_pool(str(tmp_path / "pool"), n=2, heartbeat_s=0.05,
                       deadline_s=0.25).start()
    router = Router(pool, RouterConfig(retries=1, capacity_floor=0.9))
    x = np.arange(3, dtype=np.float32)
    try:
        # both up: every class serves
        assert np.allclose(router.predict(x, priority=1), x)
        # r1 resigns; its beacon drops and capacity halves
        pool.replicas["r1"].stop()
        time.sleep(0.4)
        with pytest.raises(ServerOverloaded) as exc:
            router.predict(x, priority=1)
        assert exc.value.tier == "capacity_floor"
        assert np.allclose(router.predict(x, priority=0), x)  # tier 0 ok
    finally:
        router.stop()
        pool.stop()
    sheds = _records(journal_file, "router_shed")
    assert sheds and sheds[-1]["tier"] == "capacity_floor" \
        and sheds[-1]["priority"] == 1


def test_rolling_reload_version_stamps_old_or_new_only(
        tmp_path, journal_file):
    """Satellite: rolling ``pool.reload()`` while the trainer publishes
    a NEW commit root mid-roll — every response is stamped with (and
    numerically matches) exactly the old or the new step; client-visible
    errors stay zero because at most ``surge`` replicas leave rotation."""
    ck = str(tmp_path / "ckpt")
    _commit_scale(ck, 1, 2.0)
    pool = _local_pool(str(tmp_path / "pool"), n=3, ckpt_root=ck,
                       reload_poll_s=-1.0).start()
    router = Router(pool, RouterConfig(retries=3))
    x = np.ones(4, np.float32)
    seen, errors, stop = [], [], threading.Event()

    def client():
        while not stop.is_set():
            try:
                resp = router.call(x, deadline_ms=8000)
            except Exception as e:           # pragma: no cover - loud
                errors.append(repr(e))
                return
            seen.append((float(np.asarray(resp.value)[0]),
                         resp.params_step))
            time.sleep(0.002)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(3)]
    try:
        assert all(s.params_step == 1 for s in pool.view())
        for t in threads:
            t.start()
        roll = threading.Thread(target=pool.reload, daemon=True)
        roll.start()
        # mid-roll: a fresh step lands; replicas restarted after this
        # moment pick it up, earlier ones stay on step 1
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                not _records(journal_file, "pool_restart"):
            time.sleep(0.02)
        _commit_scale(ck, 2, 5.0)
        roll.join(timeout=60)
        assert not roll.is_alive()
        time.sleep(0.2)
        final = {s.params_step for s in pool.view()}
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        router.stop()
        pool.stop()
    assert not errors, errors[:3]
    assert seen
    for value, step in seen:
        if step == 1:
            assert abs(value - 2.0) < 1e-6, (value, step)
        elif step == 2:
            assert abs(value - 5.0) < 1e-6, (value, step)
        else:
            raise AssertionError(f"response from unknown root: "
                                 f"step={step} value={value}")
    # the fleet ends split across exactly the old and the new root
    assert final <= {1, 2}
    rolls = [r for r in _records(journal_file, "pool_reload")
             if r.get("phase") == "end"]
    assert rolls and set(rolls[-1]["steps"].values()) <= {1, 2}


# -- the chaos headline (CI tier 0.5 smoke) ----------------------------------

def test_pool_chaos_smoke_sigkill_one_of_three_replicas(
        tmp_path, journal_file):
    """SIGKILL 1 of 3 real replica worker processes under closed-loop
    load: detection within the heartbeat deadline, in-flight requests
    retried on survivors within their deadline budget, zero corrupt
    responses, shed-rate under the ceiling, the respawned replica
    re-admitted through a half-open probe — all trace-correlated and
    summarized by the doctor's serving-journal report."""
    from mxnet_tpu.observability import trace as obtrace
    obtrace.configure(mode="journal")
    ck = str(tmp_path / "ckpt")
    _commit_scale(ck, 1, 3.0)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "MXNET_TPU_JOURNAL": journal_file, "PYTHONPATH": REPO,
           "MXNET_TPU_TRACE": "off"}
    env.pop("XLA_FLAGS", None)           # 1-device workers start faster
    cfg = PoolConfig(heartbeat_s=0.25, deadline_s=1.5, monitor_s=0.3)
    pool = ReplicaPool(str(tmp_path / "pool"), cfg)
    for i in range(3):
        pool.add_proc(f"p{i}", {"--model": "scale", "--ckpt-root": ck,
                                "--window-ms": 1.0,
                                "--reload-poll-s": -1.0}, env=env)
    router = Router(pool, RouterConfig(
        retries=3, breaker_k=2, breaker_cooldown_s=1.0))
    x = np.arange(4, dtype=np.float32)
    corrupt, unexpected, ok_count, sheds = [], [], [0], [0]
    stop = threading.Event()
    threads = []

    def client(idx):
        while not stop.is_set():
            try:
                resp = router.call(x, deadline_ms=8000)
            except ServerOverloaded:
                sheds[0] += 1
                time.sleep(0.01)
                continue
            except Exception as e:
                unexpected.append(repr(e))
                time.sleep(0.05)
                continue
            v = np.asarray(resp.value)
            if not np.allclose(v, x * 3.0, atol=1e-5):
                corrupt.append(v.tolist())
            ok_count[0] += 1
            time.sleep(0.005)

    try:
        pool.start()                     # bounded: spawn deadline inside
        pool.monitor_start()
        threads += [threading.Thread(target=client, args=(i,),
                                     daemon=True) for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(1.5)                  # steady-state traffic first
        served_before = router.stats()["served"]
        assert served_before > 0
        t_kill = time.time()
        pool.replicas["p1"].kill()       # the host-vanished shape

        # (1) detection within the heartbeat deadline (+ monitor tick)
        deadline = time.monotonic() + 20
        lost = []
        while time.monotonic() < deadline and not lost:
            lost = [r for r in _records(journal_file, "replica_lost")
                    if r.get("replica") == "p1"]
            time.sleep(0.05)
        assert lost, "replica loss never detected"
        detect_s = lost[0]["ts"] - t_kill
        assert detect_s <= cfg.deadline_s + cfg.monitor_s + 3.0, detect_s

        # (2) the respawned replica is re-admitted via half-open probe
        deadline = time.monotonic() + 60
        readmitted = False
        while time.monotonic() < deadline and not readmitted:
            readmitted = any(
                r["frm"] == "half_open" and r["to"] == "closed"
                for r in _records(journal_file, "router_breaker")
                if r.get("replica") == "p1")
            time.sleep(0.1)
        assert readmitted, "p1 never re-admitted through half-open"
        # and actually serves again
        deadline = time.monotonic() + 30
        base = router.stats()["replicas"]["p1"]["attempts"]
        while time.monotonic() < deadline and \
                router.stats()["replicas"]["p1"]["attempts"] <= base:
            time.sleep(0.1)
        assert router.stats()["replicas"]["p1"]["attempts"] > base
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        router.stop()
        pool.stop()
        obtrace.reset_tracer()

    # (3) zero corrupt responses, survivors absorbed the retries within
    # the deadline budget (no DeadlineExceeded/unhandled errors), and
    # the shed ceiling held
    assert not corrupt, corrupt[:3]
    assert not unexpected, unexpected[:5]
    assert ok_count[0] > served_before
    total = ok_count[0] + sheds[0]
    assert sheds[0] / total <= 0.2, (sheds[0], total)

    # (4) transitions are trace-correlated: the breaker flips that fire
    # inside a routed request carry its trace/span ids
    breakers = [r for r in _records(journal_file, "router_breaker")
                if r.get("replica") == "p1"]
    assert breakers
    assert any(r.get("trace_id") for r in breakers)
    retries = _records(journal_file, "router_retry")
    assert retries and any(r.get("trace_id") for r in retries)

    # (5) the doctor's journal reduction tells the whole story
    rep = serving_report(journal_file)
    assert rep["ok"]
    rt = rep["router"]
    assert any(row["replica"] == "p1" for row in rt["replicas_lost"])
    assert "p1" in rt["readmitted"]
    assert rt["retries"] >= 1
    transitions = [(t["frm"], t["to"]) for t in rt["breaker_transitions"]]
    assert ("half_open", "closed") in transitions
    # the doctor's one-line summary names the recovery
    from mxnet_tpu.diagnostics.__main__ import _summ_serving
    line = _summ_serving(rep)
    assert "replicas lost" in line and "re-admitted" in line
    # zero corrupt responses server-side too: every batch served from
    # the one CRC-valid commit root
    steps = {r.get("params_step")
             for r in _records(journal_file, "serving_batch")}
    assert steps <= {1, None}


# -- reporting ----------------------------------------------------------------

def test_serving_report_router_section_synthetic(tmp_path):
    path = str(tmp_path / "j.jsonl")
    recs = [
        {"kind": "pool_start", "replicas": ["r0", "r1"]},
        {"kind": "serving_start"},       # replica-local run records
        {"kind": "serving_batch", "batch": 2, "delivered": 2,
         "fill": 1.0, "hits": 1, "misses": 1},
        {"kind": "router_retry", "replica": "r0", "attempt": 1,
         "error": "ReplicaUnavailable"},
        {"kind": "router_breaker", "replica": "r0", "frm": "closed",
         "to": "open", "reason": "heartbeat_stall", "trace_id": "t1"},
        {"kind": "replica_lost", "replica": "r0", "idle_s": 2.2},
        {"kind": "pool_restart", "replica": "r0", "ready": True},
        {"kind": "router_breaker", "replica": "r0", "frm": "open",
         "to": "half_open", "reason": "cooldown_elapsed"},
        {"kind": "router_breaker", "replica": "r0", "frm": "half_open",
         "to": "closed", "reason": "probe_succeeded"},
        {"kind": "router_hedge", "primary": "r0", "hedge": "r1",
         "delay_ms": 40.0},
        {"kind": "router_shed", "tier": "capacity_floor", "priority": 1},
        {"kind": "serving_stop", "stuck": False},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rep = serving_report(path)
    assert rep["ok"] and rep["served"] == 2
    rt = rep["router"]
    assert rt["retries"] == 1 and rt["hedges"] == 1
    assert rt["sheds_by_tier"] == {"capacity_floor": 1}
    assert rt["replicas_lost"] == [{"replica": "r0", "idle_s": 2.2}]
    assert rt["restarts"] == 1
    assert rt["readmitted"] == ["r0"]
    assert [t["to"] for t in rt["breaker_transitions"]] == \
        ["open", "half_open", "closed"]
    assert rt["breaker_transitions"][0]["trace_id"] == "t1"


def test_serving_report_anchors_on_pool_start(tmp_path):
    """With a pool run, the last-run slice anchors at pool_start — the
    workers' own serving_start records must not truncate the fleet."""
    path = str(tmp_path / "j.jsonl")
    recs = [
        {"kind": "serving_batch", "batch": 9, "delivered": 9,
         "fill": 1.0},                       # previous run: sliced away
        {"kind": "pool_start", "replicas": ["r0", "r1"]},
        {"kind": "serving_start"},
        {"kind": "serving_batch", "batch": 1, "delivered": 1, "fill": 1.0},
        {"kind": "serving_start"},
        {"kind": "serving_batch", "batch": 2, "delivered": 2, "fill": 1.0},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rep = serving_report(path)
    assert rep["served"] == 3 and rep["batches"] == 2


def test_serving_report_closed_pool_run_then_solo_run(tmp_path):
    """A pool drill that already CLOSED (pool_stop) followed by a later
    plain-Server run: the report must describe the solo run, not
    resurrect the stale fleet's records."""
    path = str(tmp_path / "j.jsonl")
    recs = [
        {"kind": "pool_start", "replicas": ["r0"]},
        {"kind": "serving_start"},
        {"kind": "serving_batch", "batch": 9, "delivered": 9, "fill": 1.0},
        {"kind": "replica_lost", "replica": "r0", "idle_s": 2.0},
        {"kind": "pool_stop"},
        {"kind": "serving_start"},           # the new solo run
        {"kind": "serving_batch", "batch": 2, "delivered": 2, "fill": 1.0},
        {"kind": "serving_stop", "stuck": False},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rep = serving_report(path)
    assert rep["served"] == 2 and rep["batches"] == 1
    assert "router" not in rep               # the drill is history


@pytest.mark.slow
def test_pool_bench_cli_emits_artifact(tmp_path):
    """``python -m mxnet_tpu.serving bench --replicas 2`` routes the
    closed loop through the front door and emits the one-JSON-line +
    BENCH_serving_pool artifact with router counters and the
    observability snapshot."""
    import subprocess
    import sys
    artifact = str(tmp_path / "BENCH_serving_pool.json")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.serving", "bench",
         "--seconds", "1", "--clients", "2", "--dim", "8",
         "--replicas", "2", "--out", artifact],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "MXNET_TPU_JOURNAL": "off"})
    assert out.returncode == 0, out.stderr[-800:]
    line = [l for l in out.stdout.splitlines()
            if l.startswith("{") and '"metric"' in l][-1]
    doc = json.loads(line)
    assert doc["metric"] == "serving_pool_requests_per_sec"
    assert doc["value"] and doc["value"] > 0
    assert doc["router"]["served"] > 0
    assert "hedges" in doc["router"] and "breaker_opens" in doc["router"]
    assert doc["router"]["replicas"].keys() == {"r0", "r1"}
    assert "metrics" in doc["observability"]
    with open(artifact, encoding="utf-8") as f:
        assert json.load(f)["metric"] == "serving_pool_requests_per_sec"


@pytest.mark.slow
def test_router_metrics_text_families(tmp_path):
    from mxnet_tpu.observability.metrics import reset_metrics
    reset_metrics()
    pool = _local_pool(str(tmp_path / "pool"), n=2).start()
    router = Router(pool, RouterConfig())
    try:
        router.predict(np.ones(4, np.float32))
        text = router.metrics_text()
    finally:
        router.stop()
        pool.stop()
        reset_metrics()
    assert "mxnet_tpu_router_events" in text
    assert 'mxnet_tpu_router_breaker_state{replica="r0"} 0' in text
    assert "mxnet_tpu_router_attempts_total" in text

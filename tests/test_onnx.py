"""ONNX export/import: wire codec round trips, model round trips
(ref test analog: tests/python-pytest/onnx/ in the reference)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import onnx as mxonnx
from mxnet_tpu.contrib.onnx import proto


def test_proto_codec_roundtrip():
    model = {
        "ir_version": 8, "opset": 13, "producer_name": "mxnet_tpu",
        "graph": {
            "name": "g",
            "inputs": [{"name": "x", "dtype": "float32",
                        "shape": (2, 3)}],
            "outputs": [{"name": "y", "dtype": "float32", "shape": ()}],
            "initializers": [
                {"name": "w", "data": np.arange(6, dtype=np.float32)
                 .reshape(2, 3)},
                {"name": "idx", "data": np.asarray([-1, 0, 7],
                                                   np.int64)}],
            "nodes": [{"op_type": "Gemm", "name": "n0",
                       "inputs": ["x", "w"], "outputs": ["y"],
                       "attrs": {"alpha": 1.5, "transB": 1,
                                 "axis": -1, "mode": "test",
                                 "ints": [1, -2, 3],
                                 "floats": [0.5, 1.25]}}],
        }}
    buf = proto.encode_model(model)
    got = proto.decode_model(bytes(buf))
    assert got["ir_version"] == 8 and got["opset"] == 13
    g = got["graph"]
    assert g["inputs"][0]["shape"] == (2, 3)
    w = {t["name"]: t["data"] for t in g["initializers"]}
    np.testing.assert_array_equal(
        w["w"], np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_array_equal(w["idx"], [-1, 0, 7])
    a = g["nodes"][0]["attrs"]
    assert a["alpha"] == pytest.approx(1.5)
    assert a["transB"] == 1 and a["axis"] == -1
    assert a["mode"] == "test"
    assert a["ints"] == [1, -2, 3]
    assert a["floats"] == pytest.approx([0.5, 1.25])


def _lenet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, activation="tanh"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, activation="tanh"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(120, activation="tanh"),
            gluon.nn.Dense(84, activation="tanh"),
            gluon.nn.Dense(10))
    return net


def _roundtrip(net, x, tmp_path, name, tol=1e-4):
    net.initialize()
    net.hybridize()
    want = net(nd.array(x)).asnumpy()
    prefix = str(tmp_path / name)
    net.export(prefix)
    path = mxonnx.export_model(
        f"{prefix}-symbol.json", f"{prefix}-0000.params",
        input_shape=[x.shape], onnx_file_path=f"{prefix}.onnx")
    sym, arg_params, aux_params = mxonnx.import_model(path)
    data = [n for n in sym.list_arguments() if n not in arg_params]
    assert len(data) == 1
    ex = sym.bind(mx.cpu(), dict({data[0]: nd.array(x)}, **arg_params),
                  aux_states=aux_params)
    got = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(got, want, atol=tol, rtol=tol)
    return path, want


def test_lenet_roundtrip(tmp_path):
    x = np.random.randn(4, 1, 28, 28).astype(np.float32)
    path, want = _roundtrip(_lenet(), x, tmp_path, "lenet")
    meta = mxonnx.get_model_metadata(path)
    assert meta["input_tensor_data"][0][1] == (4, 1, 28, 28)


def test_resnet18_roundtrip_and_gluon_import(tmp_path):
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1(classes=10)
    net.initialize()
    # make BN running stats non-trivial before export
    for _ in range(2):
        with autograd.record():
            net(nd.array(np.random.randn(4, 3, 32, 32)
                         .astype(np.float32)))
    x = np.random.randn(2, 3, 32, 32).astype(np.float32)
    path, want = _roundtrip(net, x, tmp_path, "rn18", tol=1e-3)
    blk = mxonnx.import_to_gluon(path)
    got = blk(nd.array(x)).asnumpy()
    np.testing.assert_allclose(got, want, atol=1e-3, rtol=1e-3)


def test_bert_onnx_roundtrip(tmp_path):
    """Transformer-family ONNX coverage (round-3 roadmap): full tiny BERT
    (embeddings, fused self-attention decomposed to Split/MatMul/Softmax,
    LayerNormalization, gelu-as-Erf, pooler, MLM head) exports and
    imports back numerically intact."""
    from mxnet_tpu.gluon.model_zoo import bert
    from mxnet_tpu.model import load_checkpoint
    net = bert.BERTModel(num_layers=2, units=32, hidden_size=64,
                         num_heads=4, max_length=64, vocab_size=97,
                         use_pooler=True, use_decoder=True,
                         use_classifier=False, dropout=0.0)
    net.initialize(mx.init.Normal(0.1))
    net.hybridize()
    toks = np.random.RandomState(0).randint(0, 97, (2, 12)) \
        .astype(np.float32)
    want = [o.asnumpy() for o in net(nd.array(toks))]
    net.export(str(tmp_path / "bert"))
    sym, args, aux = load_checkpoint(str(tmp_path / "bert"), 0)
    path = mxonnx.export_model(
        sym, dict(args, **aux), input_shape=[(2, 12)],
        onnx_file_path=str(tmp_path / "bert.onnx"))
    sym2, args2, aux2 = mxonnx.import_model(path)
    data = [n for n in sym2.list_arguments() if n not in args2][0]
    ex = sym2.bind(mx.cpu(),
                   dict({data: nd.array(toks)},
                        **{k: nd.array(v) for k, v in args2.items()}),
                   aux_states={k: nd.array(v) for k, v in aux2.items()})
    got = [o.asnumpy() for o in ex.forward()]
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=2e-4, atol=2e-4)


def test_nmt_transformer_onnx_roundtrip(tmp_path):
    """Encoder-decoder NMT transformer through ONNX: two data inputs,
    causal self-attention (static mask initializer), cross attention,
    slice_like position tables (static Slice via shape inference)."""
    from mxnet_tpu.gluon.model_zoo import transformer
    from mxnet_tpu.model import load_checkpoint
    net = transformer.TransformerModel(
        src_vocab=53, tgt_vocab=61, num_layers=2, units=32,
        hidden_size=64, num_heads=4, max_length=40, dropout=0.0)
    net.initialize(mx.init.Normal(0.1))
    net.hybridize()
    rng = np.random.RandomState(1)
    feed = {"data0": rng.randint(1, 53, (2, 9)).astype(np.float32),
            "data1": rng.randint(1, 61, (2, 7)).astype(np.float32)}
    want = net(nd.array(feed["data0"]), nd.array(feed["data1"])).asnumpy()
    net.export(str(tmp_path / "nmt"))
    sym, args, aux = load_checkpoint(str(tmp_path / "nmt"), 0)
    data_names = [n for n in sym.list_arguments()
                  if n not in args and n not in aux]
    path = mxonnx.export_model(
        sym, dict(args, **aux),
        input_shape=[feed[n].shape for n in data_names],
        onnx_file_path=str(tmp_path / "nmt.onnx"))
    sym2, args2, aux2 = mxonnx.import_model(path)
    ex = sym2.bind(mx.cpu(),
                   dict({k: nd.array(v) for k, v in feed.items()},
                        **{k: nd.array(v) for k, v in args2.items()}),
                   aux_states={k: nd.array(v) for k, v in aux2.items()})
    got = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_export_unsupported_op_message(tmp_path):
    s = mx.sym.var("a")
    out = mx.sym.topk(s, k=2)
    with pytest.raises(MXNetError, match="no converter"):
        mxonnx.export_model(out, {}, input_shape=[(3, 4)],
                            onnx_file_path=str(tmp_path / "x.onnx"))


def test_import_rebind_after_fold_uses_new_weights():
    """ADVICE r5 regression: import-time constant folding must not bake
    trained initializer values into derived constants. A chain rooted at
    an initializer (Neg(w)) imports as a real op, so re-binding
    different arg_params changes the output; a chain rooted at true
    Constant nodes still folds away."""
    w = np.arange(6, dtype=np.float32).reshape(2, 3)
    model = {"ir_version": 8, "opset": 13, "graph": {
        "name": "fold",
        "inputs": [{"name": "x", "dtype": "float32", "shape": (2, 3)}],
        "outputs": [{"name": "y", "dtype": "float32", "shape": ()}],
        "initializers": [{"name": "w", "data": w}],
        "nodes": [
            # initializer-rooted chain: must NOT fold (w is rebindable)
            {"op_type": "Neg", "name": "negw", "inputs": ["w"],
             "outputs": ["wn"], "attrs": {}},
            # Constant-rooted chain: still folds to a single constant
            {"op_type": "Constant", "name": "c2", "inputs": [],
             "outputs": ["two"],
             "attrs": {"value": np.array(2.0, np.float32)}},
            {"op_type": "Neg", "name": "negc", "inputs": ["two"],
             "outputs": ["ntwo"], "attrs": {}},
            {"op_type": "Mul", "name": "scale", "inputs": ["x", "ntwo"],
             "outputs": ["xs"], "attrs": {}},
            {"op_type": "Add", "name": "add", "inputs": ["xs", "wn"],
             "outputs": ["y"], "attrs": {}}]}}
    sym, arg_params, aux_params = mxonnx.import_model(model)
    # the rebindable weight survives as an argument; the folded constant
    # chain contributes only its final value
    assert "w" in sym.list_arguments() and "w" in arg_params
    x = np.ones((2, 3), np.float32)
    got = sym.eval(x=nd.array(x), **arg_params)[0].asnumpy()
    np.testing.assert_allclose(got, x * -2.0 - w, atol=1e-6)
    # REBIND: swap in different trained weights (the checkpoint-reload
    # pattern — replace trained entries, keep the rest of arg_params).
    # Pre-fix, the folded Neg kept -w_original baked in and this
    # returned the OLD result.
    w2 = w + 100.0
    got2 = sym.eval(x=nd.array(x),
                    **{**arg_params, "w": nd.array(w2)})[0].asnumpy()
    np.testing.assert_allclose(got2, x * -2.0 - w2, atol=1e-6)


def test_import_graph_dict_level():
    w = np.random.randn(4, 3).astype(np.float32)
    b = np.zeros(4, np.float32)
    model = {"ir_version": 8, "opset": 13, "graph": {
        "name": "mlp",
        "inputs": [{"name": "x", "dtype": "float32", "shape": (2, 3)}],
        "outputs": [{"name": "y", "dtype": "float32", "shape": ()}],
        "initializers": [{"name": "w", "data": w},
                         {"name": "b", "data": b}],
        "nodes": [
            {"op_type": "Gemm", "name": "fc", "inputs": ["x", "w", "b"],
             "outputs": ["h"], "attrs": {"transB": 1}},
            {"op_type": "Relu", "name": "act", "inputs": ["h"],
             "outputs": ["y"], "attrs": {}}]}}
    sym, arg_params, aux_params = mxonnx.import_model(model)
    x = np.random.randn(2, 3).astype(np.float32)
    ex = sym.bind(mx.cpu(), dict({"x": nd.array(x)}, **arg_params))
    got = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(got, np.maximum(x @ w.T + b, 0),
                               atol=1e-5)

"""gluon.rnn tests (ref: tests/python/unittest/test_gluon_rnn.py):
cell/layer shapes, fused-vs-cell consistency, bidirectional, autograd."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import rnn


def test_rnn_cell_shapes():
    cell = rnn.RNNCell(16, input_size=8)
    cell.initialize()
    x = mx.nd.random.normal(shape=(4, 8))
    states = cell.begin_state(4)
    out, new_states = cell(x, states)
    assert out.shape == (4, 16)
    assert new_states[0].shape == (4, 16)


def test_lstm_cell_unroll():
    cell = rnn.LSTMCell(10, input_size=6)
    cell.initialize()
    x = mx.nd.random.normal(shape=(2, 5, 6))  # NTC
    outputs, states = cell.unroll(5, x, layout="NTC")
    assert outputs.shape == (2, 5, 10)
    assert len(states) == 2


def test_gru_cell_deferred_init():
    cell = rnn.GRUCell(12)
    cell.initialize()
    out, states = cell(mx.nd.random.normal(shape=(3, 7)),
                       cell.begin_state(3))
    assert out.shape == (3, 12)


def test_lstm_layer_forward():
    layer = rnn.LSTM(20, num_layers=2)
    layer.initialize()
    x = mx.nd.random.normal(shape=(5, 3, 10))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 20)
    out, states = layer(x, layer.begin_state(3))
    assert out.shape == (5, 3, 20)
    assert states[0].shape == (2, 3, 20)
    assert states[1].shape == (2, 3, 20)


def test_bidirectional_lstm_layer():
    layer = rnn.LSTM(8, num_layers=1, bidirectional=True, layout="NTC")
    layer.initialize()
    x = mx.nd.random.normal(shape=(2, 6, 4))
    out = layer(x)
    assert out.shape == (2, 6, 16)


def test_gru_layer_matches_cell():
    """Fused GRU layer ≡ stepping the GRUCell with the same weights — the
    reference's fused-vs-unfused consistency check."""
    T, N, C, H = 4, 2, 3, 5
    layer = rnn.GRU(H, input_size=C)
    layer.initialize()
    x = mx.nd.random.normal(shape=(T, N, C))
    out = layer(x)

    cell = rnn.GRUCell(H, input_size=C)
    cell.initialize()
    # copy the layer's weights into the cell
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    states = cell.begin_state(N)
    outs = []
    for t in range(T):
        o, states = cell(x[t], states)
        outs.append(o.asnumpy())
    np.testing.assert_allclose(out.asnumpy(),
                               np.stack(outs, axis=0), rtol=1e-5, atol=1e-6)


def test_lstm_layer_matches_cell():
    T, N, C, H = 3, 2, 4, 6
    layer = rnn.LSTM(H, input_size=C)
    layer.initialize()
    x = mx.nd.random.normal(shape=(T, N, C))
    out = layer(x)

    cell = rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    states = cell.begin_state(N)
    outs = []
    for t in range(T):
        o, states = cell(x[t], states)
        outs.append(o.asnumpy())
    np.testing.assert_allclose(out.asnumpy(),
                               np.stack(outs, axis=0), rtol=1e-5, atol=1e-6)


def test_lstm_layer_backward():
    layer = rnn.LSTM(8, num_layers=1)
    layer.initialize()
    x = mx.nd.random.normal(shape=(3, 2, 5))
    with autograd.record():
        out = layer(x)
        loss = (out * out).sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad()
    assert g.shape == layer.l0_i2h_weight.shape
    assert float(np.abs(g.asnumpy()).sum()) > 0


def test_sequential_cell_and_residual():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(8, input_size=8))
    stack.add(rnn.ResidualCell(rnn.LSTMCell(8, input_size=8)))
    stack.initialize()
    x = mx.nd.random.normal(shape=(2, 6, 8))
    out, states = stack.unroll(6, x, layout="NTC")
    assert out.shape == (2, 6, 8)


def test_bidirectional_cell_unroll():
    bi = rnn.BidirectionalCell(rnn.LSTMCell(5, input_size=4),
                               rnn.LSTMCell(5, input_size=4))
    bi.initialize()
    x = mx.nd.random.normal(shape=(2, 3, 4))
    out, states = bi.unroll(3, x, layout="NTC")
    assert out.shape == (2, 3, 10)

"""gluon.rnn tests (ref: tests/python/unittest/test_gluon_rnn.py):
cell/layer shapes, fused-vs-cell consistency, bidirectional, autograd."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon import rnn


def test_rnn_cell_shapes():
    cell = rnn.RNNCell(16, input_size=8)
    cell.initialize()
    x = mx.nd.random.normal(shape=(4, 8))
    states = cell.begin_state(4)
    out, new_states = cell(x, states)
    assert out.shape == (4, 16)
    assert new_states[0].shape == (4, 16)


def test_lstm_cell_unroll():
    cell = rnn.LSTMCell(10, input_size=6)
    cell.initialize()
    x = mx.nd.random.normal(shape=(2, 5, 6))  # NTC
    outputs, states = cell.unroll(5, x, layout="NTC")
    assert outputs.shape == (2, 5, 10)
    assert len(states) == 2


def test_gru_cell_deferred_init():
    cell = rnn.GRUCell(12)
    cell.initialize()
    out, states = cell(mx.nd.random.normal(shape=(3, 7)),
                       cell.begin_state(3))
    assert out.shape == (3, 12)


def test_lstm_layer_forward():
    layer = rnn.LSTM(20, num_layers=2)
    layer.initialize()
    x = mx.nd.random.normal(shape=(5, 3, 10))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 20)
    out, states = layer(x, layer.begin_state(3))
    assert out.shape == (5, 3, 20)
    assert states[0].shape == (2, 3, 20)
    assert states[1].shape == (2, 3, 20)


def test_bidirectional_lstm_layer():
    layer = rnn.LSTM(8, num_layers=1, bidirectional=True, layout="NTC")
    layer.initialize()
    x = mx.nd.random.normal(shape=(2, 6, 4))
    out = layer(x)
    assert out.shape == (2, 6, 16)


def test_gru_layer_matches_cell():
    """Fused GRU layer ≡ stepping the GRUCell with the same weights — the
    reference's fused-vs-unfused consistency check."""
    T, N, C, H = 4, 2, 3, 5
    layer = rnn.GRU(H, input_size=C)
    layer.initialize()
    x = mx.nd.random.normal(shape=(T, N, C))
    out = layer(x)

    cell = rnn.GRUCell(H, input_size=C)
    cell.initialize()
    # copy the layer's weights into the cell
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    states = cell.begin_state(N)
    outs = []
    for t in range(T):
        o, states = cell(x[t], states)
        outs.append(o.asnumpy())
    np.testing.assert_allclose(out.asnumpy(),
                               np.stack(outs, axis=0), rtol=1e-5, atol=1e-6)


def test_lstm_layer_matches_cell():
    T, N, C, H = 3, 2, 4, 6
    layer = rnn.LSTM(H, input_size=C)
    layer.initialize()
    x = mx.nd.random.normal(shape=(T, N, C))
    out = layer(x)

    cell = rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    states = cell.begin_state(N)
    outs = []
    for t in range(T):
        o, states = cell(x[t], states)
        outs.append(o.asnumpy())
    np.testing.assert_allclose(out.asnumpy(),
                               np.stack(outs, axis=0), rtol=1e-5, atol=1e-6)


def test_lstm_layer_backward():
    layer = rnn.LSTM(8, num_layers=1)
    layer.initialize()
    x = mx.nd.random.normal(shape=(3, 2, 5))
    with autograd.record():
        out = layer(x)
        loss = (out * out).sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad()
    assert g.shape == layer.l0_i2h_weight.shape
    assert float(np.abs(g.asnumpy()).sum()) > 0


def test_sequential_cell_and_residual():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(8, input_size=8))
    stack.add(rnn.ResidualCell(rnn.LSTMCell(8, input_size=8)))
    stack.initialize()
    x = mx.nd.random.normal(shape=(2, 6, 8))
    out, states = stack.unroll(6, x, layout="NTC")
    assert out.shape == (2, 6, 8)


def test_bidirectional_cell_unroll():
    bi = rnn.BidirectionalCell(rnn.LSTMCell(5, input_size=4),
                               rnn.LSTMCell(5, input_size=4))
    bi.initialize()
    x = mx.nd.random.normal(shape=(2, 3, 4))
    out, states = bi.unroll(3, x, layout="NTC")
    assert out.shape == (2, 3, 10)


def test_rnn_use_sequence_length():
    # cuDNN varlen semantics: outputs zero past each length, final state
    # is the state at len-1 (ref: rnn.cc use_sequence_length)
    T, N, C, H = 6, 3, 4, 5
    rng = np.random.RandomState(0)
    data = rng.randn(T, N, C).astype(np.float32)
    g = 4
    params = (rng.randn(g * H * C + g * H * H + 2 * g * H)
              .astype(np.float32) * 0.1)
    h0 = np.zeros((1, N, H), np.float32)
    c0 = np.zeros((1, N, H), np.float32)
    seq_len = np.array([6, 3, 1], np.float32)
    out, hy, cy = mx.nd.RNN(
        mx.nd.array(data), mx.nd.array(params), mx.nd.array(h0),
        mx.nd.array(c0), mx.nd.array(seq_len), state_size=H,
        num_layers=1, mode="lstm", state_outputs=True,
        use_sequence_length=True)
    o = out.asnumpy()
    assert np.all(o[3:, 1] == 0) and np.all(o[1:, 2] == 0)
    ref, hy_f, cy_f = mx.nd.RNN(
        mx.nd.array(data[:3, 1:2]), mx.nd.array(params),
        mx.nd.array(h0[:, 1:2]), mx.nd.array(c0[:, 1:2]), state_size=H,
        num_layers=1, mode="lstm", state_outputs=True)
    np.testing.assert_allclose(o[:3, 1], ref.asnumpy()[:, 0], atol=1e-5)
    np.testing.assert_allclose(hy.asnumpy()[0, 1], hy_f.asnumpy()[0, 0],
                               atol=1e-5)
    np.testing.assert_allclose(cy.asnumpy()[0, 1], cy_f.asnumpy()[0, 0],
                               atol=1e-5)


def test_rnn_lstm_projection():
    # LSTMP (ref: rnn-inl.h projection_size): hidden projected H -> P
    T, N, C, H, P = 6, 3, 4, 5, 3
    rng = np.random.RandomState(1)
    data = rng.randn(T, N, C).astype(np.float32)
    g = 4
    params = (rng.randn(g * H * C + g * H * P + P * H + 2 * g * H)
              .astype(np.float32) * 0.1)
    h0 = np.zeros((1, N, P), np.float32)
    c0 = np.zeros((1, N, H), np.float32)
    out = mx.nd.RNN(mx.nd.array(data), mx.nd.array(params),
                    mx.nd.array(h0), mx.nd.array(c0), state_size=H,
                    num_layers=1, mode="lstm", projection_size=P)
    assert out.shape == (T, N, P)
    assert np.isfinite(out.asnumpy()).all()


def test_topk_mask():
    x = np.array([[3., 1., 4., 1., 5.], [2., 7., 1., 8., 2.]],
                 np.float32)
    m = mx.nd.topk(mx.nd.array(x), k=2, ret_typ="mask").asnumpy()
    np.testing.assert_array_equal(m, [[0, 0, 1, 0, 1], [0, 1, 0, 1, 0]])

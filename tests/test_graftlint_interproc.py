"""graftlint v2 — the interprocedural tier: call-graph construction
(same-module resolution, base-class methods, nested defs), summary
extraction + cycle-safe fixpoint (blocking reach, lock orders, rank
taint), the fingerprint-keyed summary cache, ``--jobs`` parity,
``--changed-only`` selection with reverse import-graph dependents, the
doctor ``--lint`` report, and the audit fixes the engine drove
(heartbeat beat outside its lock on unique temps, restart deadlines
threaded)."""
import ast
import json
import os
import subprocess
import sys
import threading

import pytest

from mxnet_tpu.analysis import callgraph as cg
from mxnet_tpu.analysis import cli as lint_cli
from mxnet_tpu.analysis import core
from mxnet_tpu.analysis import summaries as sm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "graftlint")


def _ctx(src, path="mxnet_tpu/fake_mod.py"):
    return core.FileContext(path, src, ast.parse(src))


def _summ(src, cache=None):
    return sm.module_summaries(_ctx(src), cache=cache)


# -- call-graph construction -------------------------------------------------

def test_callgraph_resolves_self_module_and_base_methods():
    src = (
        "def helper():\n"
        "    return 1\n"
        "class Base:\n"
        "    def shared(self):\n"
        "        return helper()\n"
        "class Child(Base):\n"
        "    def go(self):\n"
        "        return self.shared()\n"
    )
    ctx = _ctx(src)
    index = cg.build_index(ctx)
    assert set(index.functions) == {"helper", "Base.shared", "Child.go"}
    call = next(n for n in ast.walk(index.functions["Child.go"].node)
                if isinstance(n, ast.Call))
    # self.shared() resolves through the same-module base chain
    assert cg.resolve_callee(index, call, "Child", "Child.go") == \
        "Base.shared"


def test_callgraph_nested_defs_are_separate_scopes():
    src = (
        "import time\n"
        "import threading\n"
        "_lk = threading.Lock()\n"
        "def outer():\n"
        "    def inner():\n"
        "        time.sleep(1)\n"
        "    with _lk:\n"
        "        return inner\n"            # DEFINED under the lock,
    )                                       # never CALLED under it
    ms = _summ(src)
    assert "outer.inner" in ms.functions
    # the sleep belongs to inner, and outer never calls it: no G15 food
    assert not ms.functions["outer"].blocks
    assert ("sleep", "time.sleep") in ms.reach["outer.inner"]
    assert ("sleep", "time.sleep") not in ms.reach["outer"]


# -- fixpoint ----------------------------------------------------------------

def test_fixpoint_converges_on_recursion_and_cycles():
    """a <-> b mutual recursion plus a self-recursive c: the monotone
    join must terminate and both cycle members must reach the sleep."""
    src = (
        "import time\n"
        "def a(n):\n"
        "    time.sleep(0.1)\n"
        "    return b(n - 1)\n"
        "def b(n):\n"
        "    return a(n) if n else 0\n"
        "def c(n):\n"
        "    return c(n - 1) if n else b(0)\n"
    )
    ms = _summ(src)
    for fn in ("a", "b", "c"):
        assert ("sleep", "time.sleep") in ms.reach[fn], fn
    path, line = ms.chain("c", ("sleep", "time.sleep"))
    assert path[0] == "c" and path[-1] == "a" and line == 3


def test_rank_taint_propagates_through_returns_and_cycles():
    src = (
        "import jax\n"
        "def direct():\n"
        "    return jax.process_index() == 0\n"
        "def hop():\n"
        "    v = direct()\n"
        "    return v\n"
        "def cycle_a():\n"
        "    return cycle_b() or hop()\n"
        "def cycle_b():\n"
        "    return cycle_a()\n"
        "def clean():\n"
        "    return 42\n"
    )
    ms = _summ(src)
    assert ms.rank_taint["direct"] and ms.rank_taint["hop"]
    assert ms.rank_taint["cycle_a"] and ms.rank_taint["cycle_b"]
    assert not ms.rank_taint["clean"]


def test_deadline_param_read_tracking_includes_closures():
    src = (
        "import queue\n"
        "q = queue.Queue(maxsize=2)\n"
        "def dropped(x, timeout_s):\n"
        "    return q.get(timeout=5.0)\n"
        "def threaded(x, timeout_s):\n"
        "    def attempt():\n"
        "        return q.get(timeout=timeout_s)\n"
        "    return attempt()\n"
    )
    ms = _summ(src)
    d, t = ms.functions["dropped"], ms.functions["threaded"]
    assert d.deadline_params == ["timeout_s"] and d.deadline_read == []
    assert t.deadline_read == ["timeout_s"]


def test_lock_regions_annotate_blocks_and_orders():
    src = (
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n"
        "                time.sleep(1)\n"
    )
    ms = _summ(src)
    s = ms.functions["C.one"]
    (kind, what, _line, held, _dl), = s.blocks
    assert kind == "sleep" and len(held) == 2
    (outer, _l1, held0), (inner, _l2, held1) = s.acq_with
    assert held0 == () and outer in held1


# -- summary cache -----------------------------------------------------------

def test_summary_cache_hit_equals_computed(tmp_path):
    path = os.path.join(FIXTURES, "g15_blocking_under_lock.py")
    src = open(path, encoding="utf-8").read()
    cache = sm.SummaryCache(str(tmp_path / "c.json"))
    cold = sm.module_summaries(
        core.FileContext(path, src, ast.parse(src)), cache=cache)
    assert cache.misses == 1 and cache.hits == 0
    warm = sm.module_summaries(
        core.FileContext(path, src, ast.parse(src)), cache=cache)
    assert cache.hits == 1
    assert warm.reach == cold.reach
    assert warm.trans_acquires == cold.trans_acquires
    assert {k: s.to_dict() for k, s in warm.functions.items()} == \
        {k: s.to_dict() for k, s in cold.functions.items()}


def test_summary_cache_invalidates_on_content_change(tmp_path):
    cache = sm.SummaryCache(str(tmp_path / "c.json"))
    _summ("def f():\n    return 1\n", cache=cache)
    _summ("def f():\n    return 2\n", cache=cache)   # edited: must MISS
    assert cache.misses == 2 and cache.hits == 0


def test_summary_cache_roundtrips_and_survives_corruption(tmp_path):
    cpath = str(tmp_path / "c.json")
    cache = sm.SummaryCache(cpath)
    src = "import time\ndef f():\n    time.sleep(1)\n"
    _summ(src, cache=cache)
    cache.save()
    reloaded = sm.SummaryCache.load(cpath)
    ms = _summ(src, cache=reloaded)
    assert reloaded.hits == 1
    assert ("sleep", "time.sleep") in ms.reach["f"]
    with open(cpath, "w") as f:
        f.write("{ corrupt json")
    broken = sm.SummaryCache.load(cpath)       # must not raise
    _summ(src, cache=broken)
    assert broken.misses == 1


def test_findings_identical_with_and_without_cache(tmp_path):
    """The acceptance shape: a cache hit changes nothing about the
    findings — fingerprint pins the file text, lines included."""
    cache = sm.SummaryCache(str(tmp_path / "c.json"))
    prev = sm.set_active_cache(cache)
    try:
        first = core.run([FIXTURES], root=REPO)[0]
        second = core.run([FIXTURES], root=REPO)[0]
    finally:
        sm.set_active_cache(prev)
    assert cache.hits > 0
    nocache = core.run([FIXTURES], root=REPO)[0]
    as_key = lambda fs: [f.sort_key() for f in fs]
    assert as_key(first) == as_key(second) == as_key(nocache)


# -- --jobs parity -----------------------------------------------------------

def test_jobs_parallel_findings_match_serial():
    serial, n1 = core.run([FIXTURES], root=REPO)
    parallel, n2 = core.run([FIXTURES], root=REPO, jobs=4)
    assert n1 == n2
    assert [f.sort_key() for f in serial] == \
        [f.sort_key() for f in parallel]
    assert serial, "fixture corpus must produce findings"


# -- historical fixtures (the engine catches the real PR-9/10 bugs) ----------

def test_historical_latched_probe_is_flagged():
    path = os.path.join(FIXTURES, "hist_latched_probe.py")
    found = core.lint_file(path, rules=[core.load_rules()["G17"]],
                           root=REPO)
    assert len(found) == 1 and found[0].code == "G17"
    assert "latches the slot" in found[0].message


def test_historical_lock_held_ledger_io_is_flagged():
    path = os.path.join(FIXTURES, "hist_lock_held_ledger_io.py")
    found = core.lint_file(path, rules=[core.load_rules()["G15"]],
                           root=REPO)
    assert len(found) == 1 and found[0].code == "G15"
    assert "_view" in found[0].message     # names the call chain


# -- the audited subsystems stay clean ---------------------------------------

@pytest.mark.parametrize("subsystem", [
    "mxnet_tpu/serving", "mxnet_tpu/elastic", "mxnet_tpu/observability",
    "mxnet_tpu/diagnostics", "mxnet_tpu/resilience"])
def test_concurrency_rules_clean_on_audited_subsystems(subsystem):
    """The audit-and-fix acceptance: every live G15-G20 finding was
    fixed (router/fleet transition journaling deferred past the locks,
    heartbeat write outside its lock, restart deadlines threaded, the
    hedge-arm span restructured onto `with`), none baselined."""
    registry = core.load_rules()
    rules = [registry[c]
             for c in ("G15", "G16", "G17", "G18", "G19", "G20")]
    findings, n = core.run([subsystem], rules=rules, root=REPO)
    assert n >= 4 and findings == []


# -- G20 leaked-open-span -----------------------------------------------------

_G20_PRELUDE = "from mxnet_tpu.observability import trace\n"


def _g20_run(src, tmp_path):
    path = tmp_path / "fake_spans.py"
    path.write_text("# graftlint: scope=library\n" + _G20_PRELUDE + src)
    return core.lint_file(str(path), rules=[core.load_rules()["G20"]],
                          root=str(tmp_path))


def test_g20_param_end_fixpoint_two_hops(tmp_path):
    """A finally-called helper that forwards the span to ANOTHER helper
    that ends it counts as exception-safe — the param-position fixpoint
    follows the chain; the SAME helper on a straight-line path does
    not (a raise before it leaks the span), and a helper that merely
    annotates transfers nothing (silent handoff, documented limit)."""
    src = (
        "def _really_close(sp, status='ok'):\n"
        "    sp.end(status=status)\n"
        "def _close(span):\n"
        "    _really_close(span)\n"
        "def _annotate(span):\n"
        "    span.set_attrs(seen=True)\n"
        "def good(work):\n"
        "    sp = trace.start_span('a')\n"
        "    try:\n"
        "        return work()\n"
        "    finally:\n"
        "        _close(sp)\n"
        "def bad(work):\n"
        "    sp = trace.start_span('a')\n"
        "    out = work()\n"      # a raise here leaks sp: _close is
        "    _close(sp)\n"        # straight-line, not finally
        "    return out\n"
    )
    found = _g20_run(src, tmp_path)
    assert len(found) == 1 and found[0].code == "G20"
    assert "never on a finally: path" in found[0].message
    # the finding sits on bad()'s open, not good()'s
    assert "start_span('a')" in open(tmp_path / "fake_spans.py")\
        .read().splitlines()[found[0].line - 1]
    assert found[0].line > 10


def test_g20_keyword_forwarding_and_method_offset(tmp_path):
    """self-method helpers (param offset past ``self``) and keyword
    forwarding both resolve to the right param position."""
    src = (
        "class R:\n"
        "    def _close(self, span, status='ok'):\n"
        "        span.end(status=status)\n"
        "    def good_kw(self, work):\n"
        "        sp = trace.start_span('a')\n"
        "        try:\n"
        "            return work()\n"
        "        finally:\n"
        "            self._close(span=sp)\n"
        "    def good_pos(self, work):\n"
        "        sp = trace.start_span('a')\n"
        "        try:\n"
        "            return work()\n"
        "        finally:\n"
        "            self._close(sp)\n"
    )
    assert _g20_run(src, tmp_path) == []


def test_g20_ownership_transfer_shapes_are_silent(tmp_path):
    """Stored / returned / aliased / handed-to-opaque-callee spans are
    ownership transfers, not leaks (the serving_request lifecycle)."""
    src = (
        "def stored(req):\n"
        "    req.trace = trace.start_span('root')\n"
        "def returned():\n"
        "    sp = trace.start_span('root')\n"
        "    return sp\n"
        "def aliased():\n"
        "    sp = trace.start_span('root')\n"
        "    keep = sp\n"
        "    return keep\n"
        "def queued(q):\n"
        "    sp = trace.start_span('root')\n"
        "    q.put_nowait(sp)\n"
    )
    assert _g20_run(src, tmp_path) == []


def test_g20_historical_hedge_arm_shape_is_flagged(tmp_path):
    """The real pre-fix router bug: the hedge arm span ended in try AND
    except — no finally, so an exception in the except body (or an
    uncaught type) leaked it."""
    src = (
        "def run(results, dispatch, st):\n"
        "    arm = trace.start_span('router_hedge_arm')\n"
        "    try:\n"
        "        v = dispatch(st)\n"
        "        results.put_nowait((st, None, v))\n"
        "        arm.end(status='ok')\n"
        "    except BaseException as e:\n"
        "        results.put_nowait((st, e, None))\n"
        "        arm.end(status=type(e).__name__)\n"
    )
    found = _g20_run(src, tmp_path)
    assert len(found) == 1 and found[0].code == "G20"
    assert "never on a finally: path" in found[0].message


# -- --changed-only ----------------------------------------------------------

def _git(cwd, *args):
    out = subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t"] + list(args),
        cwd=cwd, capture_output=True, text=True, timeout=30)
    assert out.returncode == 0, out.stderr
    return out.stdout


def test_changed_only_selects_reverse_dependents(tmp_path):
    root = str(tmp_path)
    files = {
        "helper.py": "def f():\n    return 1\n",
        "caller.py": "import helper\n\n\ndef g():\n    return helper.f()\n",
        "indirect.py": "import caller\n\n\ndef h():\n    return caller.g()\n",
        "unrelated.py": "def z():\n    return 0\n",
    }
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    _git(root, "init", "-q")
    _git(root, "add", ".")
    _git(root, "commit", "-qm", "seed")
    (tmp_path / "helper.py").write_text("def f():\n    return 2\n")
    surface = set(files)
    got = lint_cli.changed_only_paths(root, "HEAD", surface=surface)
    # the edit + its transitive reverse importers; unrelated stays out
    assert got == ["caller.py", "helper.py", "indirect.py"]
    # untracked files count as changed
    (tmp_path / "fresh.py").write_text("x = 1\n")
    got = lint_cli.changed_only_paths(root, "HEAD",
                                      surface=surface | {"fresh.py"})
    assert "fresh.py" in got
    # a clean tree selects nothing
    _git(root, "add", ".")
    _git(root, "commit", "-qm", "apply")
    assert lint_cli.changed_only_paths(root, "HEAD",
                                       surface=surface) == []


def test_changed_only_cli_flags():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--changed-only",
         "HEAD", "mxnet_tpu/engine.py"],
        cwd=REPO, capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 2
    assert "own path set" in out.stderr
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--write-baseline",
         "--changed-only", "HEAD"],
        cwd=REPO, capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 2 and "clobber" in out.stderr


# -- doctor --lint -----------------------------------------------------------

def test_doctor_lint_report_shape():
    from mxnet_tpu.analysis.report import lint_report
    rep = lint_report(REPO)
    assert rep["ok"] is True
    assert rep["files"] > 200 and rep["new"] == 0
    assert rep["rules"] == {}              # empty-baseline steady state
    assert rep["wall_s"] > 0
    cache = rep["cache"]
    assert cache is None or set(cache) == {"hits", "misses", "hit_rate"}


def test_doctor_lint_report_on_broken_root(tmp_path):
    from mxnet_tpu.analysis.report import lint_report
    rep = lint_report(str(tmp_path))       # no .py files at all
    assert rep["ok"] is False and rep["error"] == "no_files"


# -- audit-fix regressions (runtime behavior) --------------------------------

def test_atomic_write_concurrent_same_path_never_tears(tmp_path):
    """The heartbeat-race fix at its root: per-call-unique staging
    temps let concurrent writers target one path safely — every
    observable state of the file is a complete document."""
    from mxnet_tpu.resilience.atomic import atomic_write
    path = str(tmp_path / "beacon.json")
    errors = []

    def hammer(tag):
        try:
            for i in range(100):
                with atomic_write(path, "w", durable=False) as f:
                    json.dump({"tag": tag, "i": i, "pad": "x" * 256}, f)
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)      # torn JSON would raise here
                assert set(doc) == {"tag", "i", "pad"}
        except Exception as e:              # surfaced to the main thread
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert errors == []
    leftovers = [n for n in os.listdir(tmp_path) if ".tmp." in n]
    assert leftovers == [], "clean exits must not litter temps"


def test_heartbeat_beat_concurrent_with_daemon(tmp_path):
    """PR-10's beat()-vs-daemon race, now without holding a lock across
    the write: concurrent beats keep the seq file a whole document and
    the seq strictly advances within each writer."""
    from mxnet_tpu.elastic.membership import Heartbeat
    hb = Heartbeat(str(tmp_path), 0, interval_s=0.005,
                   payload=lambda: {"ready": True})
    hb.start()
    try:
        for _ in range(200):
            hb.beat()                      # lifecycle publishes, racing
            with open(hb.path, encoding="utf-8") as f:
                doc = json.load(f)         # the daemon's own beats
            assert doc["member"] == 0 and "seq" in doc
    finally:
        hb.stop(resign=True)


def test_proc_restart_threads_deadline_into_stop_ladder():
    """The G19 audit fix: ProcReplica.restart(deadline_s=) must bound
    every wait in the stop ladder instead of dropping the budget."""
    import inspect

    from mxnet_tpu.serving.pool import ProcReplica
    src = inspect.getsource(ProcReplica.restart)
    assert "deadline_s" in src and "budget(" in src
    # and the summary engine agrees: the param is read
    ms = sm.module_summaries(_ctx(
        open(os.path.join(REPO, "mxnet_tpu/serving/pool.py"),
             encoding="utf-8").read(),
        path="mxnet_tpu/serving/pool.py"))
    s = ms.functions["ProcReplica.restart"]
    assert "deadline_s" in s.deadline_read

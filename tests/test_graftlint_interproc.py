"""graftlint v2 — the interprocedural tier: call-graph construction
(same-module resolution, base-class methods, nested defs), summary
extraction + cycle-safe fixpoint (blocking reach, lock orders, rank
taint), the fingerprint-keyed summary cache, ``--jobs`` parity,
``--changed-only`` selection with reverse import-graph dependents, the
doctor ``--lint`` report, and the audit fixes the engine drove
(heartbeat beat outside its lock on unique temps, restart deadlines
threaded)."""
import ast
import json
import os
import subprocess
import sys
import threading

import pytest

from mxnet_tpu.analysis import callgraph as cg
from mxnet_tpu.analysis import cli as lint_cli
from mxnet_tpu.analysis import core
from mxnet_tpu.analysis import summaries as sm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "graftlint")


def _ctx(src, path="mxnet_tpu/fake_mod.py"):
    return core.FileContext(path, src, ast.parse(src))


def _summ(src, cache=None):
    return sm.module_summaries(_ctx(src), cache=cache)


# -- call-graph construction -------------------------------------------------

def test_callgraph_resolves_self_module_and_base_methods():
    src = (
        "def helper():\n"
        "    return 1\n"
        "class Base:\n"
        "    def shared(self):\n"
        "        return helper()\n"
        "class Child(Base):\n"
        "    def go(self):\n"
        "        return self.shared()\n"
    )
    ctx = _ctx(src)
    index = cg.build_index(ctx)
    assert set(index.functions) == {"helper", "Base.shared", "Child.go"}
    call = next(n for n in ast.walk(index.functions["Child.go"].node)
                if isinstance(n, ast.Call))
    # self.shared() resolves through the same-module base chain
    assert cg.resolve_callee(index, call, "Child", "Child.go") == \
        "Base.shared"


def test_callgraph_nested_defs_are_separate_scopes():
    src = (
        "import time\n"
        "import threading\n"
        "_lk = threading.Lock()\n"
        "def outer():\n"
        "    def inner():\n"
        "        time.sleep(1)\n"
        "    with _lk:\n"
        "        return inner\n"            # DEFINED under the lock,
    )                                       # never CALLED under it
    ms = _summ(src)
    assert "outer.inner" in ms.functions
    # the sleep belongs to inner, and outer never calls it: no G15 food
    assert not ms.functions["outer"].blocks
    assert ("sleep", "time.sleep") in ms.reach["outer.inner"]
    assert ("sleep", "time.sleep") not in ms.reach["outer"]


# -- fixpoint ----------------------------------------------------------------

def test_fixpoint_converges_on_recursion_and_cycles():
    """a <-> b mutual recursion plus a self-recursive c: the monotone
    join must terminate and both cycle members must reach the sleep."""
    src = (
        "import time\n"
        "def a(n):\n"
        "    time.sleep(0.1)\n"
        "    return b(n - 1)\n"
        "def b(n):\n"
        "    return a(n) if n else 0\n"
        "def c(n):\n"
        "    return c(n - 1) if n else b(0)\n"
    )
    ms = _summ(src)
    for fn in ("a", "b", "c"):
        assert ("sleep", "time.sleep") in ms.reach[fn], fn
    path, line = ms.chain("c", ("sleep", "time.sleep"))
    assert path[0] == "c" and path[-1] == "a" and line == 3


def test_rank_taint_propagates_through_returns_and_cycles():
    src = (
        "import jax\n"
        "def direct():\n"
        "    return jax.process_index() == 0\n"
        "def hop():\n"
        "    v = direct()\n"
        "    return v\n"
        "def cycle_a():\n"
        "    return cycle_b() or hop()\n"
        "def cycle_b():\n"
        "    return cycle_a()\n"
        "def clean():\n"
        "    return 42\n"
    )
    ms = _summ(src)
    assert ms.rank_taint["direct"] and ms.rank_taint["hop"]
    assert ms.rank_taint["cycle_a"] and ms.rank_taint["cycle_b"]
    assert not ms.rank_taint["clean"]


def test_deadline_param_read_tracking_includes_closures():
    src = (
        "import queue\n"
        "q = queue.Queue(maxsize=2)\n"
        "def dropped(x, timeout_s):\n"
        "    return q.get(timeout=5.0)\n"
        "def threaded(x, timeout_s):\n"
        "    def attempt():\n"
        "        return q.get(timeout=timeout_s)\n"
        "    return attempt()\n"
    )
    ms = _summ(src)
    d, t = ms.functions["dropped"], ms.functions["threaded"]
    assert d.deadline_params == ["timeout_s"] and d.deadline_read == []
    assert t.deadline_read == ["timeout_s"]


def test_lock_regions_annotate_blocks_and_orders():
    src = (
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n"
        "                time.sleep(1)\n"
    )
    ms = _summ(src)
    s = ms.functions["C.one"]
    (kind, what, _line, held, _dl), = s.blocks
    assert kind == "sleep" and len(held) == 2
    (outer, _l1, held0), (inner, _l2, held1) = s.acq_with
    assert held0 == () and outer in held1


# -- summary cache -----------------------------------------------------------

def test_summary_cache_hit_equals_computed(tmp_path):
    path = os.path.join(FIXTURES, "g15_blocking_under_lock.py")
    src = open(path, encoding="utf-8").read()
    cache = sm.SummaryCache(str(tmp_path / "c.json"))
    cold = sm.module_summaries(
        core.FileContext(path, src, ast.parse(src)), cache=cache)
    assert cache.misses == 1 and cache.hits == 0
    warm = sm.module_summaries(
        core.FileContext(path, src, ast.parse(src)), cache=cache)
    assert cache.hits == 1
    assert warm.reach == cold.reach
    assert warm.trans_acquires == cold.trans_acquires
    assert {k: s.to_dict() for k, s in warm.functions.items()} == \
        {k: s.to_dict() for k, s in cold.functions.items()}


def test_summary_cache_invalidates_on_content_change(tmp_path):
    cache = sm.SummaryCache(str(tmp_path / "c.json"))
    _summ("def f():\n    return 1\n", cache=cache)
    _summ("def f():\n    return 2\n", cache=cache)   # edited: must MISS
    assert cache.misses == 2 and cache.hits == 0


def test_summary_cache_roundtrips_and_survives_corruption(tmp_path):
    cpath = str(tmp_path / "c.json")
    cache = sm.SummaryCache(cpath)
    src = "import time\ndef f():\n    time.sleep(1)\n"
    _summ(src, cache=cache)
    cache.save()
    reloaded = sm.SummaryCache.load(cpath)
    ms = _summ(src, cache=reloaded)
    assert reloaded.hits == 1
    assert ("sleep", "time.sleep") in ms.reach["f"]
    with open(cpath, "w") as f:
        f.write("{ corrupt json")
    broken = sm.SummaryCache.load(cpath)       # must not raise
    _summ(src, cache=broken)
    assert broken.misses == 1


def test_summary_cache_schema_bump_cold_starts(tmp_path):
    """A cache written by an older schema must be IGNORED wholesale,
    even when its entries are keyed by the current fingerprints: the
    race rules read summary fields (attrs/toctou/spawns) that v1
    entries simply don't carry, and serving a stale entry would mask
    every G22-G25 finding on a cache hit."""
    src = "def f():\n    return 1\n"
    cpath = str(tmp_path / "c.json")
    # forge a pre-G22 cache: right fingerprints, wrong schema version
    poisoned = {"version": sm._SCHEMA_VERSION - 1,
                "entries": {sm.fingerprint(src): {"bogus": True}}}
    with open(cpath, "w") as f:
        json.dump(poisoned, f)
    cache = sm.SummaryCache.load(cpath)
    assert cache._data == {}               # gated out at load
    ms = _summ(src, cache=cache)
    assert cache.misses == 1 and cache.hits == 0
    assert "f" in ms.functions             # recomputed, not the poison
    # and the rewrite persists under the CURRENT version
    cache.save()
    with open(cpath) as f:
        assert json.load(f)["version"] == sm._SCHEMA_VERSION


def test_findings_identical_with_and_without_cache(tmp_path):
    """The acceptance shape: a cache hit changes nothing about the
    findings — fingerprint pins the file text, lines included."""
    cache = sm.SummaryCache(str(tmp_path / "c.json"))
    prev = sm.set_active_cache(cache)
    try:
        first = core.run([FIXTURES], root=REPO)[0]
        second = core.run([FIXTURES], root=REPO)[0]
    finally:
        sm.set_active_cache(prev)
    assert cache.hits > 0
    nocache = core.run([FIXTURES], root=REPO)[0]
    as_key = lambda fs: [f.sort_key() for f in fs]
    assert as_key(first) == as_key(second) == as_key(nocache)


# -- --jobs parity -----------------------------------------------------------

def test_jobs_parallel_findings_match_serial():
    serial, n1 = core.run([FIXTURES], root=REPO)
    parallel, n2 = core.run([FIXTURES], root=REPO, jobs=4)
    assert n1 == n2
    assert [f.sort_key() for f in serial] == \
        [f.sort_key() for f in parallel]
    assert serial, "fixture corpus must produce findings"


# -- historical fixtures (the engine catches the real PR-9/10 bugs) ----------

def test_historical_latched_probe_is_flagged():
    path = os.path.join(FIXTURES, "hist_latched_probe.py")
    found = core.lint_file(path, rules=[core.load_rules()["G17"]],
                           root=REPO)
    assert len(found) == 1 and found[0].code == "G17"
    assert "latches the slot" in found[0].message


def test_historical_lock_held_ledger_io_is_flagged():
    path = os.path.join(FIXTURES, "hist_lock_held_ledger_io.py")
    found = core.lint_file(path, rules=[core.load_rules()["G15"]],
                           root=REPO)
    assert len(found) == 1 and found[0].code == "G15"
    assert "_view" in found[0].message     # names the call chain


def test_historical_heartbeat_overwrite_is_flagged():
    """The PR-11 beat() stale-overwrite, pre-fix: two locks that never
    meet on one document is exactly G23's inconsistent-lockset class."""
    path = os.path.join(FIXTURES, "hist_heartbeat_overwrite.py")
    found = core.lint_file(path, rules=[core.load_rules()["G23"]],
                           root=REPO)
    assert [(f.line, f.code) for f in found] == [(35, "G23")]
    assert "_doc" in found[0].message


def test_historical_probe_toctou_is_flagged():
    """The PR-9 half-open probe admission, pre-fix: membership checked
    and the slot claimed with no lock spanning the pair — G24."""
    path = os.path.join(FIXTURES, "hist_latched_probe_toctou.py")
    found = core.lint_file(path, rules=[core.load_rules()["G24"]],
                           root=REPO)
    assert [(f.line, f.code) for f in found] == [(32, "G24")]
    assert "_probing" in found[0].message


# -- race-detector engine (thread escape, entry locks) -----------------------

def test_thread_escape_roots_and_reachability():
    src = (
        "import threading\n"
        "class Worker(threading.Thread):\n"
        "    def run(self):\n"
        "        self.step()\n"
        "    def step(self):\n"
        "        return 1\n"
        "class Owner:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "        threading.Timer(1.0, self._expire).start()\n"
        "    def _loop(self):\n"
        "        self._tick()\n"
        "    def _expire(self):\n"
        "        pass\n"
        "    def _tick(self):\n"
        "        pass\n"
        "    def untouched(self):\n"
        "        pass\n"
    )
    ms = _summ(src)
    assert ms.thread_roots == {"Worker.run", "Owner._loop",
                               "Owner._expire"}
    # reachability follows call edges out of the roots
    assert {"Worker.step", "Owner._tick"} <= ms.thread_reachable
    assert "Owner.untouched" not in ms.thread_reachable
    assert "Owner.start" not in ms.thread_reachable


def test_thread_escape_callback_registration():
    src = (
        "class Bus:\n"
        "    def subscribe(self, reg):\n"
        "        reg.add_callback(self._on_event)\n"
        "    def _on_event(self, msg):\n"
        "        self._handle(msg)\n"
        "    def _handle(self, msg):\n"
        "        pass\n"
    )
    ms = _summ(src)
    assert "Bus._on_event" in ms.thread_roots
    assert "Bus._handle" in ms.thread_reachable


def test_entry_locks_credit_private_helpers():
    """A private helper whose every same-module caller holds the lock
    inherits it as an entry lock; a public method stays open-entry
    (external callers are assumed lockless)."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def public(self):\n"
        "        with self._lock:\n"
        "            self._bump()\n"
        "    def other(self):\n"
        "        with self._lock:\n"
        "            self._bump()\n"
        "    def _bump(self):\n"
        "        pass\n"
    )
    ms = _summ(src)
    assert ms.entry_locks["C._bump"] == {"C::self._lock"}
    assert ms.entry_locks["C.public"] == set()
    # one lockless caller breaks the credit
    ms2 = _summ(src + "    def sloppy(self):\n        self._bump()\n")
    assert ms2.entry_locks["C._bump"] == set()


def test_nested_def_sibling_thread_target_resolves():
    """The router hedge shape: ``Thread(target=run)`` from inside a
    sibling nested def — the target must resolve through the enclosing
    method's scope, and ``self.m()`` from the nested def through the
    enclosing class."""
    src = (
        "import threading\n"
        "class R:\n"
        "    def dispatch(self):\n"
        "        def run():\n"
        "            self._attempt()\n"
        "        def launch():\n"
        "            threading.Thread(target=run).start()\n"
        "        launch()\n"
        "    def _attempt(self):\n"
        "        pass\n"
    )
    ms = _summ(src)
    assert "R.dispatch.run" in ms.thread_roots
    assert "R._attempt" in ms.thread_reachable


# -- the audited subsystems stay clean ---------------------------------------

@pytest.mark.parametrize("subsystem", [
    "mxnet_tpu/serving", "mxnet_tpu/elastic", "mxnet_tpu/observability",
    "mxnet_tpu/diagnostics", "mxnet_tpu/resilience"])
def test_concurrency_rules_clean_on_audited_subsystems(subsystem):
    """The audit-and-fix acceptance: every live G15-G20 finding was
    fixed (router/fleet transition journaling deferred past the locks,
    heartbeat write outside its lock, restart deadlines threaded, the
    hedge-arm span restructured onto `with`), none baselined."""
    registry = core.load_rules()
    rules = [registry[c]
             for c in ("G15", "G16", "G17", "G18", "G19", "G20",
                       "G22", "G23", "G24", "G25")]
    findings, n = core.run([subsystem], rules=rules, root=REPO)
    assert n >= 4 and findings == []


# -- G20 leaked-open-span -----------------------------------------------------

_G20_PRELUDE = "from mxnet_tpu.observability import trace\n"


def _g20_run(src, tmp_path):
    path = tmp_path / "fake_spans.py"
    path.write_text("# graftlint: scope=library\n" + _G20_PRELUDE + src)
    return core.lint_file(str(path), rules=[core.load_rules()["G20"]],
                          root=str(tmp_path))


def test_g20_param_end_fixpoint_two_hops(tmp_path):
    """A finally-called helper that forwards the span to ANOTHER helper
    that ends it counts as exception-safe — the param-position fixpoint
    follows the chain; the SAME helper on a straight-line path does
    not (a raise before it leaks the span), and a helper that merely
    annotates transfers nothing (silent handoff, documented limit)."""
    src = (
        "def _really_close(sp, status='ok'):\n"
        "    sp.end(status=status)\n"
        "def _close(span):\n"
        "    _really_close(span)\n"
        "def _annotate(span):\n"
        "    span.set_attrs(seen=True)\n"
        "def good(work):\n"
        "    sp = trace.start_span('a')\n"
        "    try:\n"
        "        return work()\n"
        "    finally:\n"
        "        _close(sp)\n"
        "def bad(work):\n"
        "    sp = trace.start_span('a')\n"
        "    out = work()\n"      # a raise here leaks sp: _close is
        "    _close(sp)\n"        # straight-line, not finally
        "    return out\n"
    )
    found = _g20_run(src, tmp_path)
    assert len(found) == 1 and found[0].code == "G20"
    assert "never on a finally: path" in found[0].message
    # the finding sits on bad()'s open, not good()'s
    assert "start_span('a')" in open(tmp_path / "fake_spans.py")\
        .read().splitlines()[found[0].line - 1]
    assert found[0].line > 10


def test_g20_keyword_forwarding_and_method_offset(tmp_path):
    """self-method helpers (param offset past ``self``) and keyword
    forwarding both resolve to the right param position."""
    src = (
        "class R:\n"
        "    def _close(self, span, status='ok'):\n"
        "        span.end(status=status)\n"
        "    def good_kw(self, work):\n"
        "        sp = trace.start_span('a')\n"
        "        try:\n"
        "            return work()\n"
        "        finally:\n"
        "            self._close(span=sp)\n"
        "    def good_pos(self, work):\n"
        "        sp = trace.start_span('a')\n"
        "        try:\n"
        "            return work()\n"
        "        finally:\n"
        "            self._close(sp)\n"
    )
    assert _g20_run(src, tmp_path) == []


def test_g20_ownership_transfer_shapes_are_silent(tmp_path):
    """Stored / returned / aliased / handed-to-opaque-callee spans are
    ownership transfers, not leaks (the serving_request lifecycle)."""
    src = (
        "def stored(req):\n"
        "    req.trace = trace.start_span('root')\n"
        "def returned():\n"
        "    sp = trace.start_span('root')\n"
        "    return sp\n"
        "def aliased():\n"
        "    sp = trace.start_span('root')\n"
        "    keep = sp\n"
        "    return keep\n"
        "def queued(q):\n"
        "    sp = trace.start_span('root')\n"
        "    q.put_nowait(sp)\n"
    )
    assert _g20_run(src, tmp_path) == []


def test_g20_historical_hedge_arm_shape_is_flagged(tmp_path):
    """The real pre-fix router bug: the hedge arm span ended in try AND
    except — no finally, so an exception in the except body (or an
    uncaught type) leaked it."""
    src = (
        "def run(results, dispatch, st):\n"
        "    arm = trace.start_span('router_hedge_arm')\n"
        "    try:\n"
        "        v = dispatch(st)\n"
        "        results.put_nowait((st, None, v))\n"
        "        arm.end(status='ok')\n"
        "    except BaseException as e:\n"
        "        results.put_nowait((st, e, None))\n"
        "        arm.end(status=type(e).__name__)\n"
    )
    found = _g20_run(src, tmp_path)
    assert len(found) == 1 and found[0].code == "G20"
    assert "never on a finally: path" in found[0].message


# -- --changed-only ----------------------------------------------------------

def _git(cwd, *args):
    out = subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t"] + list(args),
        cwd=cwd, capture_output=True, text=True, timeout=30)
    assert out.returncode == 0, out.stderr
    return out.stdout


def test_changed_only_selects_reverse_dependents(tmp_path):
    root = str(tmp_path)
    files = {
        "helper.py": "def f():\n    return 1\n",
        "caller.py": "import helper\n\n\ndef g():\n    return helper.f()\n",
        "indirect.py": "import caller\n\n\ndef h():\n    return caller.g()\n",
        "unrelated.py": "def z():\n    return 0\n",
    }
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    _git(root, "init", "-q")
    _git(root, "add", ".")
    _git(root, "commit", "-qm", "seed")
    (tmp_path / "helper.py").write_text("def f():\n    return 2\n")
    surface = set(files)
    got = lint_cli.changed_only_paths(root, "HEAD", surface=surface)
    # the edit + its transitive reverse importers; unrelated stays out
    assert got == ["caller.py", "helper.py", "indirect.py"]
    # untracked files count as changed
    (tmp_path / "fresh.py").write_text("x = 1\n")
    got = lint_cli.changed_only_paths(root, "HEAD",
                                      surface=surface | {"fresh.py"})
    assert "fresh.py" in got
    # a clean tree selects nothing
    _git(root, "add", ".")
    _git(root, "commit", "-qm", "apply")
    assert lint_cli.changed_only_paths(root, "HEAD",
                                       surface=surface) == []


def test_changed_only_cli_flags():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--changed-only",
         "HEAD", "mxnet_tpu/engine.py"],
        cwd=REPO, capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 2
    assert "own path set" in out.stderr
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--write-baseline",
         "--changed-only", "HEAD"],
        cwd=REPO, capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 2 and "clobber" in out.stderr


# -- doctor --lint -----------------------------------------------------------

def test_doctor_lint_report_shape():
    from mxnet_tpu.analysis.report import lint_report
    rep = lint_report(REPO)
    assert rep["ok"] is True
    assert rep["files"] > 200 and rep["new"] == 0
    assert rep["rules"] == {}              # empty-baseline steady state
    assert rep["wall_s"] > 0
    cache = rep["cache"]
    assert cache is None or set(cache) == {"hits", "misses", "hit_rate"}
    # per-rule cost/yield: every race rule reports, raw counts include
    # the inline-disabled pool.py builder writes (they cost detection
    # time even though suppressed from the finding list)
    stats = rep["rule_stats"]
    for code in ("G22", "G23", "G24", "G25"):
        assert set(stats[code]) == {"wall_ms", "findings"}
        assert stats[code]["wall_ms"] >= 0
    assert stats["G22"]["findings"] >= 2   # the audited pool.py writes
    assert sum(s["wall_ms"] for s in stats.values()) <= \
        rep["wall_s"] * 1000.0


def test_doctor_lint_report_on_broken_root(tmp_path):
    from mxnet_tpu.analysis.report import lint_report
    rep = lint_report(str(tmp_path))       # no .py files at all
    assert rep["ok"] is False and rep["error"] == "no_files"


# -- audit-fix regressions (runtime behavior) --------------------------------

def test_atomic_write_concurrent_same_path_never_tears(tmp_path):
    """The heartbeat-race fix at its root: per-call-unique staging
    temps let concurrent writers target one path safely — every
    observable state of the file is a complete document."""
    from mxnet_tpu.resilience.atomic import atomic_write
    path = str(tmp_path / "beacon.json")
    errors = []

    def hammer(tag):
        try:
            for i in range(100):
                with atomic_write(path, "w", durable=False) as f:
                    json.dump({"tag": tag, "i": i, "pad": "x" * 256}, f)
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)      # torn JSON would raise here
                assert set(doc) == {"tag", "i", "pad"}
        except Exception as e:              # surfaced to the main thread
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert errors == []
    leftovers = [n for n in os.listdir(tmp_path) if ".tmp." in n]
    assert leftovers == [], "clean exits must not litter temps"


def test_heartbeat_beat_concurrent_with_daemon(tmp_path):
    """PR-10's beat()-vs-daemon race, now without holding a lock across
    the write: concurrent beats keep the seq file a whole document and
    the seq strictly advances within each writer."""
    from mxnet_tpu.elastic.membership import Heartbeat
    hb = Heartbeat(str(tmp_path), 0, interval_s=0.005,
                   payload=lambda: {"ready": True})
    hb.start()
    try:
        for _ in range(200):
            hb.beat()                      # lifecycle publishes, racing
            with open(hb.path, encoding="utf-8") as f:
                doc = json.load(f)         # the daemon's own beats
            assert doc["member"] == 0 and "seq" in doc
    finally:
        hb.stop(resign=True)


def test_proc_restart_threads_deadline_into_stop_ladder():
    """The G19 audit fix: ProcReplica.restart(deadline_s=) must bound
    every wait in the stop ladder instead of dropping the budget."""
    import inspect

    from mxnet_tpu.serving.pool import ProcReplica
    src = inspect.getsource(ProcReplica.restart)
    assert "deadline_s" in src and "budget(" in src
    # and the summary engine agrees: the param is read
    ms = sm.module_summaries(_ctx(
        open(os.path.join(REPO, "mxnet_tpu/serving/pool.py"),
             encoding="utf-8").read(),
        path="mxnet_tpu/serving/pool.py"))
    s = ms.functions["ProcReplica.restart"]
    assert "deadline_s" in s.deadline_read

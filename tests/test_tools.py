"""tools/ tests: im2rec list+pack round-trip, launch.py local mode env
wiring, parse_log (ref: the reference's tools/ + nightly launcher tests)."""
import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_im2rec_list_and_pack(tmp_path):
    import cv2
    # build a tiny class-folder dataset
    for cls in ("cat", "dog"):
        d = tmp_path / "data" / cls
        d.mkdir(parents=True)
        for i in range(3):
            img = (np.random.rand(20, 20, 3) * 255).astype(np.uint8)
            cv2.imwrite(str(d / f"{i}.png"), img)
    prefix = str(tmp_path / "pack")
    root = str(tmp_path / "data")
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run([sys.executable, os.path.join(REPO, "tools",
                                                     "im2rec.py"),
                        "--list", "--recursive", prefix, root],
                       capture_output=True, env=env, text=True, timeout=600)
    assert r.returncode == 0, r.stderr
    lst = open(prefix + ".lst").read().strip().splitlines()
    assert len(lst) == 6
    r = subprocess.run([sys.executable, os.path.join(REPO, "tools",
                                                     "im2rec.py"),
                        "--encoding", ".png", prefix, root],
                       capture_output=True, env=env, text=True, timeout=600)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".rec")
    assert os.path.exists(prefix + ".idx")
    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(rec.keys) == 6
    header, img = recordio.unpack_img(rec.read_idx(rec.keys[0]))
    assert img.shape == (20, 20, 3)


def test_launch_local_env(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, json, sys\n"
        "out = {k: os.environ[k] for k in"
        " ('MXTPU_PROC_ID', 'MXTPU_NUM_PROC', 'MXTPU_COORD_ADDR',"
        "  'DMLC_ROLE')}\n"
        "path = os.path.join(os.path.dirname(__file__),"
        " f\"out_{out['MXTPU_PROC_ID']}.json\")\n"
        "json.dump(out, open(path, 'w'))\n")
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "launch.py"),
                        "-n", "3", "--launcher", "local",
                        sys.executable, str(script)],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr
    ranks = set()
    for i in range(3):
        data = json.load(open(tmp_path / f"out_{i}.json"))
        ranks.add(data["MXTPU_PROC_ID"])
        assert data["MXTPU_NUM_PROC"] == "3"
        assert data["DMLC_ROLE"] == "worker"
    assert ranks == {"0", "1", "2"}


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "INFO:root:Epoch[0] Batch [50]\tSpeed: 1000.00 samples/sec\t"
        "accuracy=0.5\n"
        "INFO:root:Epoch[0] Train-accuracy=0.612\n"
        "INFO:root:Epoch[0] Time cost=12.5\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.587\n"
        "INFO:root:Epoch[1] Train-accuracy=0.701\n")
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "parse_log.py"),
                        str(log), "--format", "csv"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0].startswith("epoch,")
    assert "0.612" in lines[1] and "0.587" in lines[1]

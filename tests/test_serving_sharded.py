"""Tensor-parallel serving (serving/shardplan.py, docs/serving.md).

Acceptance criteria: on a >= 2-device CPU mesh a sharded predictor
serves bit-identically to the single-device reference (the default rule
column-shards the OUTPUT dim, so no reduction crosses shards);
checkpoint weights land on the serving mesh through the SAME
``elastic.reshard`` placement the elastic restore path uses
(``place_named`` at startup, ``place_global``-style adoption on hot
reload); and an AOT warm restart of a sharded replica performs ZERO XLA
compiles (the mesh signature joins the cache key).  The ``smoke`` test
runs in CI tier 0.5.
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import observability as obs
from mxnet_tpu.diagnostics.journal import reset_journal
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving import Server, ServerConfig
from mxnet_tpu.serving.shardplan import (ShardPlan, parse_axes,
                                         plan_from_env)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def journal_file(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    reset_journal(path)
    try:
        yield path
    finally:
        reset_journal("stderr")


def _records(path, kind=None):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def _mlp(dim=8, seed=11):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=dim))
        net.add(nn.Dense(4, in_units=16))
    net.initialize()
    return net


def _snapshot(block):
    """Host copies of every parameter, keyed structurally — the
    weight-clone idiom the fleet's page-out uses."""
    out = {}
    for name, param in block._structural_names().items():
        arr = param.data(param.list_ctx()[0])
        out[name] = np.asarray(getattr(arr, "_data", arr))
    return out


def _clone_into(dst, src):
    from mxnet_tpu import nd
    dst.load_dict({k: nd.array(v) for k, v in _snapshot(src).items()},
                  ignore_extra=True)


def _plan(n=2, **kw):
    import jax
    if len(jax.devices()) < n:
        pytest.skip(f"needs >= {n} devices")
    return ShardPlan(axes={"model": n}, devices=jax.devices()[:n], **kw)


# -- spec derivation ---------------------------------------------------------

def test_default_rule_shards_the_output_dim():
    """MXNet blocks store (out, in): the tensor-parallel default is
    P("model", None) — a column-split matmul that concatenates, never
    reduces, so sharded outputs are bit-identical by construction."""
    plan = _plan()
    assert tuple(plan.param_spec("dense0_weight", (16, 8))) == \
        ("model", None)
    # vectors/scalars replicate (a sharded bias would change the math)
    assert tuple(plan.param_spec("dense0_bias", (16,))) == ()
    # 4-D conv kernels shard dim 0 (out channels) too
    assert tuple(plan.param_spec("conv0_weight", (16, 3, 3, 3))) == \
        ("model", None, None, None)


def test_indivisible_dims_degrade_to_replication():
    plan = _plan()
    assert tuple(plan.param_spec("odd_weight", (7, 8))) == (None, None)
    assert "odd_weight" in plan.degraded


def test_param_rules_override_the_default():
    from jax.sharding import PartitionSpec as P
    plan = _plan(param_rules=((r"_weight$", P(None, "model")),))
    # an (in, out) layout opts into row sharding via rules
    assert tuple(plan.param_spec("dense0_weight", (16, 8))) == \
        (None, "model")


def test_parse_axes_and_env_plan(monkeypatch):
    assert parse_axes("model=-1") == {"model": -1}
    assert parse_axes("batch=2, model=4") == {"batch": 2, "model": 4}
    monkeypatch.delenv("MXNET_TPU_SERVING_MESH", raising=False)
    assert plan_from_env() is None
    monkeypatch.setenv("MXNET_TPU_SERVING_MESH", "off")
    assert plan_from_env() is None
    import jax
    if len(jax.devices()) >= 2:
        monkeypatch.setenv("MXNET_TPU_SERVING_MESH", "model=2")
        plan = plan_from_env(devices=jax.devices()[:2])
        assert plan is not None and plan.axes == {"model": 2}


# -- weight placement rides elastic.reshard ----------------------------------

def test_place_named_lands_the_planned_sharding():
    from jax.sharding import NamedSharding

    from mxnet_tpu.elastic.reshard import place_named
    plan = _plan()
    host = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    spec = plan.param_spec("w_weight", host.shape)
    arr = place_named("w_weight", plan.mesh, spec, host)
    assert isinstance(arr.sharding, NamedSharding)
    assert arr.sharding == plan.param_sharding("w_weight", host.shape)
    np.testing.assert_array_equal(np.asarray(arr), host)
    # each shard holds exactly its row slice (really partitioned, not
    # replicated under a named label)
    assert arr.addressable_shards[0].data.shape == (8, 8)


def test_place_global_preserves_the_serving_sharding():
    """Hot reload drops host entries onto the LIVE array's sharding —
    the compiled predictors were lowered against those placements."""
    from mxnet_tpu.elastic.reshard import place_global, place_named
    plan = _plan()
    spec = plan.param_spec("w_weight", (16, 8))
    cur = place_named("w_weight", plan.mesh, spec,
                      np.zeros((16, 8), np.float32))
    host = np.random.default_rng(0).standard_normal((16, 8)) \
        .astype(np.float32)
    arr = place_global("w_weight", cur, host)
    assert arr.sharding == cur.sharding
    np.testing.assert_array_equal(np.asarray(arr), host)


def test_plan_place_and_adopt_entries(journal_file):
    from jax.sharding import NamedSharding
    plan = _plan()
    net = _mlp()
    plan.place(net, site="test_place")
    recs = _records(journal_file, "shard_place")
    assert recs and recs[-1]["site"] == "test_place"
    assert recs[-1]["mesh"]["axes"] == {"model": 2}
    shardings = {}
    for name, param in net._structural_names().items():
        arr = param.data(param.list_ctx()[0])._data
        assert isinstance(arr.sharding, NamedSharding)
        shardings[name] = arr.sharding
    # adopt_entries swaps VALUES while every placement survives
    new = {k: v + 1.0 for k, v in _snapshot(net).items()}
    plan.adopt_entries(net, new)
    for name, param in net._structural_names().items():
        arr = param.data(param.list_ctx()[0])._data
        assert arr.sharding == shardings[name]
        np.testing.assert_array_equal(np.asarray(arr), new[name])


# -- the serving acceptance criteria -----------------------------------------

def test_smoke_sharded_predictor_bit_identical_to_single_device(
        journal_file):
    """The tier-0.5 sharded smoke: the SAME weights served through a
    2-device tensor-parallel Server and a plain single-device Server
    answer bit-identically across bucket shapes, and the placement is
    journaled."""
    plan = _plan()
    ref_net, tp_net = _mlp(), _mlp(seed=99)
    _clone_into(tp_net, ref_net)
    ref = Server(ref_net, config=ServerConfig(window_ms=1.0)).start()
    tp = Server(tp_net, config=ServerConfig(window_ms=1.0,
                                            shard_plan=plan)).start()
    try:
        rng = np.random.default_rng(5)
        for n in (1, 3, 8):
            xs = [rng.standard_normal(8).astype(np.float32)
                  for _ in range(n)]
            for x in xs:
                a = np.asarray(ref.predict(x))
                b = np.asarray(tp.predict(x))
                np.testing.assert_array_equal(a, b)
    finally:
        ref.stop()
        tp.stop()
    recs = _records(journal_file, "shard_place")
    assert recs and recs[-1]["site"] == "serving_start"


def test_sharded_through_router_matches_single_device(tmp_path):
    from mxnet_tpu.serving.pool import PoolConfig, ReplicaPool
    from mxnet_tpu.serving.router import Router, RouterConfig
    ref_net = _mlp()
    snap = _snapshot(ref_net)

    def factory():
        from mxnet_tpu import nd
        net = _mlp(seed=123)
        net.load_dict({k: nd.array(v) for k, v in snap.items()},
                      ignore_extra=True)
        return Server(net, config=ServerConfig(
            window_ms=1.0, shard_plan=_plan()))

    ref = Server(ref_net, config=ServerConfig(window_ms=1.0)).start()
    pool = ReplicaPool(str(tmp_path / "pool"),
                       PoolConfig(heartbeat_s=0.1, deadline_s=2.0))
    pool.add_local("tp0", factory)
    pool.start()
    router = Router(pool, RouterConfig(hedge_ms=-1.0))
    try:
        x = np.random.default_rng(9).standard_normal(8) \
            .astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(router.call(x, deadline_ms=10000).value),
            np.asarray(ref.predict(x)))
    finally:
        router.stop()
        pool.stop()
        ref.stop()


def test_sharded_warm_restart_zero_compiles(tmp_path):
    """AOT warm restart of a tensor-parallel replica: the second start
    on the same cache dir (same mesh) loads every warmed bucket with
    ZERO XLA compiles and answers bit-identically."""
    root = str(tmp_path / "aot")
    snap = None
    x = np.ones(8, np.float32)

    def boot():
        nonlocal snap
        from mxnet_tpu import nd
        net = _mlp()
        if snap is None:
            snap = _snapshot(net)
        else:
            net.load_dict({k: nd.array(v) for k, v in snap.items()},
                          ignore_extra=True)
        cfg = ServerConfig(window_ms=1.0, shard_plan=_plan(),
                           aot_dir=root, aot_prewarm=((8,),))
        return Server(net, config=cfg).start()

    obs.reset_metrics()
    cold = boot()
    try:
        y_cold = np.asarray(cold.predict(x))
        assert obs.compile_stats()["compiles"] > 0
        assert cold.stats()["aot"]["stores"] > 0
    finally:
        cold.stop()

    obs.reset_metrics()
    warm = boot()
    try:
        y_warm = np.asarray(warm.predict(x))
        cs = obs.compile_stats()
        assert cs["compiles"] == 0, cs     # the zero-cold-start proof
        assert cs["aot_loads"] > 0
        np.testing.assert_array_equal(y_cold, y_warm)
    finally:
        warm.stop()

    # a DIFFERENT mesh shape must NOT load those entries (key includes
    # the mesh signature): 4-device boot compiles fresh
    import jax
    if len(jax.devices()) >= 4:
        from mxnet_tpu import nd
        net = _mlp(seed=321)
        net.load_dict({k: nd.array(v) for k, v in snap.items()},
                      ignore_extra=True)
        cfg = ServerConfig(window_ms=1.0, shard_plan=_plan(4),
                           aot_dir=root, aot_prewarm=((8,),))
        obs.reset_metrics()
        other = Server(net, config=cfg).start()
        try:
            np.testing.assert_array_equal(np.asarray(other.predict(x)),
                                          y_cold)
            assert obs.compile_stats()["compiles"] > 0
        finally:
            other.stop()

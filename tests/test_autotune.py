"""Closed-loop autotuner (mxnet_tpu/autotune/, docs/autotune.md).

Acceptance criteria under test: tuned tables are CRC/format/schema/
envelope-validated BEFORE any knob is believed, every failure degrades
to built-in defaults with ONE journaled ``tuned_fallback{reason}``
(never a crash); runtime consumers (pallas.dispatch, Server, Router)
demonstrably read tuned values with journaled ``tuned_load`` and
explicit env/constructor values win over the table; a concurrent
``apply`` against a reading runtime always lands intact old-or-new; a
``block=`` override through the Pallas registry is bit-identical to the
default; and the ``search`` CLI explores ≥ 2 knob families end to end
on CPU with every trial journaled and the committed winner measuring
≥ the built-in default on the same harness (the default is trial #1 by
construction).  The ``smoke`` tests run in CI tier 0.5.
"""
import json
import os
import random
import subprocess
import sys
import threading

import numpy as np
import pytest

from mxnet_tpu.autotune import runner as atrunner
from mxnet_tpu.autotune import search as atsearch
from mxnet_tpu.autotune import space as atspace
from mxnet_tpu.autotune import table as attable
from mxnet_tpu.diagnostics.journal import reset_journal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def journal_file(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    reset_journal(path)
    try:
        yield path
    finally:
        reset_journal("stderr")


@pytest.fixture
def tuned_env(tmp_path):
    """Point MXNET_TPU_TUNED_TABLE at a scratch path and reset every
    tuned cache; restore on exit."""
    from mxnet_tpu.pallas import registry
    path = str(tmp_path / "tuned_table.json")
    old = os.environ.get(attable.ENV_TABLE)
    old_mode = os.environ.pop("MXNET_TPU_PALLAS", None)  # order-proof
    os.environ[attable.ENV_TABLE] = path
    attable.reset_cache()
    registry.reset_provenance()
    try:
        yield path
    finally:
        if old is None:
            os.environ.pop(attable.ENV_TABLE, None)
        else:
            os.environ[attable.ENV_TABLE] = old
        if old_mode is not None:
            os.environ["MXNET_TPU_PALLAS"] = old_mode
        attable.reset_cache()
        registry.reset_provenance()


def _records(path, kind=None):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def _table_doc(**knobs):
    knobs = knobs or {"serving": {"window_ms": 2.0, "max_queue": 64}}
    return attable.build_table(knobs, provenance={"trials": 1},
                               envelope=attable.current_envelope())


def _mlp(dim=8):
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=dim))
    net.initialize()
    return net


# ---------------------------------------------------------------------------
# table: roundtrip + audit
# ---------------------------------------------------------------------------
class TestTableRoundtrip:
    def test_build_commit_read_smoke(self, tmp_path, journal_file):
        doc = _table_doc(pallas={"conv_epilogue":
                                 {"64x32": {"block": [16, 16]}}},
                         serving={"window_ms": 2.0})
        path = str(tmp_path / "t.json")
        attable.commit_table(doc, path)
        got, reason = attable.read_table(
            path, envelope=attable.current_envelope())
        assert reason is None
        assert got == doc
        assert attable.pallas_entry(got, "conv_epilogue",
                                    "64x32")["block"] == [16, 16]
        assert attable.knob(got, "serving", "window_ms") == 2.0
        kinds = [r["kind"] for r in _records(journal_file)]
        assert "tuned_commit" in kinds

    def test_wildcard_shape_class(self):
        doc = _table_doc(pallas={"conv_epilogue":
                                 {"*": {"block": [8, 8]}}})
        assert attable.pallas_entry(doc, "conv_epilogue",
                                    "999x999")["block"] == [8, 8]
        assert attable.pallas_entry(doc, "other_kernel", "8x8") is None

    def test_builder_rejects_malformed(self):
        with pytest.raises(ValueError):
            attable.build_table({"serving": {"window_ms": "fast"}},
                                envelope={"platform": "cpu",
                                          "device_kind": "cpu",
                                          "jax": "x"})
        with pytest.raises(ValueError):
            attable.build_table({"nonsense_family": {"x": 1}},
                                envelope={"platform": "cpu",
                                          "device_kind": "cpu",
                                          "jax": "x"})

    def test_commit_refuses_stale_crc(self, tmp_path):
        doc = _table_doc()
        doc["knobs"]["serving"]["window_ms"] = 9.0   # crc now stale
        with pytest.raises(ValueError):
            attable.commit_table(doc, str(tmp_path / "t.json"))

    def test_audit_is_stdlib_and_reports_knobs(self, tmp_path):
        doc = _table_doc(serving={"window_ms": 3.0},
                         router={"hedge_ms": 5.0})
        path = str(tmp_path / "t.json")
        attable.commit_table(doc, path)
        rep = attable.audit_table(path)
        assert rep["ok"] and rep["envelope_checked"] is False
        assert rep["knobs"]["serving.window_ms"] == 3.0
        assert rep["knobs"]["router.hedge_ms"] == 5.0
        bad = attable.audit_table(str(tmp_path / "nope.json"))
        assert bad == {"ok": False, "path": str(tmp_path / "nope.json"),
                       "error": "missing"}


# ---------------------------------------------------------------------------
# corruption / truncation / envelope fuzz matrix (satellite 3)
# ---------------------------------------------------------------------------
def _mutations():
    """(name, mutate(path), expected_reason) matrix over one committed
    table file."""
    def truncate(path):
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[:len(raw) // 2])

    def bitflip(path):
        raw = bytearray(open(path, "rb").read())
        # flip inside a knob value, far from the braces, keeping JSON
        # parseable most of the time — the CRC must catch it either way
        idx = raw.rindex(b"window_ms") + len(b"window_ms") + 3
        raw[idx] ^= 0x01
        open(path, "wb").write(bytes(raw))

    def garbage(path):
        open(path, "wb").write(b"\x00\xffnot json at all")

    def wrong_format(path):
        doc = json.load(open(path))
        doc["format"] = "mxtpu-tuned-v999"
        json.dump(doc, open(path, "w"))

    def bad_schema(path):
        doc = json.load(open(path))
        doc["knobs"]["serving"]["window_ms"] = "fast"
        doc["crc32"] = attable.table_crc(doc)   # valid CRC, bad schema
        json.dump(doc, open(path, "w"))

    def oversize(path):
        with open(path, "ab") as f:
            f.write(b" " * (attable.MAX_TABLE_BYTES + 1))

    def delete(path):
        os.remove(path)

    return [
        ("truncated", truncate, ("json", "crc")),
        ("bitflip", bitflip, ("crc", "json")),
        ("garbage", garbage, ("json",)),
        ("wrong_format", wrong_format, ("format",)),
        ("bad_schema", bad_schema, ("schema:serving.window_ms",)),
        ("oversize", oversize, ("too_large",)),
        ("deleted", delete, ("missing",)),
    ]


class TestCorruptionMatrix:
    @pytest.mark.parametrize(
        "name,mutate,expected",
        _mutations(), ids=[m[0] for m in _mutations()])
    def test_fuzz_degrades_with_exact_reason_smoke(
            self, name, mutate, expected, tuned_env, journal_file):
        attable.commit_table(_table_doc(), tuned_env)
        mutate(tuned_env)
        attable.reset_cache()
        doc = attable.tuned_for("test")       # must not raise
        assert doc is None
        falls = _records(journal_file, "tuned_fallback")
        assert len(falls) == 1, falls
        assert falls[0]["reason"] in expected
        assert falls[0]["fallback"] == "builtin_defaults"
        assert falls[0]["site"] == "test"
        # deduped: consulting again journals nothing new
        attable.tuned_for("test")
        assert len(_records(journal_file, "tuned_fallback")) == 1

    def test_envelope_mismatch_and_stale(self, tuned_env, journal_file):
        env = dict(attable.current_envelope())
        for mutated, expected in (
                (dict(env, platform="tpu"), "envelope"),
                (dict(env, device_kind="TPU v4"), "envelope"),
                (dict(env, jax=env["jax"] + ".post1"), "stale")):
            attable.commit_table(
                attable.build_table(
                    {"serving": {"window_ms": 2.0}}, envelope=mutated),
                tuned_env)
            attable.reset_cache()
            with open(journal_file, "w"):
                pass                          # truncate between cases
            assert attable.tuned_for("test") is None
            falls = _records(journal_file, "tuned_fallback")
            assert [f["reason"] for f in falls] == [expected]

    def test_loader_picks_up_recommit(self, tuned_env):
        attable.commit_table(_table_doc(serving={"window_ms": 2.0}),
                             tuned_env)
        attable.reset_cache()
        assert attable.knob(attable.tuned_for("t"), "serving",
                            "window_ms") == 2.0
        attable.commit_table(_table_doc(serving={"window_ms": 9.0}),
                             tuned_env)
        attable.reset_cache()                 # bypass the 1s throttle
        assert attable.knob(attable.tuned_for("t"), "serving",
                            "window_ms") == 9.0


class TestConcurrentApply:
    def test_apply_vs_read_lands_old_or_new(self, tmp_path):
        """A writer re-committing A/B tables while readers validate:
        every successful read is exactly doc A or doc B — never torn,
        never a crash (the atomic_write + CRC contract)."""
        path = str(tmp_path / "t.json")
        doc_a = _table_doc(serving={"window_ms": 1.0})
        doc_b = _table_doc(serving={"window_ms": 20.0})
        attable.commit_table(doc_a, path)
        stop = threading.Event()
        bad = []

        def writer():
            i = 0
            while not stop.is_set():
                attable.commit_table(doc_b if i % 2 else doc_a, path)
                i += 1

        def reader():
            while not stop.is_set():
                doc, reason = attable.read_table(path)
                if reason is not None:
                    bad.append(("reason", reason))
                elif doc not in (doc_a, doc_b):
                    bad.append(("torn", doc))

        threads = [threading.Thread(target=writer)] + \
            [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        import time
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not bad, bad[:3]


# ---------------------------------------------------------------------------
# spaces + search (stdlib)
# ---------------------------------------------------------------------------
class TestSpacesAndSearch:
    def test_pallas_space_only_valid_tilings_smoke(self):
        sp = atspace.pallas_block_space("conv_epilogue", 48, 20)
        rng = random.Random(0)
        for _ in range(50):
            cfg = sp.sample(rng)
            assert 48 % cfg["block_r"] == 0 and 20 % cfg["block_c"] == 0
        assert sp.reason({"block_r": 7, "block_c": 4}) is not None
        assert sp.reason(dict(sp.default)) is None

    def test_bucket_space_enforces_grid_bound(self):
        sp = atspace.bucket_space(max_batch=8, compile_cap=2)
        # the full 1..8 lattice busts a compile cap of 2
        assert sp.reason(
            {"batch_buckets": tuple(range(1, 9))}) is not None
        assert sp.reason({"batch_buckets": (8,)}) is None

    def test_random_search_includes_default_first(self):
        sp = atspace.serving_space()
        seen = []

        class R:
            def __init__(self, cfg, fitness):
                self.config, self.fitness = cfg, fitness

        def ev(cfg, resource=1.0):
            seen.append(dict(cfg))
            return R(cfg, -cfg["window_ms"])

        budget = atsearch.Budget(max_trials=5, wall_s=30.0)
        hist = atsearch.random_search(sp, ev, budget, random.Random(1))
        assert seen[0] == sp.default              # the A/B anchor
        assert len(hist) == 5
        assert len({tuple(sorted(c.items())) for c in seen}) == 5

    def test_budget_bounds_trials_and_wall(self):
        b = atsearch.Budget(max_trials=3, wall_s=0.0).start()
        assert b.exhausted() is not None          # wall already gone
        b2 = atsearch.Budget(max_trials=2, wall_s=60.0).start()
        assert b2.allow() and b2.allow() and not b2.allow()
        assert b2.exhausted().startswith("trials:")

    def test_run_search_converges_to_optimum(self):
        sp = atspace.serving_space()

        class R:
            def __init__(self, cfg, fitness):
                self.config, self.fitness = cfg, fitness

        def ev(cfg, resource=1.0):
            return R(dict(cfg), -(abs(cfg["window_ms"] - 2.0)
                                  + abs(cfg["max_queue"] - 64) / 64.0))

        budget = atsearch.Budget(max_trials=40, wall_s=60.0)
        hist = atsearch.run_search(sp, ev, budget, seed=3,
                                   descent_rounds=2)
        best = max(hist, key=lambda r: r.fitness)
        assert best.config == {"window_ms": 2.0, "max_queue": 64}

    def test_successive_halving_scales_resource(self):
        sp = atspace.serving_space()
        calls = []

        class R:
            def __init__(self, cfg, fitness):
                self.config, self.fitness = cfg, fitness

        def ev(cfg, resource=1.0):
            calls.append(resource)
            return R(dict(cfg), -cfg["window_ms"])

        budget = atsearch.Budget(max_trials=30, wall_s=60.0)
        atsearch.successive_halving(sp, ev, budget, random.Random(0),
                                    n0=6, resource0=0.25)
        assert min(calls) == 0.25 and max(calls) <= 1.0
        assert len(set(calls)) >= 2               # rungs grew


# ---------------------------------------------------------------------------
# runner (deadlined subprocess contract)
# ---------------------------------------------------------------------------
class TestRunner:
    def test_deadline_gates_a_wedged_child(self, tmp_path, journal_file):
        class Wedge(atrunner._Objective):
            name = "wedge"

            def argv(self, config, resource, workdir):
                return [sys.executable, "-c",
                        "import time; time.sleep(60)"]

            def score(self, doc, config, workdir):
                return 1.0, None, {}

        r = atrunner.TrialRunner(Wedge(deadline_s=1.0),
                                 workdir=str(tmp_path))
        res = r.evaluate({"x": 1})
        assert res.fitness is None and res.gate == "deadline:1s"
        rec = _records(journal_file, "autotune_trial")[-1]
        assert rec["gate"] == "deadline:1s" and rec["ok"] is False

    def test_garbage_child_output_is_a_gate_not_a_crash(self, tmp_path):
        class Garbage(atrunner._Objective):
            name = "garbage"

            def argv(self, config, resource, workdir):
                return [sys.executable, "-c",
                        "print('no json here'); raise SystemExit(3)"]

            def score(self, doc, config, workdir):
                return 1.0, None, {}

        res = atrunner.TrialRunner(
            Garbage(deadline_s=30.0),
            workdir=str(tmp_path)).evaluate({})
        assert res.fitness is None
        assert res.gate == "no_metric_line:rc=3"

    def test_memoized_revisit_journals_cached(self, tmp_path,
                                              journal_file):
        class Echo(atrunner._Objective):
            name = "echo"

            def argv(self, config, resource, workdir):
                return [sys.executable, "-c",
                        "print('{\"value\": 5}')"]

            def score(self, doc, config, workdir):
                return float(doc["value"]), None, {}

        r = atrunner.TrialRunner(Echo(deadline_s=30.0),
                                 workdir=str(tmp_path))
        a = r.evaluate({"k": 1})
        b = r.evaluate({"k": 1})
        assert a.fitness == b.fitness == 5.0
        assert not a.cached and b.cached
        recs = _records(journal_file, "autotune_trial")
        assert [r_["cached"] for r_ in recs] == [False, True]
        assert r.summary()["cached"] == 1

    def test_kernel_objective_parity_gate_end_to_end_smoke(
            self, tmp_path, journal_file):
        """One REAL kernel trial through the subprocess harness: the
        parity gate runs in the child and a fitness comes back."""
        obj = atrunner.KernelObjective(kernel="conv_epilogue", r=32,
                                       c=16, iters=2, deadline_s=120.0)
        res = atrunner.TrialRunner(
            obj, workdir=str(tmp_path)).evaluate(
                {"block_r": 16, "block_c": 16})
        assert res.ok, res.gate
        assert res.fitness > 0
        assert res.metrics["max_err"] <= res.metrics["tolerance"]


# ---------------------------------------------------------------------------
# runtime consumers read tuned values (regression: tuned_load + changed
# effective knob)
# ---------------------------------------------------------------------------
class TestConsumers:
    def test_server_reads_tuned_and_env_wins_smoke(self, tuned_env,
                                                   journal_file):
        from mxnet_tpu.serving.server import Server, ServerConfig
        attable.commit_table(
            _table_doc(serving={"window_ms": 2.5, "max_queue": 64},
                       buckets={"batch": [1, 2, 8]}), tuned_env)
        attable.reset_cache()
        net = _mlp()
        s = Server(net)                       # never started
        assert s.config.window_ms == 2.5      # changed effective knob
        assert s.config.max_queue == 64
        assert s.grid.batch_buckets == (1, 2, 8)
        loads = [r for r in _records(journal_file, "tuned_load")
                 if r["site"] == "server"]
        assert loads and loads[0]["window_ms"] == 2.5
        # explicit constructor value wins over the table
        s2 = Server(net, config=ServerConfig(window_ms=1.25))
        assert s2.config.window_ms == 1.25
        # env var wins over the table
        os.environ["MXNET_TPU_SERVING_WINDOW_MS"] = "7.5"
        try:
            s3 = Server(net, config=ServerConfig())
            assert s3.config.window_ms == 7.5
        finally:
            del os.environ["MXNET_TPU_SERVING_WINDOW_MS"]

    def test_router_reads_tuned_hedge(self, tuned_env, journal_file):
        from mxnet_tpu.serving.router import (RouterConfig,
                                              _apply_tuned_router)
        attable.commit_table(_table_doc(router={"hedge_ms": 12.5}),
                             tuned_env)
        attable.reset_cache()
        cfg = RouterConfig()
        _apply_tuned_router(cfg)
        assert cfg.hedge_ms == 12.5
        loads = [r for r in _records(journal_file, "tuned_load")
                 if r["site"] == "router"]
        assert loads and loads[0]["hedge_ms"] == 12.5
        # constructor-provided hedge wins
        cfg2 = RouterConfig(hedge_ms=3.0)
        _apply_tuned_router(cfg2)
        assert cfg2.hedge_ms == 3.0

    def test_dispatch_reads_tuned_block_bit_identical_smoke(
            self, tuned_env, journal_file):
        import jax.numpy as jnp
        from mxnet_tpu.pallas import registry
        rng = np.random.RandomState(0)
        y = jnp.asarray(rng.randn(64, 32), np.float32)
        sc = jnp.asarray(rng.rand(1, 32) + 0.5, np.float32)
        b = jnp.asarray(rng.randn(1, 32) * 0.1, np.float32)
        args = (y, sc, b, None)
        base = registry.dispatch("conv_epilogue", *args,
                                 act_type="relu", interpret=True)
        attable.commit_table(
            _table_doc(pallas={"conv_epilogue":
                               {"64x32": {"block": [16, 16]}}}),
            tuned_env)
        attable.reset_cache()
        registry.reset_provenance()
        tuned = registry.dispatch("conv_epilogue", *args,
                                  act_type="relu", interpret=True)
        assert (np.asarray(base) == np.asarray(tuned)).all()
        loads = [r for r in _records(journal_file, "tuned_load")
                 if r["site"] == "pallas"]
        assert loads and loads[0]["block"] == [16, 16]
        assert loads[0]["kernel"] == "conv_epilogue"
        assert loads[0]["shape_class"] == "64x32"

    def test_dispatch_refuses_invalid_tuned_block(self, tuned_env,
                                                  journal_file):
        import jax.numpy as jnp
        from mxnet_tpu.pallas import registry
        rng = np.random.RandomState(1)
        y = jnp.asarray(rng.randn(64, 32), np.float32)
        sc = jnp.asarray(rng.rand(1, 32) + 0.5, np.float32)
        b = jnp.asarray(rng.randn(1, 32) * 0.1, np.float32)
        # 48 does not divide 64: table is schema-valid but wrong for
        # this shape class — dispatch must refuse it, journaled
        attable.commit_table(
            _table_doc(pallas={"conv_epilogue":
                               {"64x32": {"block": [48, 16]}}}),
            tuned_env)
        attable.reset_cache()
        registry.reset_provenance()
        out = registry.dispatch("conv_epilogue", y, sc, b, None,
                                act_type="relu", interpret=True)
        assert out.shape == (64, 32)
        falls = [r for r in _records(journal_file, "tuned_fallback")
                 if r.get("site") == "pallas"]
        assert falls and falls[0]["reason"] == "invalid_block"
        assert not [r for r in _records(journal_file, "tuned_load")
                    if r.get("site") == "pallas"]

    def test_explicit_block_override_bit_identical_and_grad(self):
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.pallas import registry
        rng = np.random.RandomState(2)
        y = jnp.asarray(rng.randn(32, 16), np.float32)
        sc = jnp.asarray(rng.rand(1, 16) + 0.5, np.float32)
        b = jnp.asarray(rng.randn(1, 16) * 0.1, np.float32)
        base = registry.dispatch("conv_epilogue", y, sc, b, None,
                                 act_type="relu", interpret=True)
        for blk in ((8, 8), (32, 16), (1, 16), (7, 3)):  # last clamps
            out = registry.dispatch("conv_epilogue", y, sc, b, None,
                                    act_type="relu", interpret=True,
                                    block=blk)
            assert (np.asarray(base) == np.asarray(out)).all(), blk
        g = jax.grad(lambda a: registry.dispatch(
            "conv_epilogue", a, sc, b, None, act_type="relu",
            interpret=True, block=(8, 8)).sum())(y)
        assert g.shape == y.shape


# ---------------------------------------------------------------------------
# CLI: search end to end (CPU, tiny budget), show/apply
# ---------------------------------------------------------------------------
def _run_cli(argv, cwd, extra_env=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.autotune"] + argv,
        capture_output=True, text=True, timeout=600, cwd=cwd, env=env)


@pytest.mark.slow
class TestSearchCLI:
    def test_search_two_families_commits_and_runtime_loads_smoke(
            self, tmp_path):
        """The acceptance loop: search ≥2 knob families on CPU (≤8
        trials), every trial journaled with gates enforced, table
        committed with provenance, tuned ≥ default on the same harness,
        and a fresh consumer process loads the committed table with a
        journaled ``tuned_load``."""
        jpath = str(tmp_path / "search_journal.jsonl")
        out = _run_cli(
            ["search", "--table", "tuned.json",
             "--out", "BENCH_autotune.json",
             "--trials", "6", "--budget-s", "240",
             "--kernel-shape", "64x32", "--kernel-iters", "3",
             "--bench-seconds", "0.6", "--clients", "2",
             "--descent-rounds", "1",
             "--arrival",
             os.path.join(REPO, "benchmarks", "arrival_smoke.json")],
            cwd=str(tmp_path), extra_env={"MXNET_TPU_JOURNAL": jpath})
        assert out.returncode == 0, out.stderr[-2000:]
        doc = json.loads(out.stdout.strip().splitlines()[-1])
        assert doc["metric"] == "autotune_search_trials"
        fams = doc["families"]
        assert set(fams) == {"kernel", "serving"}   # ≥ 2 knob families
        for fam in fams.values():
            assert fam["trials"] >= 2
            assert fam["baseline"] is not None      # default was trial 1
            assert fam["tuned_ge_default"]
        assert doc["value"] <= 8                    # trial budget held

        # every trial journaled with config + gate outcome
        trials = _records(jpath, "autotune_trial")
        assert len(trials) == doc["value"]
        assert all("config" in t and "ok" in t for t in trials)

        # committed table: valid, with provenance referencing the trials
        table_path = str(tmp_path / "tuned.json")
        committed, reason = attable.read_table(table_path)
        assert reason is None, reason
        prov = committed["provenance"]
        assert prov["trials"] == len(trials)
        assert prov["journal"] == jpath
        assert set(prov["trial_ids"]) == {"kernel", "serving"}
        assert os.path.exists(str(tmp_path / "BENCH_autotune.json"))

        # a FRESH process (dispatch + Server) loads the tuned values
        check = (
            "import json, numpy as np, jax.numpy as jnp\n"
            "from mxnet_tpu.pallas import registry\n"
            "from mxnet_tpu.serving.server import Server\n"
            "from mxnet_tpu.gluon import nn\n"
            "net = nn.HybridSequential()\n"
            "with net.name_scope():\n"
            "    net.add(nn.Dense(4, in_units=4))\n"
            "net.initialize()\n"
            "s = Server(net)\n"
            "rng = np.random.RandomState(0)\n"
            "y = jnp.asarray(rng.randn(64, 32), np.float32)\n"
            "sc = jnp.asarray(rng.rand(1, 32) + 0.5, np.float32)\n"
            "b = jnp.asarray(rng.randn(1, 32) * 0.1, np.float32)\n"
            "registry.dispatch('conv_epilogue', y, sc, b, None,\n"
            "                  act_type='relu', interpret=True)\n"
            "print(json.dumps({'window_ms': s.config.window_ms,\n"
            "                  'max_queue': s.config.max_queue}))\n")
        cjournal = str(tmp_path / "consumer_journal.jsonl")
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": REPO + os.pathsep
                    + env.get("PYTHONPATH", ""),
                    "MXNET_TPU_TUNED_TABLE": table_path,
                    "MXNET_TPU_JOURNAL": cjournal})
        env.pop("MXNET_TPU_SERVING_WINDOW_MS", None)
        got = subprocess.run([sys.executable, "-c", check],
                             capture_output=True, text=True,
                             timeout=300, cwd=str(tmp_path), env=env)
        assert got.returncode == 0, got.stderr[-2000:]
        eff = json.loads(got.stdout.strip().splitlines()[-1])
        tuned_serving = committed["knobs"].get("serving", {})
        if "window_ms" in tuned_serving:
            assert eff["window_ms"] == tuned_serving["window_ms"]
        loads = _records(cjournal, "tuned_load")
        sites = {r["site"] for r in loads}
        assert "pallas" in sites     # the kernel family always commits
        if tuned_serving and any(
                tuned_serving.get(k) not in (None, d) for k, d in
                (("window_ms", 5.0), ("max_queue", 128))):
            assert "server" in sites

    def test_apply_validates_then_installs(self, tmp_path):
        src = str(tmp_path / "cand.json")
        dest = str(tmp_path / "active.json")
        attable.commit_table(_table_doc(), src)
        out = _run_cli(["apply", "--src", src, "--dest", dest],
                       cwd=str(tmp_path))
        assert out.returncode == 0, out.stderr[-500:]
        assert json.loads(out.stdout.strip().splitlines()[-1])["ok"]
        assert attable.read_table(dest)[1] is None
        with open(src, "w") as f:
            f.write("{}")
        out2 = _run_cli(["apply", "--src", src, "--dest", dest],
                        cwd=str(tmp_path))
        assert out2.returncode == 1
        assert "invalid_table" in out2.stdout
        assert attable.read_table(dest)[1] is None   # dest untouched


# ---------------------------------------------------------------------------
# serving bench --arrival replay (satellite 2)
# ---------------------------------------------------------------------------
class TestArrivalReplay:
    def test_trace_file_is_valid(self):
        from mxnet_tpu.serving.__main__ import _load_arrival
        events, why = _load_arrival(
            os.path.join(REPO, "benchmarks", "arrival_smoke.json"))
        assert why is None and len(events) >= 40
        assert all(dt >= 0 for dt, _dim in events)

    def test_loader_rejects_malformed(self, tmp_path):
        from mxnet_tpu.serving.__main__ import _load_arrival
        cases = {
            "missing.json": None,
            "garbage.json": "not json",
            "noformat.json": json.dumps({"events": [{"dt_ms": 1}]}),
            "noevents.json": json.dumps(
                {"format": "mxtpu-arrival-v1", "events": []}),
            "baddt.json": json.dumps(
                {"format": "mxtpu-arrival-v1",
                 "events": [{"dt_ms": -4}]}),
        }
        for name, content in cases.items():
            p = str(tmp_path / name)
            if content is not None:
                with open(p, "w") as f:
                    f.write(content)
            events, why = _load_arrival(p)
            assert events is None and why, name

    @pytest.mark.slow
    def test_bench_replay_smoke(self, tmp_path):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.run(
            [sys.executable, "-m", "mxnet_tpu.serving", "bench",
             "--seconds", "1.0", "--clients", "2", "--dim", "8",
             "--arrival",
             os.path.join(REPO, "benchmarks", "arrival_smoke.json"),
             "--out", str(tmp_path / "b.json")],
            capture_output=True, text=True, timeout=300,
            cwd=str(tmp_path), env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        doc = json.loads(out.stdout.strip().splitlines()[-1])
        assert doc["arrival"]["mode"] == "replay"
        assert doc["arrival"]["events"] == 54
        assert doc["completed"] > 0

"""Deployment round trip (ref: gluon/block.py export + SymbolBlock.imports,
SURVEY §3.5): gluon model → -symbol.json + .params → SymbolBlock → same
outputs."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _mlp():
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu"))
        net.add(gluon.nn.BatchNorm())
        net.add(gluon.nn.Dense(4))
    return net


def test_export_import_roundtrip(tmp_path):
    net = _mlp()
    net.initialize(mx.init.Xavier())
    x = mx.nd.random.normal(shape=(3, 8))
    want = net(x).asnumpy()

    prefix = str(tmp_path / "model")
    sym_file, param_file = net.export(prefix, epoch=7)
    assert sym_file.endswith("-symbol.json")
    assert param_file.endswith("-0007.params")

    block = gluon.SymbolBlock.imports(sym_file, ["data"], param_file)
    got = block(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_export_conv_roundtrip(tmp_path):
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"))
        net.add(gluon.nn.MaxPool2D(2))
        net.add(gluon.nn.Flatten())
        net.add(gluon.nn.Dense(5))
    net.initialize()
    x = mx.nd.random.normal(shape=(2, 3, 8, 8))
    want = net(x).asnumpy()
    prefix = str(tmp_path / "conv")
    sym_file, param_file = net.export(prefix)
    block = gluon.SymbolBlock.imports(sym_file, ["data"], param_file)
    np.testing.assert_allclose(block(x).asnumpy(), want, rtol=1e-5,
                               atol=1e-6)


def test_exported_symbol_loadable_by_sym_api(tmp_path):
    """The exported graph is a plain mx.sym graph (deployment parity with
    the C predict API consumers)."""
    from mxnet_tpu import sym
    net = _mlp()
    net.initialize()
    x = mx.nd.random.normal(shape=(2, 6))
    net(x)
    prefix = str(tmp_path / "m")
    sym_file, _ = net.export(prefix)
    graph = sym.load(sym_file)
    args = graph.list_arguments()
    assert "data" in args
    assert any(a.endswith("weight") for a in args)
    # moving stats are aux, not args
    aux = graph.list_auxiliary_states()
    assert any("running_mean" in a for a in aux)


def test_resnet_export_roundtrip(tmp_path):
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1(classes=10, thumbnail=True)
    net.initialize()
    x = mx.nd.random.normal(shape=(1, 3, 32, 32))
    want = net(x).asnumpy()
    prefix = str(tmp_path / "rn")
    sym_file, param_file = net.export(prefix)
    block = gluon.SymbolBlock.imports(sym_file, ["data"], param_file)
    np.testing.assert_allclose(block(x).asnumpy(), want, rtol=1e-4,
                               atol=1e-5)

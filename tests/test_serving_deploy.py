"""Canary-gated deployment controller (docs/serving.md, canary
deployment).

The headline chaos drill (CI tier 0.5, ``-k smoke``): a trainer commits
a REGRESSED step (systematically skewed weights, CRC-valid — the
corruption class checksums cannot catch) onto a 3-replica pool under
closed-loop load; the deploy controller canaries it onto exactly one
replica, the sampled output-parity gate trips on the first mirrored
comparison, and the fleet auto-rolls-back — zero responses whose value
contradicts their version stamp, control replicas never serve the bad
root (blast radius = the canary set by construction), the rolled-back
store stays PINNED so the bad-but-newest commit cannot be silently
re-adopted, and the whole trail is journaled under one ``deploy`` trace
span for ``doctor --serving-journal``.

Around it: the good-path promote (with a concurrent ``pool.reload()``
refused mid-canary as structured ``DeployInProgress``), the slow-canary
p99 gate, the canary-lost hard signal (heartbeat gone mid-canary), the
``ParamStore`` pin regression, ``regress_params`` itself, and the
journal reduction's deploy section.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.diagnostics.journal import reset_journal
from mxnet_tpu.gluon.block import HybridBlock
from mxnet_tpu.resilience import commit
from mxnet_tpu.serving import (DeployConfig, DeployController,
                               DeployInProgress, ParamStore, PoolConfig,
                               ReplicaPool, Router, RouterConfig, Server,
                               ServerConfig, serving_report)
from mxnet_tpu.testing import faults


@pytest.fixture
def journal_file(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    reset_journal(path)
    try:
        yield path
    finally:
        reset_journal("stderr")


def _records(path, kind=None):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


class Scale(HybridBlock):
    """y = x * w: the weight value IS the served checkpoint's
    fingerprint, so stamp-vs-value assertions ride it."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.w = self.params.get("w", shape=(1,), init="ones")

    def hybrid_forward(self, F, x, w):
        return x * w


def _commit_scale(root, step, value):
    stage = commit.prepare_stage(root, step)
    nd.save(os.path.join(stage, "net.params"),
            {"w": nd.array(np.asarray([value], np.float32))})
    return commit.finalize(root, step)


def _local_pool(root, n=3, ckpt_root=None, heartbeat_s=0.1,
                deadline_s=0.6, **server_kw):
    server_kw.setdefault("max_batch", 4)
    server_kw.setdefault("window_ms", 1.0)
    server_kw.setdefault("reload_poll_s", -1.0)   # pin lane only: the
    # deploy controller must fully drive versions, not race a poller

    def factory():
        net = Scale()
        net.initialize()
        store = ParamStore(ckpt_root) if ckpt_root else None
        return Server(net, config=ServerConfig(**server_kw),
                      param_store=store)

    pool = ReplicaPool(root, PoolConfig(heartbeat_s=heartbeat_s,
                                        deadline_s=deadline_s))
    for i in range(n):
        pool.add_local(f"r{i}", factory)
    return pool


def _wait_steps(pool, step, deadline_s=15.0):
    """Bounded wait for every replica beacon to advertise ``step`` —
    the first beat can race the startup force-reload."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if all(s.params_step == step for s in pool.view()):
            return True
        time.sleep(0.02)
    return False


def _wait_record(path, kind, deadline_s=20.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        recs = _records(path, kind)
        if recs:
            return recs
        time.sleep(0.02)
    return []


# -- satellites: faults + ParamStore pin -------------------------------------

def test_regress_params_is_crc_valid_but_skewed(tmp_path):
    """``regress_params`` models the failure CRC cannot catch: the
    weights are systematically scaled, the manifest is REWRITTEN over
    the skewed bytes, so validation passes and only behavior (output
    parity) can notice."""
    ck = str(tmp_path / "ckpt")
    _commit_scale(ck, 1, 3.0)
    path = faults.regress_params(ck, 1, scale=10.0)
    assert path.endswith("net.params")
    commit.validate_step(ck, 1)              # CRC-valid: no ValueError
    loaded = nd.load(path)
    assert abs(float(np.asarray(loaded["w"].asnumpy())[0]) - 30.0) < 1e-5
    # contrast: corrupt_params leaves a stale manifest that FAILS
    faults.corrupt_params(ck, 1)
    with pytest.raises(ValueError):
        commit.validate_step(ck, 1)


def test_param_store_pin_ignores_newer_commits(tmp_path):
    """Regression (the rollback lever): a pinned store must ignore
    newer commits until unpinned — a rolled-back replica cannot
    re-adopt the bad-but-newest root on its next poll."""
    ck = str(tmp_path / "ckpt")
    _commit_scale(ck, 1, 2.0)
    store = ParamStore(ck)
    step, loaded = store.poll()
    assert step == 1 and "w" in loaded
    store.pin_step(1)
    _commit_scale(ck, 2, 5.0)                # newer lands on disk ...
    assert store.poll() is None              # ... and stays invisible
    assert store.loaded_step == 1
    # explicit load of the pinned step is a downgrade-capable no-op path
    step, loaded = store.load_step(1)
    assert step == 1
    store.pin_step(None)                     # unpin: newest-wins resumes
    step, loaded = store.poll()
    assert step == 2
    assert abs(float(np.asarray(loaded["w"].asnumpy())[0]) - 5.0) < 1e-5
    # pin below loaded_step + load_step downgrades explicitly
    store.pin_step(1)
    step, _ = store.load_step(1)
    assert step == 1 and store.loaded_step == 1


def test_slow_canary_rule_targets_deploy_trip_site():
    from mxnet_tpu.resilience import atomic
    t0 = time.monotonic()
    with faults.inject(faults.slow_canary(0.2, replica="rX")):
        atomic.trip("deploy_canary", "rX")    # matches: sleeps
        atomic.trip("deploy_canary", "rY")    # other replica: instant
        atomic.trip("router_attempt", "rX")   # other site: instant
    assert 0.2 <= time.monotonic() - t0 < 1.0


# -- controller validation ----------------------------------------------------

def test_deploy_config_validation():
    with pytest.raises(MXNetError):
        DeployConfig(canary_k=0)
    with pytest.raises(MXNetError):
        DeployConfig(window_s=0.0)
    with pytest.raises(MXNetError):
        DeployConfig(mirror_fraction=1.5)
    with pytest.raises(MXNetError):
        DeployConfig(deadline_s=1.0, window_s=2.0)


def test_deploy_noop_and_refusals(tmp_path, journal_file):
    ck = str(tmp_path / "ckpt")
    _commit_scale(ck, 1, 2.0)
    pool = _local_pool(str(tmp_path / "pool"), n=2, ckpt_root=ck).start()
    router = Router(pool, RouterConfig())
    try:
        cfg = DeployConfig(canary_k=1, window_s=0.2, deadline_s=5.0)
        ctl = DeployController(pool, router, ck, cfg)
        assert ctl.deploy(1)["result"] == "noop"     # already serving it
        with pytest.raises(MXNetError):              # no control arm left
            DeployController(pool, router, ck,
                             DeployConfig(canary_k=2, window_s=0.2,
                                          deadline_s=5.0)).deploy(1)
        with pytest.raises(ValueError):              # uncommitted step
            ctl.deploy(99)
        empty = str(tmp_path / "empty")
        os.makedirs(empty)
        with pytest.raises(MXNetError):              # nothing to deploy
            DeployController(pool, router, empty, cfg).deploy()
    finally:
        router.stop()
        pool.stop()


# -- the good path + DeployInProgress refusal --------------------------------

def test_good_deploy_promotes_and_reload_refused_mid_canary(
        tmp_path, journal_file):
    """Clean canary → promote: gates pass on live p99/error stats, the
    remaining replicas roll forward, every replica ends unpinned on the
    new step — and mid-canary the pool refuses a concurrent
    ``pool.reload()`` (and a second deploy) with structured
    ``DeployInProgress`` instead of tearing the version contract.
    Every response during the canary carries exactly the canary or the
    control step, never a third."""
    ck = str(tmp_path / "ckpt")
    _commit_scale(ck, 1, 2.0)
    pool = _local_pool(str(tmp_path / "pool"), n=3, ckpt_root=ck).start()
    router = Router(pool, RouterConfig(retries=3))
    x = np.ones(4, np.float32)
    seen, errors, stop = [], [], threading.Event()

    def client():
        while not stop.is_set():
            try:
                resp = router.call(x, deadline_ms=8000)
            except Exception as e:            # pragma: no cover - loud
                errors.append(repr(e))
                return
            seen.append((float(np.asarray(resp.value)[0]),
                         resp.params_step))
            time.sleep(0.002)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(3)]
    result = {}
    try:
        assert _wait_steps(pool, 1)
        for t in threads:
            t.start()
        _commit_scale(ck, 2, 5.0)
        # weights genuinely change, so parity mirroring is OFF: the
        # promote decision rides the statistical gates alone
        cfg = DeployConfig(canary_k=1, window_s=0.3, promote_after=2,
                           min_samples=5, mirror_fraction=0.0,
                           rollback_s=15.0, deadline_s=45.0)
        ctl = DeployController(pool, router, ck, cfg)

        def run():
            try:
                result.update(ctl.deploy(2))
            except Exception as e:            # pragma: no cover - loud
                result["error"] = repr(e)

        dep = threading.Thread(target=run, daemon=True)
        dep.start()
        assert _wait_record(journal_file, "canary_up"), \
            "canary never came up"
        # mid-canary: fleet mutations are refused, not queued
        with pytest.raises(DeployInProgress) as ei:
            pool.reload()
        assert ei.value.op == "reload"
        with pytest.raises(DeployInProgress):
            DeployController(pool, router, ck, cfg).deploy(2)
        dep.join(timeout=60)
        assert not dep.is_alive()
        final_steps = [s.params_step for s in pool.view()]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        router.stop()
        pool.stop()
    assert not errors, errors[:3]
    assert result.get("result") == "promoted", result
    assert result["gate_evals"] >= 2
    # the fleet converged on the new step, unpinned (newest-wins resumes)
    assert final_steps and all(s == 2 for s in final_steps)
    for rep in pool.replicas.values():
        assert rep.server.param_store.pinned_step is None
    assert pool.deploy_owner() is None
    # old-xor-new, numerically matched: never a third version
    assert seen
    for value, step in seen:
        assert step in (1, 2), (value, step)
        want = 2.0 if step == 1 else 5.0
        assert abs(value - want) < 1e-5, (value, step)
    assert {s for _, s in seen} == {1, 2}


# -- gate breaches ------------------------------------------------------------

def test_slow_canary_p99_gate_rolls_back(tmp_path, journal_file):
    """A canary that answers correctly but SLOWLY must still fail: the
    p99 gate compares fresh per-arm latency windows and rolls back."""
    ck = str(tmp_path / "ckpt")
    _commit_scale(ck, 1, 2.0)
    pool = _local_pool(str(tmp_path / "pool"), n=3, ckpt_root=ck).start()
    router = Router(pool, RouterConfig(retries=3))
    x = np.ones(4, np.float32)
    stop = threading.Event()

    def client():
        while not stop.is_set():
            try:
                router.call(x, deadline_ms=8000)
            except Exception:                  # pragma: no cover
                time.sleep(0.01)
            time.sleep(0.002)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(3)]
    try:
        for t in threads:
            t.start()
        _commit_scale(ck, 2, 2.0)              # same weights: only the
        cfg = DeployConfig(canary_k=1, window_s=0.5, promote_after=3,
                           min_samples=5, mirror_fraction=0.0,
                           p99_ratio=1.5, p99_floor_ms=50.0,
                           rollback_s=15.0, deadline_s=45.0)
        ctl = DeployController(pool, router, ck, cfg)
        with faults.inject(faults.slow_canary(0.25, replica="r0")):
            result = ctl.deploy(2)             # latency distinguishes
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        router.stop()
        pool.stop()
    assert result["result"] == "rolled_back", result
    assert result["reason"] == "p99"
    assert result["converged"]
    evals = _records(journal_file, "gate_eval")
    assert evals and evals[-1]["verdict"] == "breach"
    assert evals[-1]["canary_p99_ms"] > evals[-1]["control_p99_ms"]


def test_canary_lost_hard_signal_rolls_back_without_traffic(
        tmp_path, journal_file):
    """A canary losing its heartbeat mid-canary (the SIGKILL/host-
    vanished shape) is an immediate breach — no statistics, no
    min_samples wait, no traffic needed at all."""
    ck = str(tmp_path / "ckpt")
    _commit_scale(ck, 1, 2.0)
    pool = _local_pool(str(tmp_path / "pool"), n=3, ckpt_root=ck).start()
    router = Router(pool, RouterConfig())
    result = {}
    try:
        _commit_scale(ck, 2, 5.0)
        cfg = DeployConfig(canary_k=1, window_s=0.3, promote_after=50,
                           min_samples=10_000, mirror_fraction=0.0,
                           rollback_s=10.0, deadline_s=30.0)
        ctl = DeployController(pool, router, ck, cfg)

        def run():
            result.update(ctl.deploy(2))

        dep = threading.Thread(target=run, daemon=True)
        dep.start()
        assert _wait_record(journal_file, "canary_up")
        pool.replicas["r0"]._hb.stop()         # beats stop; goes stale
        dep.join(timeout=60)
        assert not dep.is_alive()
    finally:
        router.stop()
        pool.stop()
    assert result.get("result") == "rolled_back", result
    assert result["reason"] == "canary_lost"
    # the handle remembers the rollback pin: a monitor respawn of this
    # replica would come back pinned to the old step
    assert pool.replicas["r0"]._pin == 1


# -- the chaos headline (CI tier 0.5 smoke) ----------------------------------

def test_deploy_chaos_smoke_regressed_canary_parity_rollback(
        tmp_path, journal_file):
    """A REGRESSED (CRC-valid, wrong-answer) step is canaried onto 1 of
    3 replicas under closed-loop load: the sampled output-parity gate
    trips, the fleet auto-rolls-back within the deadline budget, and

    - zero responses whose value contradicts their version stamp;
    - the bad step is only ever served BY the canary (blast radius
      = the canary set, measured client-side per replica);
    - after rollback no response carries the bad step;
    - the rolled-back store stays pinned: the bad-but-newest commit
      is not re-adopted;
    - the full trail (deploy_start → canary_up → gate_eval → rollback
      → deploy_done) shares one trace id, and the doctor's
      serving-journal reduction + one-line summary render it."""
    from mxnet_tpu.observability import trace as obtrace
    obtrace.configure(mode="journal")
    ck = str(tmp_path / "ckpt")
    _commit_scale(ck, 1, 3.0)
    pool = _local_pool(str(tmp_path / "pool"), n=3, ckpt_root=ck).start()
    router = Router(pool, RouterConfig(retries=3))
    w_by_step = {1: 3.0, 2: 30.0}       # step 2 is regressed 10x
    seen, errors, stop = [], [], threading.Event()

    def client(idx):
        rng = np.random.default_rng(idx)
        while not stop.is_set():
            x = rng.standard_normal(4).astype(np.float32)
            try:
                resp = router.call(x, deadline_ms=8000)
            except Exception as e:            # pragma: no cover - loud
                errors.append(repr(e))
                time.sleep(0.05)
                continue
            ok = np.allclose(np.asarray(resp.value),
                             x * w_by_step.get(resp.params_step,
                                               float("nan")),
                             rtol=1e-4, atol=1e-5)
            seen.append((resp.params_step, resp.replica, bool(ok),
                         time.monotonic()))
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(3)]
    try:
        assert _wait_steps(pool, 1)
        for t in threads:
            t.start()
        # the trainer publishes the SAME weights ... then a systematic
        # regression lands on them, CRC-valid: only parity can see it
        _commit_scale(ck, 2, 3.0)
        faults.regress_params(ck, 2, scale=10.0)
        cfg = DeployConfig(canary_k=1, window_s=0.3, promote_after=3,
                           min_samples=5, mirror_fraction=0.25,
                           mismatch_budget=0, rollback_s=10.0,
                           deadline_s=45.0)
        ctl = DeployController(pool, router, ck, cfg)
        result = ctl.deploy(2)
        t_done = time.monotonic()
        time.sleep(0.5)                        # post-rollback traffic
        final_steps = [s.params_step for s in pool.view()]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        router.stop()
        pool.stop()
        obtrace.reset_tracer()

    # terminal state: rolled back on parity, within the deadline budget
    assert result["result"] == "rolled_back", result
    assert result["reason"] == "parity"
    assert result["converged"]
    assert result["rollback_ms"] <= cfg.rollback_s * 1000.0
    assert not errors, errors[:3]
    assert seen

    # (1) zero stamp-contradicting responses, and never a third version
    bad = [row for row in seen if not row[2]]
    assert not bad, bad[:3]
    assert {s for s, _, _, _ in seen} <= {1, 2}

    # (2) blast radius: the bad step only ever came from the canary,
    # and the control replicas served the old fingerprint throughout
    canary_rid = result["canary"][0]
    assert {r for s, r, _, _ in seen if s == 2} <= {canary_rid}
    for s, r, _, _ in seen:
        if r != canary_rid:
            assert s == 1, (s, r)

    # (3) nothing carries the bad step after rollback completed
    late_bad = [row for row in seen
                if row[0] == 2 and row[3] > t_done + 0.25]
    assert not late_bad, late_bad[:3]

    # (4) the rolled-back canary is pinned: newest-on-disk (the bad
    # step) stays invisible to its store
    store = pool.replicas[canary_rid].server.param_store
    assert store.pinned_step == 1
    assert store.poll() is None
    assert final_steps and all(s == 1 for s in final_steps)

    # (5) the journal trail is complete and trace-correlated
    mism = _records(journal_file, "deploy_mirror_mismatch")
    assert mism, "parity mismatch never journaled"
    trail = {k: _records(journal_file, k)
             for k in ("deploy_start", "canary_up", "gate_eval",
                       "rollback", "deploy_done")}
    for kind, recs in trail.items():
        assert recs, f"missing {kind} record"
    tids = {r.get("trace_id") for recs in trail.values() for r in recs}
    assert len(tids) == 1 and None not in tids, tids
    assert trail["rollback"][0]["reason"] == "parity"
    assert trail["deploy_done"][-1]["result"] == "rolled_back"

    # (6) the doctor renders the whole story
    rep = serving_report(journal_file)
    assert rep["ok"]
    dp = rep["deploy"]
    assert dp["deploys"] == 1 and dp["rollbacks"] == 1
    assert dp["mirror_mismatches"] >= 1
    assert dp["last"]["result"] == "rolled_back"
    assert dp["last"]["reason"] == "parity"
    kinds = [row["kind"] for row in dp["trail"]]
    assert kinds[0] == "deploy_start" and kinds[-1] == "deploy_done"
    assert "gate_eval" in kinds and "rollback" in kinds
    from mxnet_tpu.diagnostics.__main__ import _summ_serving
    line = _summ_serving(rep)
    assert "deploy" in line and "rolled_back" in line
    assert "parity" in line or "rollback" in line


# -- journal reduction (synthetic) -------------------------------------------

def test_serving_report_deploy_section_synthetic(tmp_path):
    path = str(tmp_path / "j.jsonl")
    rows = [
        {"kind": "pool_start", "root": "/p", "replicas": ["r0", "r1"]},
        {"kind": "deploy_start", "trace_id": "t9", "from_step": 1,
         "to_step": 2, "canary": ["r0"], "control": ["r1"]},
        {"kind": "pool_pin", "trace_id": "t9", "replica": "r1", "step": 1,
         "live": True},
        {"kind": "canary_up", "trace_id": "t9", "replicas": ["r0"],
         "step": 2},
        {"kind": "gate_eval", "trace_id": "t9", "n": 1,
         "verdict": "insufficient", "reasons": []},
        {"kind": "gate_eval", "trace_id": "t9", "n": 2,
         "verdict": "breach", "reasons": ["parity"]},
        {"kind": "deploy_mirror_mismatch", "trace_id": "t9",
         "replica": "r0", "step": 2, "max_abs_delta": 27.0},
        {"kind": "rollback", "trace_id": "t9", "reason": "parity",
         "from_step": 2, "to_step": 1, "replicas": ["r0"]},
        {"kind": "deploy_done", "trace_id": "t9", "result": "rolled_back",
         "reason": "parity", "from_step": 1, "to_step": 2,
         "canary": ["r0"], "gate_evals": 2, "rollback_ms": 120.0,
         "converged": True},
    ]
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps({"ts": 1.0, **row}) + "\n")
    rep = serving_report(path)
    dp = rep["deploy"]
    assert dp["deploys"] == 1
    assert dp["gate_evals"] == 2 and dp["gate_breaches"] == 1
    assert dp["mirror_mismatches"] == 1
    assert dp["rollbacks"] == 1 and dp["promotions"] == 0
    assert dp["pins"] == 1
    kinds = [r["kind"] for r in dp["trail"]]
    assert kinds == ["deploy_start", "canary_up", "gate_eval",
                     "gate_eval", "deploy_mirror_mismatch", "rollback",
                     "deploy_done"]
    assert all(r["trace_id"] == "t9" for r in dp["trail"])
    last = dp["last"]
    assert last["result"] == "rolled_back" and last["reason"] == "parity"
    assert last["rollback_ms"] == 120.0
    from mxnet_tpu.diagnostics.__main__ import _summ_serving
    line = _summ_serving(rep)
    assert "rolled_back" in line and "parity" in line
    assert "1 rollbacks" in line

"""Aux subsystems: profiler facade, callbacks, AMP, quantization calib
(SURVEY §5.1/§2.6 #49/#50, §2 #19)."""
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.contrib import amp, quantization


def test_profiler_trace_and_marker(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "prof.json"))
    mx.profiler.set_state("run")
    with mx.profiler.Marker("my_region"):
        x = mx.nd.ones((64, 64))
        y = mx.nd.dot(x, x)
        y.wait_to_read()
    mx.profiler.set_state("stop")
    table = mx.profiler.dumps()
    assert "my_region" in table
    trace_dir = str(tmp_path / "prof_trace")
    assert os.path.isdir(trace_dir) and os.listdir(trace_dir), \
        "profiler must write an XLA trace directory"


def test_speedometer_runs(caplog):
    sp = mx.callback.Speedometer(batch_size=32, frequent=2)

    class P:
        epoch = 0
        nbatch = 0
        eval_metric = None
    p = P()
    with caplog.at_level(logging.INFO):
        for i in range(5):
            p.nbatch = i
            sp(p)


def test_do_checkpoint(tmp_path):
    from mxnet_tpu import sym
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=2, name="fc")
    cb = mx.callback.do_checkpoint(str(tmp_path / "m"))
    arg = {"fc_weight": mx.nd.ones((2, 3)), "fc_bias": mx.nd.zeros((2,))}
    cb(0, net, arg, {})
    assert os.path.exists(str(tmp_path / "m-symbol.json"))
    assert os.path.exists(str(tmp_path / "m-0001.params"))
    sym2, a2, _ = mx.model.load_checkpoint(str(tmp_path / "m"), 1)
    np.testing.assert_allclose(a2["fc_weight"].asnumpy(), np.ones((2, 3)))


def test_amp_init_applies_to_sharded_trainer():
    from mxnet_tpu import parallel
    amp.init("bfloat16")
    try:
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize()
        tr = parallel.ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                                     {"learning_rate": 0.1},
                                     mesh=parallel.make_mesh({"data": 8}))
        assert str(tr._compute_dtype) == "bfloat16"
        x = np.random.randn(8, 8).astype(np.float32)
        y = np.random.randn(8, 4).astype(np.float32)
        loss = tr.step(x, y)
        assert np.isfinite(loss.asscalar())
        # master weights stay fp32
        assert net.weight.data().dtype == np.float32
    finally:
        amp._state["initialized"] = False
        amp._state["dtype"] = None


def test_amp_loss_scaler():
    s = amp.DynamicLossScaler(init_scale=1024, scale_factor=2.0,
                              scale_window=2)
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 2048
    s.update_scale(True)
    assert s.loss_scale == 1024


def test_amp_scale_loss_roundtrip():
    amp.init("float16")
    try:
        net = gluon.nn.Dense(2, in_units=4)
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.0})
        amp.init_trainer(trainer)
        x = mx.nd.ones((2, 4))
        with autograd.record():
            out = net(x)
            loss = (out * out).sum()
            with amp.scale_loss(loss, trainer) as scaled:
                pass
        scaled.backward()
        g_scaled = net.weight.grad().asnumpy().copy()
        amp.unscale(trainer)
        g = net.weight.grad().asnumpy()
        np.testing.assert_allclose(
            g, g_scaled / trainer._amp_loss_scaler.loss_scale, rtol=1e-6)
    finally:
        amp._state["initialized"] = False
        amp._state["dtype"] = None


def test_quantization_calibration():
    arrays = {"a": mx.nd.array(np.linspace(-1, 1, 1000))}
    mm = quantization.calib_thresholds_minmax(arrays)
    assert mm["a"][0] == pytest.approx(-1.0)
    ent = quantization.calib_thresholds_entropy(arrays)
    assert ent["a"][1] > 0
    # quantize_model is implemented now (tests/test_quantization.py);
    # unsupported dtypes still raise the documented error
    with pytest.raises(mx.MXNetError, match="int8"):
        quantization.quantize_model(mx.sym.var("x"), {}, {},
                                    quantized_dtype="uint8")

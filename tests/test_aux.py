"""Aux subsystems: profiler facade, callbacks, AMP, quantization calib
(SURVEY §5.1/§2.6 #49/#50, §2 #19)."""
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.contrib import amp, quantization


def test_profiler_trace_and_marker(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "prof.json"))
    mx.profiler.set_state("run")
    with mx.profiler.Marker("my_region"):
        x = mx.nd.ones((64, 64))
        y = mx.nd.dot(x, x)
        y.wait_to_read()
    mx.profiler.set_state("stop")
    table = mx.profiler.dumps()
    assert "my_region" in table
    trace_dir = str(tmp_path / "prof_trace")
    assert os.path.isdir(trace_dir) and os.listdir(trace_dir), \
        "profiler must write an XLA trace directory"


_DEVICE_STATS_SCRIPT = r"""
import re, sys
import numpy as np, jax, jax.numpy as jnp
import mxnet_tpu as mx

@jax.jit
def f(x, w):
    def body(c, _):
        return jnp.tanh(c @ w), ()
    out, _ = jax.lax.scan(body, x, None, length=20)
    return out

x = jnp.ones((512, 512)); w = jnp.ones((512, 512))
np.asarray(f(x, w))                       # compile outside the trace
mx.profiler.set_config(filename=sys.argv[1])
mx.profiler.set_state("run")
np.asarray(f(x, w))
mx.profiler.set_state("stop")
table = mx.profiler.device_stats()
assert "HLO category" in table or "framework op type" in table
assert "TOTAL" in table and "top" in table
times = [float(v) for v in re.findall(r"(\d+\.\d+) ms", table)]
assert times and max(times) > 0.0, table
# dump() writes the chrome-trace JSON at the configured filename
# (ref: profiler.cc DumpProfile profile.json format)
import json, os
mx.profiler.dump()
assert os.path.exists(sys.argv[1]), "dump() must write the trace json"
trace = json.load(open(sys.argv[1]))
events = trace if isinstance(trace, list) else trace.get("traceEvents", [])
assert events, "chrome trace must contain events"
print("DEVICE_STATS_OK")
"""


def test_profiler_device_stats(tmp_path):
    """device_stats parses the captured xplane into the per-op-category
    table (the reference's aggregate per-operator stats analog —
    src/profiler/aggregate_stats.cc; truth source here is the hardware
    trace via xprof). Runs in a SINGLE-device subprocess: xprof cannot
    attribute ops on the 8-virtual-device CPU plane the suite pins
    (only an IDLE row comes back), while single-device CPU and real
    TPU/GPU planes parse fine."""
    import subprocess
    import sys
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-c", _DEVICE_STATS_SCRIPT,
         str(tmp_path / "p.json")],
        capture_output=True, text=True, timeout=240, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DEVICE_STATS_OK" in r.stdout


def test_speedometer_runs(caplog):
    sp = mx.callback.Speedometer(batch_size=32, frequent=2)

    class P:
        epoch = 0
        nbatch = 0
        eval_metric = None
    p = P()
    with caplog.at_level(logging.INFO):
        for i in range(5):
            p.nbatch = i
            sp(p)


def test_do_checkpoint(tmp_path):
    from mxnet_tpu import sym
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=2, name="fc")
    cb = mx.callback.do_checkpoint(str(tmp_path / "m"))
    arg = {"fc_weight": mx.nd.ones((2, 3)), "fc_bias": mx.nd.zeros((2,))}
    cb(0, net, arg, {})
    assert os.path.exists(str(tmp_path / "m-symbol.json"))
    assert os.path.exists(str(tmp_path / "m-0001.params"))
    sym2, a2, _ = mx.model.load_checkpoint(str(tmp_path / "m"), 1)
    np.testing.assert_allclose(a2["fc_weight"].asnumpy(), np.ones((2, 3)))


def test_amp_init_applies_to_sharded_trainer():
    from mxnet_tpu import parallel
    amp.init("bfloat16")
    try:
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize()
        tr = parallel.ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                                     {"learning_rate": 0.1},
                                     mesh=parallel.make_mesh({"data": 8}))
        assert str(tr._compute_dtype) == "bfloat16"
        x = np.random.randn(8, 8).astype(np.float32)
        y = np.random.randn(8, 4).astype(np.float32)
        loss = tr.step(x, y)
        assert np.isfinite(loss.asscalar())
        # master weights stay fp32
        assert net.weight.data().dtype == np.float32
    finally:
        amp._state["initialized"] = False
        amp._state["dtype"] = None


def test_amp_op_lists_enforce_per_op_dtype():
    """The init() op lists must have semantics (round-2 verdict: they were
    silently ignored): listed ops force their floating inputs to the listed
    precision at dispatch."""
    try:
        amp.init("float16",
                 target_precision_ops=["FullyConnected"],
                 fp32_ops=["tanh"],
                 conditional_fp32_ops=[("Activation", "act_type",
                                        ["softsign"])])
        x = mx.nd.ones((2, 4), dtype="float32")
        w = mx.nd.ones((3, 4), dtype="float32")
        b = mx.nd.zeros((3,), dtype="float32")
        out = mx.nd.FullyConnected(x, w, b, num_hidden=3)
        assert out.dtype == np.float16          # forced to target dtype
        h = mx.nd.ones((2, 2), dtype="float16")
        assert mx.nd.tanh(h).dtype == np.float32            # fp32 list
        assert mx.nd.Activation(h, act_type="softsign").dtype == np.float32
        assert mx.nd.Activation(h, act_type="relu").dtype == np.float16
        # unlisted ops keep their input dtype
        assert (h + h).dtype == np.float16
    finally:
        amp.reset()


def test_amp_unknown_op_in_list_raises():
    try:
        with pytest.raises(Exception):
            amp.init("float16", fp32_ops=["not_a_real_op_name"])
    finally:
        amp.reset()


def test_amp_fp16_e2e_overflow_skips_step_then_converges():
    """fp16 E2E (round-2 verdict #5): an overflowed scale skips the update
    and halves; training then converges on a separable problem."""
    try:
        amp.init("float16")
        net = gluon.nn.Dense(1, in_units=2)
        net.initialize(mx.init.Zero())
        net.cast("float16")      # fp16 weights ⇒ fp16 gradients
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        # absurd scale: fp16 grads overflow on the first backward
        trainer._amp_loss_scaler.loss_scale = 2.0 ** 40
        rng = np.random.RandomState(0)
        x_np = rng.randn(64, 2).astype(np.float32)
        y_np = (x_np.sum(axis=1) > 0).astype(np.float32).reshape(-1, 1)
        x, y = mx.nd.array(x_np), mx.nd.array(y_np)
        loss_fn = gluon.loss.L2Loss()
        w0 = net.weight.data().asnumpy().copy()
        skipped = 0
        losses = []
        for step in range(60):
            with autograd.record():
                out = net(x.astype("float16"))
                loss = loss_fn(out.astype("float32"), y)
                with amp.scale_loss(loss, trainer) as scaled:
                    scaled.backward()
            scale_before = trainer._amp_loss_scaler.loss_scale
            trainer.step(x.shape[0])
            if trainer._amp_loss_scaler.loss_scale < scale_before:
                skipped += 1
                if skipped == 1:   # overflow step must not touch weights
                    np.testing.assert_array_equal(
                        net.weight.data().asnumpy(), w0)
            losses.append(loss.mean().asscalar())
        assert skipped >= 1, "the 2^40 scale must overflow at least once"
        assert losses[-1] < 0.5 * losses[0], \
            f"fp16 AMP training failed to converge: {losses[0]} -> {losses[-1]}"
    finally:
        amp.reset()


def test_amp_loss_scaler():
    s = amp.DynamicLossScaler(init_scale=1024, scale_factor=2.0,
                              scale_window=2)
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 2048
    s.update_scale(True)
    assert s.loss_scale == 1024


def test_amp_scale_loss_roundtrip():
    amp.init("float16")
    try:
        net = gluon.nn.Dense(2, in_units=4)
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.0})
        amp.init_trainer(trainer)
        x = mx.nd.ones((2, 4))
        with autograd.record():
            out = net(x)
            loss = (out * out).sum()
            with amp.scale_loss(loss, trainer) as scaled:
                pass
        scaled.backward()
        g_scaled = net.weight.grad().asnumpy().copy()
        amp.unscale(trainer)
        g = net.weight.grad().asnumpy()
        np.testing.assert_allclose(
            g, g_scaled / trainer._amp_loss_scaler.loss_scale, rtol=1e-6)
    finally:
        amp._state["initialized"] = False
        amp._state["dtype"] = None


def test_quantization_calibration():
    arrays = {"a": mx.nd.array(np.linspace(-1, 1, 1000))}
    mm = quantization.calib_thresholds_minmax(arrays)
    assert mm["a"][0] == pytest.approx(-1.0)
    ent = quantization.calib_thresholds_entropy(arrays)
    assert ent["a"][1] > 0
    # quantize_model is implemented now (tests/test_quantization.py);
    # unsupported dtypes still raise the documented error
    with pytest.raises(mx.MXNetError, match="int8"):
        quantization.quantize_model(mx.sym.var("x"), {}, {},
                                    quantized_dtype="uint8")

"""Control-flow op tests (ref: tests/python/unittest/test_contrib_control_flow.py
— foreach-vs-unrolled parity, while_loop semantics, cond, and the
symbolic/hybridized paths)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
import mxnet_tpu.symbol as sym


def _rand(*shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale) \
        .astype(np.float32)


def test_foreach_vs_unrolled_rnn_forward_and_grad():
    """An elman cell scanned with foreach must match the hand-unrolled
    loop in outputs AND gradients (the reference's core foreach test)."""
    T, B, I, H = 5, 2, 3, 4
    x_np = _rand(T, B, I, seed=1, scale=0.5)
    wx_np = _rand(I, H, seed=2, scale=0.5)
    wh_np = _rand(H, H, seed=3, scale=0.5)

    def run(use_foreach):
        x = nd.array(x_np)
        wx, wh = nd.array(wx_np), nd.array(wh_np)
        wx.attach_grad(), wh.attach_grad()
        h0 = nd.zeros((B, H))

        def cell(xt, h):
            return nd.tanh(nd.dot(xt, wx) + nd.dot(h, wh))

        with autograd.record():
            if use_foreach:
                outs, hT = nd.contrib.foreach(
                    lambda xt, h: (cell(xt, h), cell(xt, h)), x, h0)
            else:
                h = h0
                steps = []
                for t in range(T):
                    h = cell(x.slice_axis(axis=0, begin=t, end=t + 1)
                             .reshape(B, I), h)
                    steps.append(h)
                outs, hT = nd.stack(*steps, axis=0), h
            loss = (outs.sum() + hT.sum())
        loss.backward()
        return (outs.asnumpy(), hT.asnumpy(),
                wx.grad.asnumpy(), wh.grad.asnumpy())

    ref = run(False)
    got = run(True)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)


def test_foreach_multiple_data_and_states():
    xs = nd.array(_rand(4, 3, seed=4))
    ys = nd.array(_rand(4, 3, seed=5))
    s1, s2 = nd.zeros((3,)), nd.ones((3,))
    outs, states = nd.contrib.foreach(
        lambda data, sts: ([data[0] + sts[0], data[1] * sts[1]],
                           [sts[0] + data[0], sts[1]]),
        [xs, ys], [s1, s2])
    assert len(outs) == 2 and len(states) == 2
    np.testing.assert_allclose(states[0].asnumpy(),
                               xs.asnumpy().sum(0), rtol=1e-6)
    np.testing.assert_allclose(outs[1].asnumpy(), ys.asnumpy(), rtol=1e-6)


def test_foreach_inside_hybridized_block():
    """Traced path: foreach lowers to ONE lax.scan inside the jitted
    program; gradients flow through the enclosing trace."""
    class ScanNet(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.proj = gluon.nn.Dense(4, flatten=False)

        def hybrid_forward(self, F, x):
            h0 = F.zeros((2, 4))
            outs, hT = F.contrib.foreach(
                lambda xt, h: (self.proj(xt) + h, self.proj(xt) + h),
                x, h0)
            return outs + hT.reshape(1, 2, 4)

    x = nd.array(_rand(5, 2, 3, seed=6))
    net_e = ScanNet()
    net_e.initialize()
    out_eager = net_e(x)
    net_e.hybridize()
    out_jit = net_e(x)
    np.testing.assert_allclose(out_jit.asnumpy(), out_eager.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    with autograd.record():
        loss = net_e(x).sum()
    loss.backward()
    g = net_e.proj.weight.grad()
    assert np.isfinite(g.asnumpy()).all() and abs(g.asnumpy()).sum() > 0


def test_while_loop_eager_semantics():
    outs, (i_f, acc_f) = nd.contrib.while_loop(
        lambda i, a: i < 5,
        lambda i, a: ([i * 2], [i + 1, a + i]),
        [nd.array([0.0]), nd.array([0.0])], max_iterations=8)
    assert float(i_f.asnumpy()[0]) == 5
    assert float(acc_f.asnumpy()[0]) == 10        # 0+1+2+3+4
    # padded to max_iterations with zeros (reference convention)
    assert outs.shape == (8, 1)
    assert outs.asnumpy()[:5, 0].tolist() == [0, 2, 4, 6, 8]
    assert abs(outs.asnumpy()[5:]).max() == 0


def test_while_loop_traced_matches_eager():
    def program(i0):
        outs, (i_f, a_f) = nd.contrib.while_loop(
            lambda i, a: i < 4,
            lambda i, a: ([a + i], [i + 1, a + i * i]),
            [i0, nd.zeros((1,))], max_iterations=6)
        return outs, i_f, a_f

    eager = [x.asnumpy() for x in program(nd.array([0.0]))]

    class WL(gluon.HybridBlock):
        def hybrid_forward(self, F, i0):
            outs, (i_f, a_f) = F.contrib.while_loop(
                lambda i, a: i < 4,
                lambda i, a: ([a + i], [i + 1, a + i * i]),
                [i0, F.zeros((1,))], max_iterations=6)
            return outs, i_f, a_f

    net = WL()
    net.hybridize()
    traced = [x.asnumpy() for x in net(nd.array([0.0]))]
    for e, t in zip(eager, traced):
        np.testing.assert_allclose(t, e, rtol=1e-6)


def test_while_loop_zero_iterations():
    outs, (i_f,) = nd.contrib.while_loop(
        lambda i: i < 0, lambda i: ([i * 3], [i + 1]),
        [nd.array([7.0])], max_iterations=4)
    assert float(i_f.asnumpy()[0]) == 7
    assert outs.shape == (4, 1) and abs(outs.asnumpy()).max() == 0


def test_while_loop_beam_decode():
    """Greedy/beam-style decode as a while_loop: argmax chain over a toy
    transition matrix with EOS early exit — the control-flow shape of
    the NMT decoder (which now runs on this op, see
    gluon/model_zoo/transformer.py translate)."""
    V, L = 6, 8
    eos = 0
    trans = nd.array(_rand(V, V, seed=7))

    def cond(step, toks, fin):
        return (step < L) * (fin.sum() < 1)

    def body(step, toks, fin):
        cur = nd.take(toks, step.astype("int32"), axis=0)  # (1,) token
        logits = nd.take(trans, cur.astype("int32"), axis=0)
        nxt = logits.reshape(1, V).argmax(axis=-1)
        col = nd.one_hot(step.astype("int32") + 1, depth=L + 1)
        toks = (toks.reshape(1, L + 1) * (1 - col)
                + nd.broadcast_mul(nxt.reshape(1, 1), col)) \
            .reshape(L + 1).astype("int32")
        fin = nd.broadcast_maximum(fin, (nxt == eos).astype("float32"))
        return [], [step + 1, toks, fin]

    toks0 = nd.zeros((L + 1,), dtype="int32") + 2
    _, (steps, toks, fin) = nd.contrib.while_loop(
        cond, body, [nd.zeros((1,)), toks0, nd.zeros((1,))],
        max_iterations=L)
    # python oracle
    t = np.full((L + 1,), 2, np.int64)
    s, f = 0, False
    while s < L and not f:
        nxt = trans.asnumpy()[t[s]].argmax()
        t[s + 1] = nxt
        f = nxt == eos
        s += 1
    np.testing.assert_array_equal(toks.asnumpy(), t)
    assert int(steps.asnumpy()[0]) == s


def test_cond_eager_and_traced():
    a, b = nd.array([2.0]), nd.array([5.0])
    hi = nd.contrib.cond((a > b).reshape(()), lambda: a, lambda: b)
    assert float(hi.asnumpy()[0]) == 5.0

    class CondNet(gluon.HybridBlock):
        def hybrid_forward(self, F, x, y):
            return F.contrib.cond((x.sum() > y.sum()).reshape(()),
                                  lambda: x * 2, lambda: y * 3)

    net = CondNet()
    net.hybridize()
    out = net(a, b)
    np.testing.assert_allclose(out.asnumpy(), [15.0])
    out2 = net(nd.array([9.0]), b)
    np.testing.assert_allclose(out2.asnumpy(), [18.0])


def test_sym_foreach_bind_grad_and_json():
    """Symbolic foreach: executes under the graph executor, infers
    shapes, survives tojson/load_json, and produces gradients."""
    d = sym.var("d")
    s = sym.var("s")
    w = sym.var("w")
    outs, states = sym.contrib.foreach(
        lambda x, st: (sym.tanh(x * w + st), sym.tanh(x * w + st)), d, s)
    net = sym.sum(states)       # scalar objective over final state

    d_np = _rand(4, 3, seed=8, scale=0.5)
    w_np = _rand(3, seed=9, scale=0.5)
    args = {"d": nd.array(d_np), "s": nd.zeros((3,)),
            "w": nd.array(w_np)}
    grads = {k: nd.zeros(v.shape) for k, v in args.items()}
    exe = net.bind(mx.cpu(), args, args_grad=grads)
    exe.forward(is_train=True)
    exe.backward()

    # oracle: eager tape over the same scan
    dd, ww = nd.array(d_np), nd.array(w_np)
    dd.attach_grad(), ww.attach_grad()
    with autograd.record():
        o2, s2 = nd.contrib.foreach(
            lambda x, st: (nd.tanh(x * ww + st), nd.tanh(x * ww + st)),
            dd, nd.zeros((3,)))
        loss = s2.sum()
    loss.backward()
    np.testing.assert_allclose(exe.outputs[0].asnumpy(),
                               loss.asnumpy(), rtol=1e-5)
    np.testing.assert_allclose(grads["w"].asnumpy(), ww.grad.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(grads["d"].asnumpy(), dd.grad.asnumpy(),
                               rtol=1e-5, atol=1e-6)

    # shape inference + json round trip
    g = sym.Group([outs, states])
    _, out_shapes, _ = g.infer_shape(d=(4, 3), s=(3,), w=(3,))
    assert out_shapes == [(4, 3), (3,)]
    g2 = sym.load_json(g.tojson())
    r1 = g.eval(**args)
    r2 = g2.eval(**args)
    for x, y in zip(r1, r2):
        np.testing.assert_allclose(y.asnumpy(), x.asnumpy(), rtol=1e-6)


def test_sym_while_loop_and_cond():
    i = sym.var("i")
    outs, fin = sym.contrib.while_loop(
        lambda x: x < 5, lambda x: (x * 2, x + 1), i, max_iterations=8)
    gg = sym.Group([outs, fin])
    r = gg.eval(i=nd.array([0.0]))
    assert float(r[1].asnumpy()[0]) == 5
    assert r[0].asnumpy()[:5, 0].tolist() == [0, 2, 4, 6, 8]
    _, shapes, _ = gg.infer_shape(i=(1,))
    assert shapes == [(8, 1), (1,)]

    c = sym.contrib.cond(sym.var("p"), lambda: i + 1, lambda: i - 1)
    assert float(c.eval(p=nd.array([1.0]), i=nd.array([3.0]))[0]
                 .asnumpy()[0]) == 4.0
    assert float(c.eval(p=nd.array([0.0]), i=nd.array([3.0]))[0]
                 .asnumpy()[0]) == 2.0
    c2 = sym.load_json(c.tojson())
    assert float(c2.eval(p=nd.array([1.0]), i=nd.array([3.0]))[0]
                 .asnumpy()[0]) == 4.0


def test_while_loop_eager_padding_preserves_dtype():
    """Padding rows must keep the step outputs' dtype (int token ids
    stay int on BOTH the eager and traced paths)."""
    outs, _ = nd.contrib.while_loop(
        lambda i: i < 3, lambda i: ([i.astype("int32")], [i + 1]),
        [nd.array([0.0])], max_iterations=5)
    assert outs.dtype == np.int32

"""Training anomaly guardrails (docs/guardrails.md): the fused
non-finite guard, skip-step semantics, divergence rollback, and the
no-new-host-syncs contract — chaos-proven across all four training
paths (gluon Trainer, module.fit, ShardedTrainer, PipelinedTrainer).

The ``*smoke*`` tests are CI's tier-0.5 guardrail chaos smoke
(ci/run_tests.sh)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, io, parallel, sym
from mxnet_tpu.diagnostics import journal
from mxnet_tpu.guardrails import (AnomalyMonitor, GuardConfig,
                                  TrainingDiverged, fused, guard_report)
from mxnet_tpu.testing import faults


def _read_journal(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture
def jfile(tmp_path):
    """Route the process journal to a file for the test, restore after."""
    jf = str(tmp_path / "journal.jsonl")
    journal.reset_journal(jf)
    try:
        yield jf
    finally:
        journal.reset_journal()


def _mlp(classes=4, in_units=8):
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu", in_units=in_units))
        net.add(gluon.nn.Dense(classes, in_units=16))
    net.initialize()
    return net


def _sharded(guard=None, **kw):
    net = _mlp()
    mesh = parallel.make_mesh({"data": -1})
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh, guard=guard, **kw)
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16,))
    return tr, x, y


def _weights(tr):
    return [np.asarray(p._data[0]._data).copy() for p in tr._trainable]


def _states(tr):
    return [[np.asarray(s).copy() for s in st] for st in tr._states]


# -- chaos smoke: skip-step is a bitwise no-op -------------------------------

def test_smoke_sharded_nan_batch_skipped_bitwise(jfile):
    """A NaN batch at step N is skipped — params, optimizer state and
    the loss-free trajectory are bit-identical to not having stepped —
    then training resumes on clean data."""
    tr, x, y = _sharded(guard=True)
    tr.step(x, y)
    w0, s0 = _weights(tr), _states(tr)
    loss = tr.step(faults.poison_batch(x), y)
    assert not np.isfinite(loss.asscalar())
    for a, b in zip(w0, _weights(tr)):
        np.testing.assert_array_equal(a, b)
    for sa, sb in zip(s0, _states(tr)):
        for a, b in zip(sa, sb):
            np.testing.assert_array_equal(a, b)
    assert tr.skipped_steps == 1
    assert np.isfinite(tr.step(x, y).asscalar())
    recs = [r for r in _read_journal(jfile) if r["kind"] == "nonfinite_grad"]
    assert len(recs) == 1
    assert recs[0]["step"] == 2 and recs[0]["consecutive"] == 1
    assert recs[0]["consumer"] == "sharded_trainer"


def test_smoke_sharded_divergence_rollback_bitexact(tmp_path, jfile):
    """Persistent poison: K consecutive skips raise the divergence
    verdict; the trainer restores the last committed step bit-exact,
    backs off the LR, journals divergence_rollback, and resumes; the
    bounded retry budget then surfaces TrainingDiverged."""
    root = str(tmp_path / "ckpt")
    cfg = GuardConfig(max_consecutive_skips=2, max_rollbacks=1,
                      ckpt_root=root)
    tr, x, y = _sharded(guard=cfg)
    for _ in range(3):
        tr.step(x, y)
    committed = tr.checkpoint(root)
    w_commit, s_commit = _weights(tr), _states(tr)
    xp = faults.poison_batch(x)
    tr.step(xp, y)
    tr.step(xp, y)                      # 2nd skip -> rollback
    for a, b in zip(w_commit, _weights(tr)):
        np.testing.assert_array_equal(a, b)
    for sa, sb in zip(s_commit, _states(tr)):
        for a, b in zip(sa, sb):
            np.testing.assert_array_equal(a, b)
    assert tr.num_update == committed == 3
    assert tr.learning_rate == pytest.approx(0.05)
    recs = _read_journal(jfile)
    rb = [r for r in recs if r["kind"] == "divergence_rollback"]
    assert len(rb) == 1 and rb[0]["restored_step"] == committed
    assert rb[0]["lr_backoff"] == pytest.approx(0.5)
    # training resumes clean after the rollback
    assert np.isfinite(tr.step(x, y).asscalar())
    # budget spent: the next divergence must surface, not loop
    with pytest.raises(TrainingDiverged) as ei:
        tr.step(xp, y)
        tr.step(xp, y)
    assert ei.value.rollbacks == 1
    assert "consecutive non-finite" in str(ei.value)


def test_smoke_eager_trainer_skip_and_rollback(tmp_path, jfile):
    """The eager gluon Trainer path: poisoned grad buffers skip the
    update (no has_overflow pull involved), and divergence rolls back
    bit-exact through the Trainer's own commit-protocol checkpoint."""
    root = str(tmp_path / "ckpt")
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1},
                       guard=GuardConfig(max_consecutive_skips=2,
                                         max_rollbacks=1, ckpt_root=root))
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 2))
    y = mx.nd.array(rng.randn(8, 1))

    def one_step(poison=False):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        if poison:
            faults.poison_grads(net.collect_params().values())
        tr.step(8)

    one_step()
    tr.checkpoint(root)
    w_commit = net.weight.data().asnumpy().copy()
    one_step(poison=True)
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w_commit)
    assert tr.skipped_steps == 1
    one_step(poison=True)               # -> rollback
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w_commit)
    assert tr.learning_rate == pytest.approx(0.05)
    kinds = [r["kind"] for r in _read_journal(jfile)]
    assert "nonfinite_grad" in kinds and "divergence_rollback" in kinds
    # rollback budget spent -> TrainingDiverged surfaces
    with pytest.raises(TrainingDiverged):
        one_step(poison=True)
        one_step(poison=True)


def _pipelined(tmp_root, guard):
    d = 8
    emb = gluon.nn.Dense(d, in_units=d)
    body = [gluon.nn.Dense(d, in_units=d) for _ in range(2)]
    head = gluon.nn.Dense(4, in_units=d)
    for b in [emb] + body + [head]:
        b.initialize()
    mesh = parallel.make_mesh({"pipe": 2, "data": 4})
    tr = parallel.PipelinedTrainer(
        emb, body, head, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh, num_microbatches=2, guard=guard)
    rng = np.random.RandomState(1)
    x = rng.randn(8, d).astype(np.float32)
    y = rng.randint(0, 4, (8,))
    return tr, x, y


def test_smoke_pipelined_skip_and_rollback(tmp_path, jfile):
    root = str(tmp_path / "ckpt")
    cfg = GuardConfig(max_consecutive_skips=2, max_rollbacks=1,
                      ckpt_root=root)
    tr, x, y = _pipelined(root, cfg)
    tr.step(x, y)
    tr.checkpoint(root)
    committed = [np.asarray(w).copy() for w in tr._b_datas]
    xp = faults.poison_batch(x)
    pre = [np.asarray(w).copy() for w in tr._b_datas]
    tr.step(xp, y)                      # skip: bitwise no-op
    for a, b in zip(pre, [np.asarray(w) for w in tr._b_datas]):
        np.testing.assert_array_equal(a, b)
    assert tr.skipped_steps == 1
    tr.step(xp, y)                      # -> rollback to the commit
    for a, b in zip(committed, [np.asarray(w) for w in tr._b_datas]):
        np.testing.assert_array_equal(a, b)
    assert tr.learning_rate == pytest.approx(0.05)
    assert np.isfinite(tr.step(x, y).asscalar())
    recs = [r for r in _read_journal(jfile)
            if r["kind"] == "divergence_rollback"]
    assert len(recs) == 1 and recs[0]["consumer"] == "pipelined_trainer"


def test_module_fit_guard_skips_and_rolls_back(tmp_path, jfile):
    """module.fit(guard=...): a poisoned batch is journaled and never
    trained on; persistent poison rolls back to the newest epoch
    checkpoint and finally raises TrainingDiverged."""
    rng = np.random.RandomState(0)
    x = rng.randn(80, 6).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=2, name="fc1"), name="softmax")
    pref = str(tmp_path / "ckpt")

    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(io.NDArrayIter(x, y, batch_size=20), num_epoch=2,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            checkpoint_prefix=pref, guard=True)

    xp = x.copy()
    xp[0, 0] = np.nan                   # one poisoned batch per epoch
    mod2 = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod2.fit(io.NDArrayIter(xp, y, batch_size=20), num_epoch=1,
             optimizer="sgd", optimizer_params={"learning_rate": 0.1},
             guard=True)
    arg, _ = mod2.get_params()
    assert all(np.isfinite(v.asnumpy()).all() for v in arg.values())
    skips = [r for r in _read_journal(jfile)
             if r["kind"] == "nonfinite_grad"
             and r["consumer"] == "module_fit"]
    assert len(skips) == 1

    mod3 = mx.mod.Module(net, data_names=("data",),
                         label_names=("softmax_label",))
    with pytest.raises(TrainingDiverged):
        mod3.fit(io.NDArrayIter(np.full_like(x, np.nan), y, batch_size=20),
                 num_epoch=3, optimizer="sgd",
                 optimizer_params={"learning_rate": 0.1},
                 checkpoint_prefix=pref, resume=True,
                 guard=GuardConfig(max_consecutive_skips=2,
                                   max_rollbacks=1))
    recs = _read_journal(jfile)
    assert any(r["kind"] == "divergence_rollback"
               and r["consumer"] == "module_fit" for r in recs)


# -- multi-host / multi-device agreement -------------------------------------

def test_two_rank_skip_agreement_and_scale_trajectory():
    """Simulated 2-rank fp16 run, ranks played serially in one process
    (the crash-matrix convention): only rank 0's LOCAL grads carry a
    NaN; after the (simulated) allreduce both ranks' fused flags see it
    — both skip, and the loss-scale trajectories stay identical (the
    hang/divergence class the old per-rank early return could hit)."""
    from mxnet_tpu.contrib import amp

    def make_rank():
        mx.random.seed(3)
        net = gluon.nn.Dense(1, in_units=4)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, guard=True)
        tr._amp_loss_scaler = amp.DynamicLossScaler(init_scale=1024)
        return net, tr

    try:
        amp.init("float16")
        ranks = [make_rank(), make_rank()]
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.randn(8, 4))
        y = mx.nd.array(rng.randn(8, 1))
        loss_fn = gluon.loss.L2Loss()
        scales = {0: [], 1: []}
        for step in range(4):
            grads = []
            for i, (net, _) in enumerate(ranks):
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                if step == 1 and i == 0:   # only rank 0 sees the NaN
                    faults.poison_grads(net.collect_params().values())
                grads.append([g.asnumpy().copy()
                              for p in net.collect_params().values()
                              for g in p._grad])
            # the allreduce: the sum reaches every rank (NaN poisons it)
            import jax.numpy as jnp
            summed = [np.add.reduce([g[j] for g in grads])
                      for j in range(len(grads[0]))]
            for net, tr in ranks:
                bufs = [g for p in net.collect_params().values()
                        for g in p._grad]
                for buf, val in zip(bufs, summed):
                    buf._rebind(jnp.asarray(val))
                tr.step(8)
            for i, (_, tr) in enumerate(ranks):
                scales[i].append(tr._amp_loss_scaler.loss_scale)
        assert scales[0] == scales[1]
        assert scales[0][1] < scales[0][0]      # the overflow step halved
        w0, w1 = (net.weight.data().asnumpy() for net, _ in ranks)
        np.testing.assert_array_equal(w0, w1)
        assert all(tr.skipped_steps == 1 for _, tr in ranks)
    finally:
        amp.reset()


def test_trainer_guard_collective_is_rank_uniform(monkeypatch):
    """Multi-process flag agreement WITHOUT the deadlock class:
    _fetch_guard's allgather participation never depends on rank-local
    state (kvstore type, or whether this rank passed a ``loss``) — a
    rank-dependent decision to enter the collective would wedge the
    peers that did. A peer's non-finite verdict forces a local skip
    even though the local grads are clean, and the loss mean is scoped
    to the ranks that actually sent one (the has-loss slot)."""
    import jax
    from jax.experimental import multihost_utils
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None, guard=True)
    calls = []
    peer = [1.0, 0.0, 0.0, 5.0]     # peer rank: overflowed, sent no loss

    def fake_allgather(vec):
        calls.append(np.asarray(vec))
        return np.stack([np.asarray(vec, np.float32),
                         np.asarray(peer, np.float32)])

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        fake_allgather)
    x = mx.nd.array(np.random.RandomState(0).randn(4, 4))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    w0 = np.asarray(net.weight.data()._data).copy()
    tr.step(4)                  # no loss passed: still participates
    assert len(calls) == 1
    np.testing.assert_array_equal(w0, np.asarray(net.weight.data()._data))
    assert tr.skipped_steps == 1    # peer's flag forced the local skip

    peer[0] = 0.0               # peer finite now, still sends no loss
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr.step(4, loss=loss)       # has-loss slot: mean over senders only
    assert len(calls) == 2 and calls[-1][2] == 1.0
    local_loss = float(np.mean(np.asarray(loss._data)))
    assert tr._monitor._losses[-1] == pytest.approx(local_loss)

    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr.update(4)                # the manual flow rides the same contract
    assert len(calls) == 3

    peer[:] = [0.0, 7.25, 1.0, 5.0]  # peer sends a loss; this rank not
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr.step(4)                  # no local loss — adopt the senders' mean
    assert tr._monitor._losses[-1] == pytest.approx(7.25)


def test_guard_sees_row_sparse_grads(jfile):
    """The eager guard checks the gradient AS THE UPDATE CONSUMES IT: a
    NaN confined to an Embedding's retained row-sparse view (the dense
    buffer under it is still zeros) must veto the step — guarding the
    zero buffer would let _update apply the NaN rows silently."""
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Embedding(20, 4, sparse_grad=True),
            gluon.nn.Dense(2, flatten=False))
    net.initialize()
    net(mx.nd.array(np.zeros((1, 2))))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None, guard=True)
    tokens = mx.nd.array(np.array([[3, 7], [11, 3]]))
    with autograd.record():
        loss = net(tokens).sum()
    loss.backward()
    g = net[0].weight.grad()
    assert isinstance(g, RowSparseNDArray)
    g.data[0, 0] = np.nan           # poison ONLY the sparse view
    w0 = np.asarray(net[0].weight.data()._data).copy()
    tr.step(4)
    np.testing.assert_array_equal(w0,
                                  np.asarray(net[0].weight.data()._data))
    assert tr.skipped_steps == 1
    assert any(r["kind"] == "nonfinite_grad"
               for r in _read_journal(jfile))


def test_fp16_only_skip_is_journaled(jfile):
    """AMP fp16 WITHOUT a GuardConfig: a skipped overflow step still
    writes a nonfinite_grad record (scaler_only=True) — doctor's skip
    accounting must not depend on opting into budgets/rollback."""
    from mxnet_tpu.contrib import amp
    rng = np.random.RandomState(0)
    try:
        amp.init("float16")
        net = _mlp()
        tr = parallel.ShardedTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            optimizer_params={"learning_rate": 0.1},
            mesh=parallel.make_mesh({"data": -1}))
        assert tr._scaler is not None and tr._guard_cfg is None
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randint(0, 4, (16,))
        tr._scaler.loss_scale = 2.0 ** 40     # force fp16 overflow
        tr.step(x, y)
        tr.step(x, y)
        recs = [r for r in _read_journal(jfile)
                if r["kind"] == "nonfinite_grad"
                and r["consumer"] == "sharded_trainer"]
        assert recs and recs[-1].get("scaler_only") is True

        net2 = gluon.nn.Dense(1, in_units=4)
        net2.initialize()
        tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                            {"learning_rate": 0.05})
        tr2._amp_loss_scaler = amp.DynamicLossScaler(init_scale=1024)
        xx = mx.nd.array(rng.randn(4, 4))
        with autograd.record():
            l = net2(xx).sum()
        l.backward()
        faults.poison_grads(net2.collect_params().values())
        tr2.step(4)
        assert tr2.skipped_steps == 1
        recs = [r for r in _read_journal(jfile)
                if r["kind"] == "nonfinite_grad"
                and r["consumer"] == "gluon_trainer"]
        assert recs and recs[-1].get("scaler_only") is True
    finally:
        amp.reset()


def test_tiny_spike_window_still_arms():
    """spike_window <= 7 must still arm: the deque can never exceed the
    window, so the arming gate is capped at it (an uncapped >= 8 gate
    silently disabled the protection the user configured)."""
    mon = AnomalyMonitor(GuardConfig(spike_window=4, spike_steps=2,
                                     spike_factor=10.0))
    for i in range(4):
        assert mon.observe(i, True, loss=1.0) == "ok"
    assert mon.observe(4, True, loss=100.0) == "ok"     # spike run 1
    assert mon.observe(5, True, loss=100.0) == "diverged"
    with pytest.raises(mx.MXNetError):
        GuardConfig(spike_window=0)


def test_sharded_multidevice_flag_is_global():
    """On the 8-device mesh, a NaN confined to ONE data shard's examples
    must skip the step for every device's shard of the params."""
    tr, x, y = _sharded(guard=True)
    tr.step(x, y)
    w0 = _weights(tr)
    xp = x.copy()
    xp[0, 0] = np.inf                   # lands on shard 0 only
    tr.step(xp, y)
    for a, b in zip(w0, _weights(tr)):
        np.testing.assert_array_equal(a, b)
    assert tr.skipped_steps == 1


# -- the no-new-host-syncs contract ------------------------------------------

def test_deferred_mode_zero_device_to_host_transfers():
    """GuardConfig(mode='deferred'): steps run with device→host
    transfers DISALLOWED at the jax layer — the guard adds zero host
    reads; guard_poll() then fetches the in-program counters once."""
    import jax
    tr, x, y = _sharded(guard=GuardConfig(mode="deferred"))
    tr.step(x, y)                       # compile + warm outside the guard
    xb = [tr._shard_batch_arg(b) for b in (x, y)]
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(3):
            tr.step(*xb)
        tr.step(faults.poison_batch(x), y)
    total, consec = tr.guard_poll()
    assert (total, consec) == (1, 1)
    assert tr.skipped_steps == 1


def test_step_mode_single_fetch_single_program(monkeypatch):
    """Eager ('step') monitoring costs exactly ONE host fetch per step —
    of the step's own outputs — and the guard lives inside the ONE
    compiled step program (no secondary jitted guard computation)."""
    tr, x, y = _sharded(guard=True)
    tr.step(x, y)                       # build
    fetches, calls = [], []
    real_fetch = fused.host_fetch
    monkeypatch.setattr(fused, "host_fetch",
                        lambda *a: (fetches.append(len(a)),
                                    real_fetch(*a))[1])
    real_fn = tr._step_fn
    tr._step_fn = lambda *a, **kw: (calls.append(1), real_fn(*a, **kw))[1]
    for _ in range(3):
        tr.step(x, y)
    assert len(calls) == 3              # one program dispatch per step
    assert len(fetches) == 3            # one host fetch per step
    assert all(n == 3 for n in fetches)  # (flag, loss, norm) in ONE fetch


def test_fp16_finite_path_never_pulls_has_overflow(monkeypatch):
    """Satellite contract: the eager fp16 path's old per-step
    has_overflow gradient pull is gone — finite steps ride the fused
    post-allreduce flag, and scale bookkeeping is unchanged."""
    from mxnet_tpu.contrib import amp
    try:
        amp.init("float16")
        net = gluon.nn.Dense(1, in_units=2)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        amp.init_trainer(tr)
        tr._amp_loss_scaler.loss_scale = 128.0
        scaler = tr._amp_loss_scaler
        monkeypatch.setattr(
            scaler, "has_overflow",
            lambda *a, **k: pytest.fail("per-step has_overflow pull"))
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.randn(8, 2))
        y = mx.nd.array(rng.randn(8, 1))
        loss_fn = gluon.loss.L2Loss()
        w_prev = net.weight.data().asnumpy().copy()
        for _ in range(3):
            with autograd.record():
                loss = loss_fn(net(x), y)
                with amp.scale_loss(loss, tr) as scaled:
                    scaled.backward()
            tr.step(8)
        assert scaler.loss_scale == 128.0       # no overflow, no growth yet
        assert not np.array_equal(net.weight.data().asnumpy(), w_prev)
    finally:
        amp.reset()


def test_sharded_fp16_scaler_rides_in_program_flag():
    """ShardedTrainer fp16 parity: an absurd loss scale overflows fp16
    grads — the step skips in-program (params bit-identical), the scale
    halves, and training then converges."""
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize()
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1},
        mesh=parallel.make_mesh({"data": -1}), compute_dtype="float16")
    assert tr._scaler is not None
    tr._scaler.loss_scale = 2.0 ** 40
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16,))
    tr.step(x, y)
    w0 = _weights(tr)
    s_before = tr._scaler.loss_scale
    tr.step(x, y)
    assert tr._scaler.loss_scale == s_before / 2
    for a, b in zip(w0, _weights(tr)):
        np.testing.assert_array_equal(a, b)
    losses = [tr.step(x, y).asscalar() for _ in range(40)]
    assert np.isfinite(losses[-1]) and losses[-1] < losses[-10]
    assert tr.skipped_steps >= 1


def test_run_steps_threads_guard_through_scan(jfile):
    """The scanned multi-step program carries the guard state and
    per-step flags; a poisoned window skips every inner step."""
    tr, x, y = _sharded(guard=GuardConfig(max_consecutive_skips=10))
    tr.step(x, y)
    w0 = _weights(tr)
    tr.run_steps(faults.poison_batch(x), y, num_steps=4)
    for a, b in zip(w0, _weights(tr)):
        np.testing.assert_array_equal(a, b)
    assert tr.skipped_steps == 4
    loss = tr.run_steps(x, y, num_steps=4)
    assert np.isfinite(loss.asscalar())
    assert tr.skipped_steps == 4
    recs = [r for r in _read_journal(jfile) if r["kind"] == "nonfinite_grad"]
    assert len(recs) == 4
    assert [r["consecutive"] for r in recs] == [1, 2, 3, 4]


# -- clip_global_norm: device-side + reused norm -----------------------------

def test_clip_global_norm_numeric_parity():
    arrays = [mx.nd.ones((2,)) * 3, mx.nd.ones((2,)) * 4]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    assert isinstance(norm, float)
    assert norm == pytest.approx(np.sqrt(9 * 2 + 16 * 2), rel=1e-4)
    total = sum(float(mx.nd.sum(mx.nd.square(a)).asscalar())
                for a in arrays)
    assert np.sqrt(total) == pytest.approx(1.0, rel=1e-3)


def test_clip_global_norm_lazy_and_reused_norm():
    """check_isfinite=False is fully lazy (NDArray norm, no float);
    global_norm= reuses a precomputed norm — same clipped values."""
    vals = [np.full((3,), 2.0, np.float32), np.full((2,), 1.0, np.float32)]
    a1 = [mx.nd.array(v) for v in vals]
    n1 = gluon.utils.clip_global_norm(a1, 1.0, check_isfinite=False)
    assert isinstance(n1, mx.nd.NDArray)
    a2 = [mx.nd.array(v) for v in vals]
    precomputed = float(np.sqrt(sum(float((v * v).sum()) for v in vals)))
    gluon.utils.clip_global_norm(a2, 1.0, check_isfinite=False,
                                 global_norm=precomputed)
    for u, v in zip(a1, a2):
        np.testing.assert_allclose(u.asnumpy(), v.asnumpy(), rtol=1e-6)
    assert float(n1.asscalar()) == pytest.approx(precomputed, rel=1e-5)


def test_clip_global_norm_nonfinite_left_unclipped():
    arrays = [mx.nd.array(np.array([np.nan, 1.0], np.float32)),
              mx.nd.ones((2,))]
    with pytest.warns(UserWarning, match="non-finite"):
        norm = gluon.utils.clip_global_norm(arrays, 1.0)
    assert not np.isfinite(norm)
    np.testing.assert_array_equal(arrays[1].asnumpy(),
                                  np.ones((2,), np.float32))


def test_clip_under_norm_is_bit_exact_noop():
    a = mx.nd.array(np.array([0.1, -0.2], np.float32))
    before = a.asnumpy().copy()
    gluon.utils.clip_global_norm([a], 1e6)
    np.testing.assert_array_equal(a.asnumpy(), before)


def test_guard_clip_norm_sharded_matches_manual():
    """GuardConfig.clip_norm inside the fused step == eager
    clip-then-update on the same single-parameter problem."""
    import jax.numpy as jnp
    w_init = np.array([[0.3, -0.2], [0.1, 0.4]], np.float32)
    # 8 identical rows (one per device shard): the mean-loss gradient
    # equals the single-row gradient, keeping the oracle one line
    x = np.tile(np.array([[1.0, 2.0]], np.float32), (8, 1))
    y = np.zeros((8,), np.int64)

    def manual():
        w = w_init.copy()
        logits = (x[:1] @ w.T)[0]
        e = np.exp(logits - logits.max())
        p = e / e.sum()
        g = np.outer(p - np.array([1.0, 0.0]), x[0])   # CE grad wrt w
        norm = np.sqrt((g ** 2).sum())
        scale = min(1.0, 0.01 / (norm + 1e-8))
        return w - 0.5 * g * scale

    net = gluon.nn.Dense(2, in_units=2, use_bias=False)
    net.initialize()
    net.weight.data()._rebind(jnp.asarray(w_init))
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.5},
        mesh=parallel.make_mesh({"data": -1}),
        guard=GuardConfig(clip_norm=0.01))
    tr.step(x, y)
    np.testing.assert_allclose(_weights(tr)[0], manual(), rtol=1e-4)


# -- monitor / policy units --------------------------------------------------

def test_monitor_spike_detection_diverges(jfile):
    mon = AnomalyMonitor(GuardConfig(spike_window=16, spike_factor=10.0,
                                     spike_steps=3))
    for i in range(8):
        assert mon.observe(i, True, loss=1.0 + 0.01 * i) == "ok"
    assert mon.observe(8, True, loss=50.0) == "ok"
    assert mon.observe(9, True, loss=60.0) == "ok"
    assert mon.observe(10, True, loss=70.0) == "diverged"
    assert "rolling median" in mon.reason
    assert sum(1 for r in _read_journal(jfile)
               if r["kind"] == "loss_spike") == 3


def test_monitor_spike_recovery_resets_run():
    mon = AnomalyMonitor(GuardConfig(spike_window=16, spike_factor=10.0,
                                     spike_steps=3))
    for i in range(8):
        mon.observe(i, True, loss=1.0)
    mon.observe(8, True, loss=50.0)
    mon.observe(9, True, loss=1.1)      # recovered
    mon.observe(10, True, loss=55.0)
    assert mon.observe(11, True, loss=55.0) != "diverged"


def test_monitor_skip_budget_and_reset():
    mon = AnomalyMonitor(GuardConfig(max_consecutive_skips=3))
    assert mon.observe(1, False) == "skip"
    assert mon.observe(2, False) == "skip"
    assert mon.observe(3, True) == "ok"        # run broken
    assert mon.observe(4, False) == "skip"
    assert mon.observe(5, False) == "skip"
    assert mon.observe(6, False) == "diverged"
    assert mon.total_skips == 5
    mon.reset_stats()
    assert mon.consecutive_skips == 0 and mon.reason is None
    assert mon.total_skips == 5                 # cumulative survives


def test_lr_backoff_wraps_scheduler():
    from mxnet_tpu import lr_scheduler, optimizer as opt_mod
    from mxnet_tpu.guardrails.monitor import set_cumulative_lr_backoff
    sched = lr_scheduler.FactorScheduler(step=100, factor=1.0)
    o = opt_mod.create("sgd", learning_rate=0.2, lr_scheduler=sched)
    base = o.learning_rate
    set_cumulative_lr_backoff(o, 0.5)
    assert o.learning_rate == pytest.approx(base * 0.5)
    # cumulative semantics: re-targets the wrapper, never compounds on it
    set_cumulative_lr_backoff(o, 0.25)
    assert o.learning_rate == pytest.approx(base * 0.25)

    # scheduler-less optimizer: the carried marker makes the call
    # idempotent and restore-proof (rollback #2 after load_states
    # replaced the optimizer must not double-apply rollback #1's factor)
    o2 = opt_mod.create("sgd", learning_rate=0.2)
    set_cumulative_lr_backoff(o2, 0.5)
    assert o2.learning_rate == pytest.approx(0.1)
    set_cumulative_lr_backoff(o2, 0.25)         # carried 0.5 -> 0.25
    assert o2.learning_rate == pytest.approx(0.05)


def test_guard_config_env_defaults(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_GUARD_MAX_SKIPS", "7")
    monkeypatch.setenv("MXNET_TPU_GUARD_LR_BACKOFF", "0.25")
    cfg = GuardConfig()
    assert cfg.max_consecutive_skips == 7
    assert cfg.lr_backoff == pytest.approx(0.25)
    with pytest.raises(mx.MXNetError):
        GuardConfig(mode="nope")
    with pytest.raises(mx.MXNetError):
        GuardConfig.coerce("yes")
    assert GuardConfig.coerce(None) is None
    assert isinstance(GuardConfig.coerce(True), GuardConfig)


def test_rollback_without_root_raises_structured():
    mon = AnomalyMonitor(GuardConfig(max_consecutive_skips=1))
    assert mon.observe(5, False) == "diverged"
    from mxnet_tpu.guardrails import handle_divergence
    with pytest.raises(TrainingDiverged) as ei:
        handle_divergence(mon, 5, restore_fn=lambda: 0, optimizer=None)
    assert ei.value.step == 5 and ei.value.consecutive_skips == 1


# -- faults / report / doctor -----------------------------------------------

def test_poison_helpers():
    x = np.zeros((2, 3), np.float32)
    xp = faults.poison_batch(x, index=4)
    assert np.isnan(xp.reshape(-1)[4]) and not np.isnan(x).any()
    xi = faults.poison_batch(np.zeros((2,), np.int32), value=np.inf)
    assert np.isinf(xi[0])
    sched = faults.PoisonSchedule(at_steps=(2,), persistent_from=5)
    assert [s for s in range(8) if sched.poisoned(s)] == [2, 5, 6, 7]
    assert sched.log == [2, 5, 6, 7]


def test_guard_report_summarizes_journal(tmp_path, jfile):
    mon = AnomalyMonitor(GuardConfig(max_consecutive_skips=100))
    for i in range(3):
        mon.observe(i, False, grad_norm=float("nan"), loss=None)
    mon.observe(3, True, loss=1.0)
    journal.get_journal().event("divergence_rollback", step=9,
                                restored_step=4, reason="test",
                                lr_backoff=0.5, rollback=1,
                                consumer="trainer")
    rep = guard_report(jfile)
    assert rep["ok"] and rep["skipped_steps"] == 3
    assert rep["worst_consecutive_skips"] == 3
    assert rep["rollbacks"][0]["restored_step"] == 4
    assert rep["skips_by_consumer"] == {"trainer": 3}
    bad = guard_report(str(tmp_path / "missing.jsonl"))
    assert not bad["ok"]


def test_doctor_journal_wiring(jfile):
    """The doctor report plumbing (the CLI subprocess run is slow-tier;
    this checks the report builder the CLI calls)."""
    from mxnet_tpu.diagnostics.__main__ import _guardrails_report
    AnomalyMonitor(GuardConfig()).observe(1, False, grad_norm=2.0)
    rep = _guardrails_report(jfile)
    assert rep["ok"] and rep["skipped_steps"] == 1


# -- review regressions ------------------------------------------------------

def test_guard_false_disables_like_none():
    assert GuardConfig.coerce(False) is None
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, guard=False)
    assert tr._guard_cfg is None and tr._monitor is None


def test_update_on_kvstore_guard_skips_and_journals(jfile):
    """guard= must not be silently inert on the update-on-kvstore path:
    a poisoned grad skips the push (params untouched on the store),
    counts, and journals; clean steps then update normally."""
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, update_on_kvstore=True,
                       guard=True)
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 2))
    y = mx.nd.array(rng.randn(8, 1))

    def one_step(poison=False):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        if poison:
            faults.poison_grads(net.collect_params().values())
        tr.step(8)

    one_step()
    assert tr._optimizer_applied_on_kv
    w0 = net.weight.data().asnumpy().copy()
    one_step(poison=True)
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w0)
    assert tr.skipped_steps == 1
    recs = [r for r in _read_journal(jfile)
            if r["kind"] == "nonfinite_grad"]
    assert len(recs) == 1 and recs[0]["consumer"] == "gluon_trainer"
    one_step()
    assert not np.array_equal(net.weight.data().asnumpy(), w0)


def test_run_steps_fp16_stale_scale_window_halves_once(jfile):
    """The loss scale is frozen for a scanned window, so a whole-window
    overflow run must halve the scale ONCE (not /2**num_steps) and count
    ONCE against the consecutive-skip budget — the per-step path would
    have self-healed after one halving. Follow-on in-window skips are
    still journaled (stale_scale marker)."""
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize()
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1},
        mesh=parallel.make_mesh({"data": -1}), compute_dtype="float16",
        guard=GuardConfig(max_consecutive_skips=2))
    tr._scaler.loss_scale = 2.0 ** 40   # every step of the window overflows
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16,))
    tr.run_steps(x, y, num_steps=4)     # must NOT raise TrainingDiverged
    assert tr._scaler.loss_scale == 2.0 ** 39       # one halving
    assert tr._monitor.consecutive_skips == 1       # one budget charge
    assert tr.skipped_steps == 4                    # in-program truth
    recs = [r for r in _read_journal(jfile)
            if r["kind"] == "nonfinite_grad"]
    assert len(recs) == 4
    assert sum(1 for r in recs if r.get("stale_scale")) == 3
    # stale records carry the run's true in-program position, so the
    # doctor report's worst-consecutive metric sees the 4-step run even
    # though the budget was charged once
    assert max(r["consecutive"] for r in recs) == 4
    assert guard_report(jfile)["worst_consecutive_skips"] == 4


def test_fit_does_not_mutate_caller_guard_config(tmp_path, jfile):
    """fit points the rollback at checkpoint_prefix on its own COPY of
    the config — the caller's GuardConfig (possibly shared with another
    trainer) keeps ckpt_root=None."""
    rng = np.random.RandomState(0)
    x = rng.randn(80, 6).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=2, name="fc1"), name="softmax")
    pref = str(tmp_path / "ckpt")
    cfg = GuardConfig(max_consecutive_skips=2, max_rollbacks=0)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    with pytest.raises(TrainingDiverged):
        mod.fit(io.NDArrayIter(np.full_like(x, np.nan), y, batch_size=20),
                num_epoch=2, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                checkpoint_prefix=pref, guard=cfg)
    assert cfg.ckpt_root is None
    cfg2 = cfg.copy()
    cfg2.ckpt_root = "elsewhere"
    assert cfg.ckpt_root is None and cfg2.lr_backoff == cfg.lr_backoff


def test_trainer_restore_rejects_wrong_shape(tmp_path):
    """A checkpoint entry with the right name but wrong shape must fail
    the restore up front (set_data's shape check), not resurface as an
    opaque mid-step error."""
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    fname = str(tmp_path / "bad.params")
    mx.nd.save(fname, {p.name: (mx.nd.zeros((3, 7)) if "weight" in p.name
                                else p.data(p.list_ctx()[0]))
                       for p in tr._params})
    with pytest.raises(mx.MXNetError, match="shape"):
        tr._load_params_file(fname)


def test_grad_datas_first_replica_only():
    """Post-allreduce the replicas are identical: the guard norm must
    count each parameter once, not once per replica (a sqrt(n_ctx)
    inflation would mis-clip and mis-journal)."""
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()
    x = mx.nd.ones((4, 2))
    with autograd.record():
        loss = loss_fn(net(x), mx.nd.zeros((4, 1)))
    loss.backward()
    for p in tr._params:                # simulate 2 identical replicas
        p._grad = list(p._grad) * 2
    all_g = tr._grad_datas()
    one_g = tr._grad_datas(first_replica_only=True)
    assert len(all_g) == 2 * len(one_g)
    _, n_all = fused.host_fetch(*fused.guard_stats(all_g))
    _, n_one = fused.host_fetch(*fused.guard_stats(one_g))
    assert n_all == pytest.approx(n_one * np.sqrt(2), rel=1e-5)


def test_update_on_kvstore_rollback_writes_back_store(tmp_path, jfile):
    """On the update-on-kvstore path the store holds the MASTER weights:
    restore() must write the restored params back into it, or the next
    step's pull silently undoes the rollback with the store's diverged
    trajectory."""
    root = str(tmp_path / "ckpt")
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                       update_on_kvstore=True,
                       guard=GuardConfig(max_consecutive_skips=1,
                                         max_rollbacks=1, ckpt_root=root))
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 2))
    y = mx.nd.array(rng.randn(8, 1))

    def one_step(poison=False):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        if poison:
            faults.poison_grads(net.collect_params().values())
        tr.step(8)

    one_step()
    tr.checkpoint(root)                 # commit EARLY...
    w_commit = net.weight.data().asnumpy().copy()
    for _ in range(3):
        one_step()                      # ...then let the store advance
    assert not np.array_equal(net.weight.data().asnumpy(), w_commit)
    one_step(poison=True)               # -> rollback to the commit
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w_commit)
    # lr=0 makes the next push a store no-op, so the pull exposes the
    # store's content exactly: stale (pre-rollback) weights would come
    # back here if restore skipped the writeback
    tr.set_learning_rate(0.0)
    one_step()
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w_commit)


def test_gluon_lr_backoff_compounds_across_rollbacks(tmp_path, jfile):
    """load_states replaces the optimizer with the checkpoint's pickled
    copy; the cumulative backoff must survive that (rollback #2 lands at
    factor**2, not factor)."""
    root = str(tmp_path / "ckpt")
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                       guard=GuardConfig(max_consecutive_skips=1,
                                         max_rollbacks=2, ckpt_root=root))
    loss_fn = gluon.loss.L2Loss()
    x = mx.nd.array(np.ones((4, 2), np.float32))
    y = mx.nd.array(np.zeros((4, 1), np.float32))

    def poisoned_step():
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        faults.poison_grads(net.collect_params().values())
        tr.step(4)

    tr.checkpoint(root)
    poisoned_step()                     # rollback 1
    assert tr.learning_rate == pytest.approx(0.05)
    poisoned_step()                     # rollback 2: compounds past the
    assert tr.learning_rate == pytest.approx(0.025)  # optimizer reload
    rbs = [r for r in _read_journal(jfile)
           if r["kind"] == "divergence_rollback"]
    assert [r["lr_backoff"] for r in rbs] == [
        pytest.approx(0.5), pytest.approx(0.25)]


def test_fit_commit_root_rejected_with_clear_error(tmp_path, jfile):
    """module.fit rolls back to EPOCH checkpoints; a ckpt_root pointing
    at a resilience.commit directory must fail with an explanation, not
    an opaque 'no loadable checkpoint'."""
    rng = np.random.RandomState(0)
    x = rng.randn(40, 6).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=2, name="fc1"), name="softmax")
    root = str(tmp_path / "commit_root")
    os.makedirs(os.path.join(root, "step-5"))   # commit-layout marker
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    with pytest.raises(TrainingDiverged, match="resilience.commit"):
        mod.fit(io.NDArrayIter(np.full_like(x, np.nan), y, batch_size=20),
                num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                guard=GuardConfig(max_consecutive_skips=1, max_rollbacks=1,
                                  ckpt_root=root))


def test_clip_norm_rejected_on_update_on_kvstore():
    """GuardConfig.clip_norm cannot be honored when the optimizer runs
    on the store during push (no reduced-gradient norm exists yet) — it
    must fail structurally, not silently skip clipping."""
    from mxnet_tpu.base import MXNetError
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, update_on_kvstore=True,
                       guard=GuardConfig(clip_norm=1.0))
    loss_fn = gluon.loss.L2Loss()
    x = mx.nd.array(np.ones((4, 2), np.float32))
    y = mx.nd.array(np.zeros((4, 1), np.float32))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    with pytest.raises(MXNetError, match="update-on-kvstore"):
        tr.step(4)


def test_eager_trainer_loss_spike_divergence(jfile):
    """step(loss=...) feeds the spike monitor on the eager path: a
    sustained finite-loss spike (grads finite throughout) must journal
    loss_spike records and raise TrainingDiverged — without a loss the
    eager trainer can only see the consecutive-skip budget."""
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01},
                       guard=GuardConfig(spike_factor=5.0, spike_window=8,
                                         spike_steps=2))
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 2))
    y = mx.nd.array(rng.randn(8, 1))

    def one_step(reported_loss):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.step(8, loss=mx.nd.array(np.array([reported_loss],
                                             np.float32)))

    with pytest.raises(TrainingDiverged, match="rolling\\s+median"):
        for i in range(20):
            one_step(1.0 if i < 10 else 100.0)
    spikes = [r for r in _read_journal(jfile) if r["kind"] == "loss_spike"]
    assert len(spikes) == 2 and spikes[-1]["run"] == 2


def test_deferred_mode_rejected_with_fp16_scaler():
    """mode='deferred' + fp16 loss scaling can keep neither promise
    (per-step fetches happen for the scale, the monitor is never fed) —
    the combination must fail at construction."""
    from mxnet_tpu.base import MXNetError
    net = _mlp()
    mesh = parallel.make_mesh({"data": -1})
    with pytest.raises(MXNetError, match="deferred"):
        parallel.ShardedTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            optimizer_params={"learning_rate": 0.1}, mesh=mesh,
            compute_dtype="float16",
            guard=GuardConfig(mode="deferred"))


def test_fit_rollback_resets_updater_state(tmp_path, jfile):
    """fit's epoch checkpoints hold params only: a divergence rollback
    must not carry the diverged trajectory's updater moments into the
    restored world — the updater is re-derived fresh."""
    rng = np.random.RandomState(0)
    x = rng.randn(80, 6).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=2, name="fc1"), name="softmax")
    pref = str(tmp_path / "ckpt")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(io.NDArrayIter(x, y, batch_size=20), num_epoch=1,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            checkpoint_prefix=pref)

    xp = x.copy()
    xp[40:] = np.nan        # batches 1-2 clean (momentum accumulates),
    mod2 = mx.mod.Module(   # batches 3-4 poisoned -> rollback -> raise
        net, data_names=("data",), label_names=("softmax_label",))
    with pytest.raises(TrainingDiverged):
        mod2.fit(io.NDArrayIter(xp, y, batch_size=20), num_epoch=2,
                 optimizer="sgd",
                 optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                 checkpoint_prefix=pref, resume=True,
                 guard=GuardConfig(max_consecutive_skips=1,
                                   max_rollbacks=1))
    recs = _read_journal(jfile)
    assert any(r["kind"] == "divergence_rollback" for r in recs)
    # the clean batches populated momentum states; the rollback dropped
    # them and every post-rollback batch was vetoed, so fresh == empty
    assert mod2._updater.states == {}


@pytest.mark.slow
def test_doctor_cli_journal_flag(tmp_path):
    import subprocess
    import sys
    jf = str(tmp_path / "j.jsonl")
    with open(jf, "w") as f:
        f.write(json.dumps({"kind": "nonfinite_grad", "step": 3,
                            "consecutive": 1, "consumer": "t"}) + "\n")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.diagnostics", "doctor",
         "--journal", jf],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    rep = json.loads(out.stdout.strip().splitlines()[-1])
    assert rep["guardrails"]["skipped_steps"] == 1


def test_manual_update_flow_is_guarded(jfile):
    """The documented gradient-accumulation flow (allreduce_grads();
    update()) must carry the same defense as step(): a poisoned grad
    skips the update bitwise, counts, and journals."""
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, guard=True)
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 2))
    y = mx.nd.array(rng.randn(8, 1))

    def one_manual_step(poison=False):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        if poison:
            faults.poison_grads(net.collect_params().values())
        tr.allreduce_grads()
        tr.update(8)

    one_manual_step()
    w0 = net.weight.data().asnumpy().copy()
    one_manual_step(poison=True)
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w0)
    assert tr.skipped_steps == 1
    recs = [r for r in _read_journal(jfile)
            if r["kind"] == "nonfinite_grad"]
    assert len(recs) == 1 and recs[0]["consumer"] == "gluon_trainer"
    one_manual_step()
    assert not np.array_equal(net.weight.data().asnumpy(), w0)


def test_manual_flow_guards_kvstore_push(jfile):
    """Manual flow on update-on-kvstore: the optimizer runs on the
    store during allreduce_grads()'s push, so the pre-push guard must
    veto the push there — a NaN push would corrupt the stored params."""
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, update_on_kvstore=True,
                       guard=True)
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 2))
    y = mx.nd.array(rng.randn(8, 1))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    tr.allreduce_grads()
    tr.update(8)
    assert tr._optimizer_applied_on_kv
    w0 = net.weight.data().asnumpy().copy()
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    faults.poison_grads(net.collect_params().values())
    tr.allreduce_grads()
    tr.update(8)
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w0)
    assert tr.skipped_steps == 1
    assert any(r["kind"] == "nonfinite_grad" for r in _read_journal(jfile))


def test_fp16_journaled_grad_norm_is_unscaled(jfile):
    """nonfinite_grad.grad_norm parity across trainer paths: under fp16
    AMP the eager step's gradients still carry the loss scale, but the
    journaled norm must be the UNscaled one (the fused trainers divide
    the scale out in-program) — otherwise the same model journals norms
    loss_scale x larger on the eager path."""
    from mxnet_tpu.contrib import amp
    try:
        amp.init("float16")
        net = gluon.nn.Dense(1, in_units=2)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1},
                           guard=GuardConfig(max_consecutive_skips=100))
        amp.init_trainer(tr)
        tr._amp_loss_scaler.loss_scale = 128.0
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.randn(8, 2))
        y = mx.nd.array(rng.randn(8, 1))
        loss_fn = gluon.loss.L2Loss()
        with autograd.record():
            loss = loss_fn(net(x), y)
            with amp.scale_loss(loss, tr) as scaled:
                scaled.backward()
        # grads are finite (scaled by 128); a NaN loss forces the skip,
        # so the record carries the finite grad norm
        scaled_norm = np.sqrt(sum(
            float(np.sum(np.square(p.grad().asnumpy())))
            for p in net.collect_params().values()))
        tr.step(8, loss=mx.nd.array([np.nan]))
        recs = [r for r in _read_journal(jfile)
                if r["kind"] == "nonfinite_grad"]
        assert len(recs) == 1
        np.testing.assert_allclose(recs[0]["grad_norm"],
                                   scaled_norm / 128.0, rtol=1e-5)
    finally:
        amp.reset()


def test_bucketing_module_guard_sees_gradients(jfile):
    """fit(guard=) must not be blind on BucketingModule: _grad_datas
    delegates to the active bucket's executor, so a NaN batch is vetoed
    and journaled (it used to silently return None -> no check at all)."""
    def sym_gen(seq_len):
        data = sym.var("data")
        fc = sym.FullyConnected(data, num_hidden=8, name="fc")
        return (sym.SoftmaxOutput(fc, name="softmax"),
                ("data",), ("softmax_label",))

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10)
    batch = io.DataBatch(
        data=[mx.nd.array(np.full((4, 10), np.nan, np.float32))],
        label=[mx.nd.zeros((4,))], bucket_key=10,
        provide_data=[io.DataDesc("data", (4, 10))],
        provide_label=[io.DataDesc("softmax_label", (4,))])
    mod.bind(batch.provide_data, batch.provide_label)
    mod.init_params()
    mod.init_optimizer()
    mod.forward_backward(batch)
    assert mod._grad_datas()
    mon = AnomalyMonitor(GuardConfig(max_consecutive_skips=100))
    assert mod._guarded_veto(mon, 0, None) is True
    assert any(r["kind"] == "nonfinite_grad" for r in _read_journal(jfile))


def test_manual_flow_counts_steps_and_checkpoints_unguarded(tmp_path):
    """The manual flow must advance _step_count with NO guard attached
    too: checkpoint() defaults its step to the counter, so a stuck
    counter makes every later checkpoint() hit the already-committed
    branch and silently stop saving progress."""
    root = str(tmp_path / "ckpt")
    net = gluon.nn.Dense(1, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 2))
    y = mx.nd.array(rng.randn(8, 1))

    def one_manual_step():
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.allreduce_grads()
        tr.update(8)

    one_manual_step()
    assert tr.checkpoint(root) == 1
    one_manual_step()
    one_manual_step()
    assert tr._step_count == 3
    assert tr.checkpoint(root) == 3     # a NEW step commits, not a no-op


def test_fp16_norm_not_double_unscaled_after_amp_unscale(jfile):
    """The amp.unscale() manual pattern: grads no longer carry the loss
    scale when step() runs, so the journaled norm must NOT be divided
    by the scale again (trainer._scale tracks what the grads carry)."""
    from mxnet_tpu.contrib import amp
    try:
        amp.init("float16")
        net = gluon.nn.Dense(1, in_units=2)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1},
                           guard=GuardConfig(max_consecutive_skips=100))
        amp.init_trainer(tr)
        tr._amp_loss_scaler.loss_scale = 128.0
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.randn(8, 2))
        y = mx.nd.array(rng.randn(8, 1))
        loss_fn = gluon.loss.L2Loss()
        with autograd.record():
            loss = loss_fn(net(x), y)
            with amp.scale_loss(loss, tr) as scaled:
                scaled.backward()
        amp.unscale(tr)                  # grads now carry NO scale
        true_norm = np.sqrt(sum(
            float(np.sum(np.square(p.grad().asnumpy())))
            for p in net.collect_params().values()))
        tr.step(8, loss=mx.nd.array([np.nan]))   # force a skip record
        recs = [r for r in _read_journal(jfile)
                if r["kind"] == "nonfinite_grad"]
        assert len(recs) == 1
        np.testing.assert_allclose(recs[0]["grad_norm"], true_norm,
                                   rtol=1e-5)
    finally:
        amp.reset()


def test_bucketing_module_divergence_rollback_backs_off_lr(tmp_path, jfile):
    """BucketingModule rollback protocol: divergence must restore the
    epoch checkpoint, back off the (bucket-shared) optimizer's LR and
    journal — not crash on a missing _optimizer attribute."""
    from mxnet_tpu import model

    def sym_gen(seq_len):
        data = sym.var("data")
        fc = sym.FullyConnected(data, num_hidden=8, name="fc")
        return (sym.SoftmaxOutput(fc, name="softmax"),
                ("data",), ("softmax_label",))

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10)
    batch = io.DataBatch(
        data=[mx.nd.array(np.full((4, 10), np.nan, np.float32))],
        label=[mx.nd.zeros((4,))], bucket_key=10,
        provide_data=[io.DataDesc("data", (4, 10))],
        provide_label=[io.DataDesc("softmax_label", (4,))])
    mod.bind(batch.provide_data, batch.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.4})
    opts = mod._guard_optimizers()
    assert len(opts) == 1
    pref = str(tmp_path / "bkt")
    arg, aux = mod.get_params()
    model.save_checkpoint(pref, 0, mod.symbol, arg, aux)
    mon = AnomalyMonitor(GuardConfig(max_consecutive_skips=1,
                                     max_rollbacks=1, ckpt_root=pref))
    mod.forward_backward(batch)
    assert mod._guarded_veto(mon, 1, pref) is True
    assert mon.rollbacks == 1
    assert mod._guard_optimizers()[0].learning_rate == pytest.approx(0.2)
    assert any(r["kind"] == "divergence_rollback"
               for r in _read_journal(jfile))


def test_fit_vetoed_batch_kept_out_of_train_metric(jfile):
    """One poisoned batch is absorbed by the guard — it must not leak
    NaN forward outputs into the epoch's running training metric."""
    rng = np.random.RandomState(0)
    x = rng.randn(40, 6).astype(np.float32)
    x[:20] = np.nan                     # exactly the first batch
    y = (rng.randn(40) > 0).astype(np.float32)
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=2, name="fc1"), name="softmax")
    from mxnet_tpu import metric as metric_mod
    m = metric_mod.create("ce")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(io.NDArrayIter(x, y, batch_size=20), num_epoch=1,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            eval_metric=m, guard=GuardConfig(max_consecutive_skips=10))
    name, val = m.get_name_value()[0]
    assert np.isfinite(val), (name, val)
    assert any(r["kind"] == "nonfinite_grad" for r in _read_journal(jfile))


def test_unguarded_sharded_lets_nonfinite_surface():
    """Skip-step is strictly opt-in: with no guard and no scaler a NaN
    batch must land in the parameters and surface (pre-guardrails
    behavior) — an unjournaled silent skip would freeze training
    invisibly."""
    tr, x, y = _sharded(guard=None)
    tr.step(x, y)
    w0 = _weights(tr)
    loss = tr.step(faults.poison_batch(x), y)
    assert not np.isfinite(loss.asscalar())
    assert tr.skipped_steps == 0
    assert any(not np.isfinite(w).all() for w in _weights(tr))


def test_pipelined_scaler_resolves_at_first_trace():
    """amp.init("float16") AFTER construction but BEFORE the first step
    must still get a loss scaler: the forward's amp casts resolve at
    trace time, so the scaler decision re-resolves there too."""
    from mxnet_tpu.contrib import amp
    try:
        tmp = None
        tr, x, y = _pipelined(tmp, guard=True)
        assert tr._scaler is None
        amp.init("float16")
        tr.step(x, y)
        assert tr._scaler is not None
    finally:
        amp.reset()


def test_sharded_scaler_follows_amp_epoch():
    """ShardedTrainer twin of the live-resolution contract:
    amp.init("float16") AFTER construction retraces the step with fp16
    casts (_maybe_invalidate_amp), and the scaler must appear with them
    — an overflow then skips in-program and halves the scale instead of
    silently applying NaN grads under a stale __init__ snapshot. An
    explicitly pinned compute_dtype stays pinned."""
    from mxnet_tpu.contrib import amp
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16,))
    try:
        net = _mlp()
        tr = parallel.ShardedTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            optimizer_params={"learning_rate": 0.1},
            mesh=parallel.make_mesh({"data": -1}), guard=True)
        assert tr._scaler is None
        tr.step(x, y)
        amp.init("float16")
        tr.step(x, y)
        assert tr._scaler is not None
        tr._scaler.loss_scale = 2.0 ** 40     # force an fp16 overflow
        w0 = _weights(tr)
        s_before = tr._scaler.loss_scale
        tr.step(x, y)
        assert tr._scaler.loss_scale == s_before / 2
        for a, b in zip(w0, _weights(tr)):
            np.testing.assert_array_equal(a, b)
        assert tr.skipped_steps >= 1
        amp.reset()
        tr.step(x, y)
        assert tr._scaler is None             # amp.reset drops it again

        pinned = parallel.ShardedTrainer(
            _mlp(), gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            optimizer_params={"learning_rate": 0.1},
            mesh=parallel.make_mesh({"data": -1}),
            compute_dtype="bfloat16")
        amp.init("float16")
        pinned.step(x, y)
        assert pinned._scaler is None         # explicit dtype stays pinned
    finally:
        amp.reset()

"""Gluon tests (modeled on ref: tests/python/unittest/test_gluon.py —
eager/hybrid consistency is this build's analog of the reference's CPU↔GPU
check_consistency, SURVEY §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(3, 4))
    p.initialize(init=mx.init.One())
    assert p.data().shape == (3, 4)
    assert np.allclose(p.data().asnumpy(), 1)
    assert p.grad().shape == (3, 4)
    p.zero_grad()
    assert np.allclose(p.grad().asnumpy(), 0)


def test_parameter_deferred_init():
    dense = nn.Dense(5)
    dense.initialize()
    with pytest.raises(Exception):
        dense.weight.data()
    out = dense(nd.ones((2, 7)))
    assert out.shape == (2, 5)
    assert dense.weight.shape == (5, 7)


def test_parameter_grad_req_null():
    p = gluon.Parameter("aux", shape=(2,), grad_req="null")
    p.initialize()
    with pytest.raises(Exception):
        p.grad()


def test_dense_numeric():
    dense = nn.Dense(3, use_bias=True, in_units=4)
    dense.initialize(mx.init.One())
    x = nd.array(np.arange(8).reshape(2, 4).astype(np.float32))
    out = dense(x).asnumpy()
    expected = x.asnumpy().sum(axis=1, keepdims=True) * np.ones((2, 3))
    assert np.allclose(out, expected)


def test_sequential_and_getitem():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    assert len(net) == 2
    assert isinstance(net[0], nn.Dense)
    net.initialize()
    assert net(nd.ones((1, 3))).shape == (1, 2)


def test_conv2d_shapes():
    conv = nn.Conv2D(8, kernel_size=3, strides=2, padding=1)
    conv.initialize()
    out = conv(nd.ones((2, 3, 16, 16)))
    assert out.shape == (2, 8, 8, 8)
    assert conv.weight.shape == (8, 3, 3, 3)


def test_conv_groups():
    conv = nn.Conv2D(8, kernel_size=1, groups=2, use_bias=False)
    conv.initialize()
    out = conv(nd.ones((1, 4, 5, 5)))
    assert out.shape == (1, 8, 5, 5)
    assert conv.weight.shape == (8, 2, 1, 1)


def test_conv_transpose():
    deconv = nn.Conv2DTranspose(4, kernel_size=2, strides=2)
    deconv.initialize()
    out = deconv(nd.ones((1, 3, 8, 8)))
    assert out.shape == (1, 4, 16, 16)


def test_pooling_layers():
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    assert np.allclose(nn.MaxPool2D()(x).asnumpy().ravel(),
                       [5, 7, 13, 15])
    assert np.allclose(nn.AvgPool2D()(x).asnumpy().ravel(),
                       [2.5, 4.5, 10.5, 12.5])
    assert nn.GlobalAvgPool2D()(x).shape == (1, 1, 1, 1)
    assert np.allclose(nn.GlobalMaxPool2D()(x).asnumpy().ravel(), [15])


def test_batchnorm_train_vs_eval():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = nd.array(np.random.randn(8, 3, 4, 4).astype(np.float32) * 3 + 1)
    with autograd.record():
        out = bn(x)
    # training: output is normalized per-batch
    o = out.asnumpy()
    assert abs(o.mean()) < 1e-2
    assert abs(o.std() - 1) < 1e-1
    # running stats moved toward batch stats (cold start ADOPTS the
    # first batch's stats outright — see gluon BatchNorm cold-start note)
    assert not np.allclose(bn.running_mean.data().asnumpy(), 0)
    np.testing.assert_allclose(bn.running_mean.data().asnumpy(),
                               x.asnumpy().mean(axis=(0, 2, 3)),
                               rtol=1e-5)
    # second step momentum-mixes; eval then uses blended running stats,
    # which differ from any single batch's normalization
    x2 = nd.array(np.random.randn(8, 3, 4, 4).astype(np.float32) * 2 - 3)
    with autograd.record():
        o2 = bn(x2).asnumpy()
    out_eval = bn(x2).asnumpy()
    assert not np.allclose(o2, out_eval)


def test_dropout_train_eval():
    do = nn.Dropout(0.5)
    x = nd.ones((100, 100))
    with autograd.record():
        y = do(x).asnumpy()
    assert (y == 0).mean() > 0.3
    y_eval = do(x).asnumpy()
    assert np.allclose(y_eval, 1)


def test_embedding_layer():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    out = emb(nd.array([[1, 2], [3, 4]]))
    assert out.shape == (2, 2, 4)


def test_layernorm_layer():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    x = nd.array(np.random.randn(3, 6).astype(np.float32) * 5)
    o = ln(x).asnumpy()
    assert np.allclose(o.mean(axis=-1), 0, atol=1e-5)


def test_hybridize_consistency_forward_grad():
    """The §4 'check_consistency' analog: same math eager vs jitted."""
    np.random.seed(2)
    results = []
    for hyb in (False, True):
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize(mx.init.Xavier(), force_reinit=True)
        # identical init across the two nets
        for p, val in zip(net.collect_params().values(),
                          results[0][2] if results else []):
            p.set_data(nd.array(val))
        if hyb:
            net.hybridize()
        x = nd.array(np.random.RandomState(0).randn(5, 8).astype(np.float32))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        grads = [p.grad().asnumpy() for p in net.collect_params().values()]
        vals = [p.data().asnumpy() for p in net.collect_params().values()]
        results.append((loss.asscalar(), grads, vals))
    assert np.allclose(results[0][0], results[1][0], atol=1e-5)
    for g0, g1 in zip(results[0][1], results[1][1]):
        assert np.allclose(g0, g1, atol=1e-5)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    x = nd.ones((2, 4))
    ref_out = net(x).asnumpy()
    path = str(tmp_path / "m.params")
    net.save_parameters(path)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net2.load_parameters(path)
    assert np.allclose(net2(x).asnumpy(), ref_out, atol=1e-6)


def test_trainer_sgd_step():
    net = nn.Dense(1, use_bias=False, in_units=1)
    net.initialize(mx.init.One())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.array([[2.0]])
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(1)
    # w=1: dL/dw = 2*(w*2)*2 = 8 → w' = 1 - 0.8
    assert np.allclose(net.weight.data().asnumpy(), 0.2, atol=1e-6)


def test_trainer_states_roundtrip(tmp_path):
    net = nn.Dense(2, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    x = nd.ones((4, 3))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(4)
    path = str(tmp_path / "trainer.states")
    trainer.save_states(path)
    trainer.load_states(path)
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(4)  # should not raise; state shapes consistent


def test_losses_against_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF
    np.random.seed(3)
    pred = np.random.randn(6, 5).astype(np.float32)
    label = np.random.randint(0, 5, (6,))

    l_mx = gluon.loss.SoftmaxCrossEntropyLoss()(
        nd.array(pred), nd.array(label)).asnumpy()
    l_th = TF.cross_entropy(torch.tensor(pred), torch.tensor(label),
                            reduction="none").numpy()
    assert np.allclose(l_mx, l_th, atol=1e-5)

    tgt = np.random.randn(6, 5).astype(np.float32)
    l2_mx = gluon.loss.L2Loss()(nd.array(pred), nd.array(tgt)).asnumpy()
    l2_ref = 0.5 * ((pred - tgt) ** 2).mean(axis=1)
    assert np.allclose(l2_mx, l2_ref, atol=1e-5)

    l1_mx = gluon.loss.L1Loss()(nd.array(pred), nd.array(tgt)).asnumpy()
    assert np.allclose(l1_mx, np.abs(pred - tgt).mean(axis=1), atol=1e-5)

    bce_mx = gluon.loss.SigmoidBCELoss()(
        nd.array(pred), nd.array((tgt > 0).astype(np.float32))).asnumpy()
    bce_th = TF.binary_cross_entropy_with_logits(
        torch.tensor(pred), torch.tensor((tgt > 0).astype(np.float32)),
        reduction="none").numpy().mean(axis=1)
    assert np.allclose(bce_mx, bce_th, atol=1e-5)


def test_label_smoothing_ce_against_torch():
    """Sockeye-style smoothed CE: the fused lse-based form must equal
    torch's cross_entropy(label_smoothing=eps) exactly."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF
    np.random.seed(4)
    pred = np.random.randn(6, 5).astype(np.float32)
    label = np.random.randint(0, 5, (6,))
    for eps in (0.1, 0.3):
        l_mx = gluon.loss.SoftmaxCrossEntropyLoss(label_smoothing=eps)(
            nd.array(pred), nd.array(label)).asnumpy()
        l_th = TF.cross_entropy(torch.tensor(pred), torch.tensor(label),
                                reduction="none",
                                label_smoothing=eps).numpy()
        assert np.allclose(l_mx, l_th, atol=1e-5), (eps, l_mx, l_th)
    # from_logits path agrees with the fused path
    logp = pred - np.log(np.exp(pred).sum(1, keepdims=True))
    l_fl = gluon.loss.SoftmaxCrossEntropyLoss(
        label_smoothing=0.1, from_logits=True)(
        nd.array(logp), nd.array(label)).asnumpy()
    l_fused = gluon.loss.SoftmaxCrossEntropyLoss(label_smoothing=0.1)(
        nd.array(pred), nd.array(label)).asnumpy()
    assert np.allclose(l_fl, l_fused, atol=1e-5)
    with pytest.raises(Exception):
        gluon.loss.SoftmaxCrossEntropyLoss(label_smoothing=0.1,
                                           sparse_label=False)


def test_ctc_loss_against_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF
    T, N, C, L = 10, 2, 6, 3
    np.random.seed(4)
    logits = np.random.randn(N, T, C).astype(np.float32)
    labels = np.random.randint(0, C - 1, (N, L)).astype(np.float32)
    loss = gluon.loss.CTCLoss()(nd.array(logits), nd.array(labels)).asnumpy()
    ref = TF.ctc_loss(
        torch.log_softmax(torch.tensor(logits.transpose(1, 0, 2)), 2),
        torch.tensor(labels, dtype=torch.long),
        torch.full((N,), T, dtype=torch.long),
        torch.full((N,), L, dtype=torch.long),
        blank=C - 1, reduction="none").numpy()
    assert np.allclose(loss, ref, atol=1e-4)


def test_metrics():
    acc = mx.metric.Accuracy()
    acc.update(nd.array([1, 0, 1]), nd.array([[0.2, 0.8], [0.9, 0.1],
                                              [0.4, 0.6]]))
    assert acc.get()[1] == 1.0
    topk = mx.metric.TopKAccuracy(top_k=2)
    topk.update(nd.array([2]), nd.array([[0.3, 0.4, 0.33]]))
    assert topk.get()[1] == 1.0
    mse = mx.metric.create("mse")
    mse.update(nd.array([1.0, 2.0]), nd.array([1.5, 2.5]))
    assert np.allclose(mse.get()[1], 0.25)
    comp = mx.metric.create(["acc", "mse"])
    names, values = (comp.get())
    assert len(names) == 2


def test_kvstore_push_pull():
    kv = mx.kv.create("device")
    kv.init("w", nd.ones((2, 2)))
    out = nd.zeros((2, 2))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), 1)
    kv.push("w", [nd.ones((2, 2)) * 2, nd.ones((2, 2)) * 3])
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), 5)


def test_kvstore_optimizer():
    kv = mx.kv.create("device")
    kv.init(0, nd.ones((3,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push(0, nd.ones((3,)))
    out = nd.zeros((3,))
    kv.pull(0, out=out)
    assert np.allclose(out.asnumpy(), 0.9)


def test_kvstore_dist_async_rejected():
    with pytest.raises(Exception):
        mx.kv.create("dist_async")


def test_split_and_load():
    ctxs = [mx.cpu(0), mx.cpu(1)]
    parts = gluon.utils.split_and_load(nd.arange(8).reshape(4, 2), ctxs)
    assert len(parts) == 2
    assert parts[0].shape == (2, 2)


def test_clip_global_norm():
    arrays = [nd.ones((2,)) * 3, nd.ones((2,)) * 4]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    assert norm == pytest.approx(np.sqrt(9 * 2 + 16 * 2), rel=1e-4)
    total = sum(float(nd.sum(nd.square(a)).asscalar()) for a in arrays)
    assert np.sqrt(total) == pytest.approx(1.0, rel=1e-3)

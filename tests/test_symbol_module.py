"""Symbol & Module tests (ref: tests/python/unittest/test_symbol.py,
test_module.py, tests/python/train/test_mlp.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io, sym


def _mlp_symbol():
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=32, name="fc1")
    act1 = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act1, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def test_symbol_compose_and_arguments():
    net = _mlp_symbol()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight",
                    "fc2_bias", "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_symbol_infer_shape():
    net = _mlp_symbol()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(
        data=(8, 16), fc1_weight=(32, 16), fc1_bias=(32,),
        fc2_weight=(4, 32), fc2_bias=(4,), softmax_label=(8,))
    assert out_shapes == [(8, 4)]


def test_symbol_eval():
    a = sym.var("a")
    b = sym.var("b")
    c = (a + b) * 2.0
    out = c.eval(a=mx.nd.ones((2, 2)), b=mx.nd.ones((2, 2)))
    np.testing.assert_allclose(out[0].asnumpy(), np.full((2, 2), 4.0))


def test_symbol_json_roundtrip(tmp_path):
    net = _mlp_symbol()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    path = str(tmp_path / "net-symbol.json")
    net.save(path)
    net3 = sym.load(path)
    assert net3.list_outputs() == net.list_outputs()


def test_symbol_group_and_internals():
    a = sym.var("a")
    fc = sym.FullyConnected(a, num_hidden=8, name="fc")
    act = sym.Activation(fc, act_type="tanh", name="t")
    grp = sym.Group([fc, act])
    assert len(grp.list_outputs()) == 2
    internals = act.get_internals()
    assert "fc_output" in internals.list_outputs()


def test_simple_bind_forward_backward():
    net = _mlp_symbol()
    exe = net.simple_bind(data=(4, 10), softmax_label=(4,))
    exe.arg_dict["data"][:] = mx.nd.random.normal(shape=(4, 10))
    exe.arg_dict["softmax_label"][:] = mx.nd.array([0, 1, 2, 3])
    for name in ("fc1_weight", "fc2_weight"):
        exe.arg_dict[name][:] = mx.nd.random.normal(
            shape=exe.arg_dict[name].shape, scale=0.1)
    outs = exe.forward(is_train=True)
    assert outs[0].shape == (4, 4)
    np.testing.assert_allclose(outs[0].asnumpy().sum(axis=1),
                               np.ones(4), rtol=1e-5)
    exe.backward()
    g = exe.grad_dict["fc1_weight"].asnumpy()
    assert np.abs(g).sum() > 0
    # labels/data get no grads by default req dict? data has write here;
    # softmax label gradient must be zero (terminal loss semantics)
    gl = exe.grad_dict.get("softmax_label")
    if gl is not None:
        assert np.abs(gl.asnumpy()).sum() == 0


def test_module_fit_mlp():
    """The reference's MLP convergence gate (tests/python/train/test_mlp.py)
    shrunk to synthetic separable data."""
    rng = np.random.RandomState(0)
    n, d = 400, 10
    w_true = rng.randn(d, 4)
    x = rng.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1).astype(np.float32)
    train = io.NDArrayIter(x, y, batch_size=40, shuffle=True)

    net = _mlp_symbol()
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(train, num_epoch=12, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9})
    score = mod.score(train, "acc")
    assert score[0][1] > 0.9, f"MLP failed to converge: {score}"


def test_module_predict_and_checkpoint(tmp_path):
    rng = np.random.RandomState(1)
    x = rng.randn(20, 6).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    it = io.NDArrayIter(x, y, batch_size=5)
    net = _mlp_symbol()
    mod = mx.mod.Module(net)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params()
    mod.init_optimizer()
    preds = mod.predict(it)
    assert preds.shape == (20, 4)
    prefix = str(tmp_path / "ckpt")
    mod.save_checkpoint(prefix, 3)
    sym2, arg_params, aux_params = mx.model.load_checkpoint(prefix, 3)
    assert sym2.list_arguments() == net.list_arguments()
    assert "fc1_weight" in arg_params
    # weights round-trip exactly
    w0 = mod.get_params()[0]["fc1_weight"].asnumpy()
    np.testing.assert_allclose(arg_params["fc1_weight"].asnumpy(), w0)


def test_module_batchnorm_aux_updates():
    data = sym.var("data")
    bn = sym.BatchNorm(data, name="bn")
    out = sym.FullyConnected(bn, num_hidden=2, name="fc")
    net = sym.SoftmaxOutput(out, name="softmax")
    assert sorted(net.list_auxiliary_states()) == \
        ["bn_moving_mean", "bn_moving_var"]
    mod = mx.mod.Module(net)
    it = io.NDArrayIter(np.random.randn(16, 8).astype(np.float32) * 3 + 1,
                        np.zeros(16), batch_size=8)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params()
    mod.init_optimizer()
    before = mod._exec.aux_dict["bn_moving_mean"].asnumpy().copy()
    batch = next(iter(it))
    mod.forward_backward(batch)
    after = mod._exec.aux_dict["bn_moving_mean"].asnumpy()
    assert not np.allclose(before, after), \
        "BatchNorm running stats must update in training forward"


def test_bucketing_module():
    def sym_gen(seq_len):
        data = sym.var("data")
        fc = sym.FullyConnected(data, num_hidden=8, name="fc")
        out = sym.SoftmaxOutput(fc, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10)
    batch10 = io.DataBatch(
        data=[mx.nd.random.normal(shape=(4, 10))],
        label=[mx.nd.zeros((4,))], bucket_key=10,
        provide_data=[io.DataDesc("data", (4, 10))],
        provide_label=[io.DataDesc("softmax_label", (4,))])
    batch5 = io.DataBatch(
        data=[mx.nd.random.normal(shape=(4, 5))],
        label=[mx.nd.zeros((4,))], bucket_key=5,
        provide_data=[io.DataDesc("data", (4, 5))],
        provide_label=[io.DataDesc("softmax_label", (4,))])
    mod.bind(batch10.provide_data, batch10.provide_label)
    mod.init_params()
    mod.init_optimizer()
    mod.forward(batch10, is_train=True)
    mod.backward()
    mod.update()
    # different bucket needs different fc weight shape — sym_gen makes
    # fc weight depend on input width, so buckets DON'T share it here;
    # shared params are those with matching names AND the default bucket's
    # executor arrays (reference shares by name too)
    mod.forward(batch5, is_train=True)
    mod.backward()
    mod.update()
    assert mod._curr_bucket_key == 5


def test_graph_pass_cse():
    """CSE pass merges identical subgraphs (SURVEY §2.2 #12 machinery)."""
    a = sym.var("a")
    b1 = sym.FullyConnected(a, num_hidden=4, name="fc")
    # build the SAME node twice through different Python objects
    t1 = sym.Activation(b1, act_type="tanh", name="t1")
    t2 = sym.Activation(b1, act_type="tanh", name="t1")
    out = t1 + t2
    n_before = len(out._topo())
    deduped = sym.apply_pass(out, "CSE")
    n_after = len(deduped._topo())
    assert n_after == n_before - 1   # one duplicate Activation removed
    # numerics unchanged
    w = mx.nd.random.normal(shape=(4, 3))
    bias = mx.nd.zeros((4,))
    x = mx.nd.random.normal(shape=(2, 3))
    got1 = out.eval(a=x, fc_weight=w, fc_bias=bias)[0].asnumpy()
    got2 = deduped.eval(a=x, fc_weight=w, fc_bias=bias)[0].asnumpy()
    np.testing.assert_allclose(got1, got2, rtol=1e-6)


def test_env_subgraph_backend_hook(monkeypatch):
    monkeypatch.setenv("MXNET_SUBGRAPH_BACKEND", "CSE")
    a = sym.var("a")
    t1 = sym.Activation(a, act_type="tanh", name="t")
    t2 = sym.Activation(a, act_type="tanh", name="t")
    out = t1 + t2
    exe = out.simple_bind(a=(2, 3))
    exe.arg_dict["a"][:] = mx.nd.ones((2, 3))
    res = exe.forward()[0].asnumpy()
    np.testing.assert_allclose(res, 2 * np.tanh(np.ones((2, 3))),
                               rtol=1e-6)
    assert len(exe._symbol._topo()) < len(out._topo())


def test_bucketing_many_buckets_memory_sharing():
    """Sockeye-style 20+ buckets (round-1 weak spot #9): parameters must
    be shared across every bucket executor (one storage, like the
    reference's shared_exec memory pool), and cycling through all buckets
    must train without unbounded per-bucket state growth."""
    def sym_gen(seq_len):
        data = sym.var("data")                      # (N, seq_len, 4)
        flat = sym.reshape(data, (-1, 4))           # merge batch x seq
        fc = sym.FullyConnected(flat, num_hidden=6, name="fc",
                                flatten=False)
        out = sym.SoftmaxOutput(fc, name="softmax", multi_output=False)
        return out, ("data",), ("softmax_label",)

    buckets = list(range(4, 28))                   # 24 buckets
    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=max(buckets))

    def batch_for(L):
        return io.DataBatch(
            data=[mx.nd.random.normal(shape=(2, L, 4))],
            label=[mx.nd.zeros((2 * L,))], bucket_key=L,
            provide_data=[io.DataDesc("data", (2, L, 4))],
            provide_label=[io.DataDesc("softmax_label", (2 * L,))])

    first = batch_for(max(buckets))
    mod.bind(first.provide_data, first.provide_label)
    mod.init_params()
    mod.init_optimizer()
    for L in buckets:
        b = batch_for(L)
        mod.forward(b, is_train=True)
        mod.backward()
        mod.update()
    # every bucket executor must reference the SAME parameter storage as
    # the default bucket (weights updated once, visible everywhere)
    default_mod = mod._buckets[mod._default_bucket_key]
    w_default = default_mod.get_params()[0]["fc_weight"]
    for key, m in mod._buckets.items():
        w = m.get_params()[0]["fc_weight"]
        np.testing.assert_array_equal(w.asnumpy(), w_default.asnumpy())
    assert len(mod._buckets) == len(buckets)


def test_sequential_module():
    """SequentialModule chains bound executors, threading outputs into the
    next module's data and gradients back (ref:
    python/mxnet/module/sequential_module.py; reference test:
    tests/python/unittest/test_module.py test_module_layout-adjacent)."""
    rng = np.random.RandomState(2)
    n, d = 400, 10
    w_true = rng.randn(d, 4)
    x = rng.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1).astype(np.float32)
    train = io.NDArrayIter(x, y, batch_size=40, shuffle=True)

    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=32, name="sfc1")
    net1 = sym.Activation(fc1, act_type="relu", name="srelu1")

    data2 = sym.var("data")
    fc2 = sym.FullyConnected(data2, num_hidden=4, name="sfc2")
    net2 = sym.SoftmaxOutput(fc2, name="softmax")

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(net1, label_names=[])) \
       .add(mx.mod.Module(net2), take_labels=True, auto_wiring=True)
    seq.bind(train.provide_data, train.provide_label)
    seq.init_params(initializer=mx.initializer.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "momentum": 0.9})
    metric = mx.metric.create("acc")
    for _epoch in range(12):
        train.reset()
        metric.reset()
        for batch in train:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
            seq.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.9, f"SequentialModule failed to learn: {metric.get()}"
    # params gather across children; outputs come from the tail module
    arg, _ = seq.get_params()
    assert "sfc1_weight" in arg and "sfc2_weight" in arg
    assert seq.get_outputs()[0].shape == (40, 4)

"""INT8 quantization: op numerics, calibration, model conversion
(ref: tests/python/quantization/test_quantization.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.ops.quantization import quantize_array


def test_quantize_dequantize_roundtrip():
    x = np.random.randn(64, 32).astype(np.float32) * 3
    xq, scale = nd.contrib.quantize_v2(nd.array(x))
    assert xq.asnumpy().dtype == np.int8
    back = nd.contrib.dequantize(xq, scale).asnumpy()
    assert np.abs(back - x).max() <= float(scale.asnumpy()) + 1e-6


def test_quantize_static_range_saturates():
    x = np.array([[-10.0, -1.0, 0.5, 1.0, 10.0]], np.float32)
    xq, scale = nd.contrib.quantize_v2(nd.array(x), min_calib_range=-1.0,
                                       max_calib_range=1.0)
    qv = xq.asnumpy()[0]
    assert qv[0] == -127 and qv[-1] == 127          # clipped
    assert abs(qv[2] - 64) <= 1                     # 0.5 / (1/127)


def test_quantized_fc_matches_fp32():
    x = np.random.randn(8, 16).astype(np.float32)
    w = np.random.randn(4, 16).astype(np.float32)
    b = np.random.randn(4).astype(np.float32)
    xq, xs = quantize_array(x)
    wq, ws = quantize_array(w, channel_axis=0)
    out = nd.contrib.quantized_fully_connected(
        nd.array(np.asarray(xq)), nd.array(np.asarray(wq)),
        nd.array(np.asarray(xs)), nd.array(np.asarray(ws)),
        nd.array(b), num_hidden=4).asnumpy()
    want = x @ w.T + b
    assert np.abs(out - want).max() / np.abs(want).max() < 0.05


def test_quantized_conv_matches_fp32():
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    w = np.random.randn(5, 3, 3, 3).astype(np.float32)
    xq, xs = quantize_array(x)
    wq, ws = quantize_array(w, channel_axis=0)
    out = nd.contrib.quantized_conv(
        nd.array(np.asarray(xq)), nd.array(np.asarray(wq)),
        nd.array(np.asarray(xs)), nd.array(np.asarray(ws)),
        kernel=(3, 3), pad=(1, 1), num_filter=5, no_bias=True).asnumpy()
    want = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                          pad=(1, 1), num_filter=5,
                          no_bias=True).asnumpy()
    assert np.abs(out - want).max() / np.abs(want).max() < 0.05


def test_entropy_threshold_reasonable():
    # long-tailed data: threshold should clip the tail, not the body
    a = np.concatenate([np.random.randn(100000) * 0.5,
                        np.array([50.0, -60.0])]).astype(np.float32)
    (lo, hi), = q.calib_thresholds_entropy({"t": a}).values()
    assert 1.0 < hi < 20.0, hi


def _train_mlp():
    np.random.seed(7)
    X = np.random.randn(512, 32).astype(np.float32)
    Y = (X @ np.random.randn(32, 5).astype(np.float32)).argmax(1)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(5))
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01})
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(50):
        with autograd.record():
            loss = lf(net(nd.array(X)), nd.array(Y))
        loss.backward()
        tr.step(512)
    return net, X, Y


@pytest.mark.parametrize("mode", ["none", "naive", "entropy"])
def test_quantize_net_accuracy_parity(mode):
    net, X, Y = _train_mlp()
    fp32_acc = (net(nd.array(X)).asnumpy().argmax(1) == Y).mean()
    qnet = q.quantize_net(net, calib_data=[X[:128], X[128:256]],
                          calib_mode=mode)
    q_acc = (qnet(nd.array(X)).asnumpy().argmax(1) == Y).mean()
    assert abs(q_acc - fp32_acc) <= 0.01
    params = qnet.collect_params()
    qw = [k for k in params if k.endswith("_quantized")]
    assert qw and params[qw[0]].data().asnumpy().dtype == np.int8


def test_quantize_model_symbol_level_conv():
    # LeNet-ish conv net through the symbol-level API
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, activation="relu"),
            gluon.nn.MaxPool2D(2), gluon.nn.Flatten(),
            gluon.nn.Dense(10))
    net.initialize()
    net.hybridize()
    x = np.random.randn(4, 1, 12, 12).astype(np.float32)
    want = net(nd.array(x)).asnumpy()
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        net.export(f"{td}/n")
        from mxnet_tpu.model import load_checkpoint
        sym, arg_params, aux_params = load_checkpoint(f"{td}/n", 0)
    qsym, qarg, qaux = q.quantize_model(
        sym, arg_params, aux_params, data_names=["data"],
        calib_mode="naive", calib_data=[x])
    data = [n for n in qsym.list_arguments() if n not in qarg][0]
    ex = qsym.bind(mx.cpu(), dict({data: nd.array(x)}, **qarg),
                   aux_states=qaux)
    got = ex.forward()[0].asnumpy()
    assert np.abs(got - want).max() / max(np.abs(want).max(), 1e-6) < 0.1
    # excluded layers stay fp32
    qsym2, qarg2, _ = q.quantize_model(
        sym, arg_params, aux_params,
        excluded_sym_names=[n.name for n in sym._topo()
                            if n.op == "Convolution"])
    assert not any(k.endswith("conv0_weight_quantized") for k in qarg2)


def _resnet_block_net(classes=8):
    """Two residual blocks (conv-BN-relu ×2 + identity add), the int8
    subgraph-depth shape (ref: mkldnn int8 fused residual subgraphs)."""

    class Residual(gluon.HybridBlock):
        def __init__(self, ch, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.c1 = gluon.nn.Conv2D(ch, 3, padding=1, use_bias=False)
                self.b1 = gluon.nn.BatchNorm()
                self.c2 = gluon.nn.Conv2D(ch, 3, padding=1, use_bias=False)
                self.b2 = gluon.nn.BatchNorm()

        def hybrid_forward(self, F, x):
            y = F.Activation(self.b1(self.c1(x)), act_type="relu")
            y = self.b2(self.c2(y))
            return F.Activation(x + y, act_type="relu")

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, use_bias=False),
            gluon.nn.BatchNorm(), gluon.nn.Activation("relu"),
            Residual(16), Residual(16),
            gluon.nn.GlobalAvgPool2D(), gluon.nn.Flatten(),
            gluon.nn.Dense(classes))
    return net


def test_int8_chains_stay_int8_through_residual_blocks():
    """Round-2 verdict #9: <=1 quantize/dequantize pair per residual
    block — BN folds into convs and pool/relu/add run on int8, so the
    chain never round-trips to fp32 between layers."""
    net = _resnet_block_net()
    net.initialize()
    x = np.random.randn(2, 3, 16, 16).astype(np.float32)
    # warm BN stats so folding has non-degenerate running statistics
    for _ in range(3):
        with autograd.record():
            net(nd.array(np.random.randn(8, 3, 16, 16)
                         .astype(np.float32)))
    net.hybridize()
    want = net(nd.array(x)).asnumpy()
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        net.export(f"{td}/n")
        from mxnet_tpu.model import load_checkpoint
        sym, arg_params, aux_params = load_checkpoint(f"{td}/n", 0)
    qsym, qarg, qaux = q.quantize_model(
        sym, arg_params, aux_params, data_names=["data"],
        calib_mode="naive", calib_data=[x])
    ops = [n.op for n in qsym._topo() if n.op]
    n_quant = sum(o == "_contrib_quantize_v2" for o in ops)
    n_dequant = sum(o == "_contrib_dequantize" for o in ops)
    n_res_blocks = 2
    # whole 5-conv trunk: ONE entry quantize; ONE dequantize at the
    # trunk exit (global pool -> Dense head requantizes internally)
    assert n_quant <= 1 + n_res_blocks, (n_quant, ops)
    assert n_dequant <= 1 + n_res_blocks, (n_dequant, ops)
    assert "BatchNorm" not in ops, "BN must fold into the convolutions"
    assert "_contrib_quantized_elemwise_add" in ops
    assert "_contrib_quantized_act" in ops
    # accuracy parity on the quantized graph
    data = [n for n in qsym.list_arguments() if n not in qarg][0]
    ex = qsym.bind(mx.cpu(), dict({data: nd.array(x)}, **qarg),
                   aux_states=qaux)
    got = ex.forward()[0].asnumpy()
    rel = np.abs(got - want).max() / max(np.abs(want).max(), 1e-6)
    assert rel < 0.15, f"int8 chain output off by {rel:.3f}"


def test_fold_batchnorm_exact():
    """BN folding alone (no quantization) must be numerically exact."""
    net = _resnet_block_net()
    net.initialize()
    for _ in range(3):
        with autograd.record():
            net(nd.array(np.random.randn(8, 3, 16, 16)
                         .astype(np.float32)))
    net.hybridize()
    x = np.random.randn(2, 3, 16, 16).astype(np.float32)
    want = net(nd.array(x)).asnumpy()
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        net.export(f"{td}/n")
        from mxnet_tpu.model import load_checkpoint
        sym, arg_params, aux_params = load_checkpoint(f"{td}/n", 0)
    fsym, fargs, faux = q.fold_batchnorm(sym, arg_params, aux_params)
    assert not any(n.op == "BatchNorm" for n in fsym._topo())
    data = [n for n in fsym.list_arguments() if n not in fargs][0]
    ex = fsym.bind(mx.cpu(),
                   dict({data: nd.array(x)},
                        **{k: nd.array(v) for k, v in fargs.items()}),
                   aux_states={k: nd.array(v) for k, v in faux.items()})
    got = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_quantize_model_rejects_other_dtypes():
    import tempfile
    net, X, _ = _train_mlp()
    net.hybridize()
    net(nd.array(X[:1]))
    with tempfile.TemporaryDirectory() as td:
        net.export(f"{td}/n")
        from mxnet_tpu.model import load_checkpoint
        sym, a, x = load_checkpoint(f"{td}/n", 0)
    with pytest.raises(MXNetError, match="int8"):
        q.quantize_model(sym, a, x, quantized_dtype="uint8")

"""Model-zoo construction + forward-shape tests
(ref: tests/python/unittest/test_gluon_model_zoo.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision


def _check(name, x_shape, classes=10):
    net = vision.get_model(name, classes=classes)
    net.initialize()
    x = mx.nd.array(np.random.randn(*x_shape).astype(np.float32))
    out = net(x)
    assert out.shape == (x_shape[0], classes)


def test_resnet18_v1_thumbnail():
    net = vision.resnet18_v1(classes=10, thumbnail=True)
    net.initialize()
    out = net(mx.nd.array(np.random.randn(2, 3, 32, 32).astype(np.float32)))
    assert out.shape == (2, 10)


def test_resnet_both_versions_agree_on_shape():
    for name in ("resnet18_v1", "resnet18_v2"):
        _check(name, (1, 3, 224, 224))


def test_mobilenet_v1_v2():
    _check("mobilenet0.25", (1, 3, 224, 224))
    _check("mobilenetv2_0.25", (1, 3, 224, 224))


def test_squeezenet():
    _check("squeezenet1.1", (1, 3, 224, 224))


def test_get_model_unknown():
    with pytest.raises(mx.MXNetError):
        vision.get_model("resnet1337_v9")


def test_pretrained_raises():
    with pytest.raises(mx.MXNetError):
        vision.get_model("resnet18_v1", pretrained=True)


def test_model_zoo_hybridize_train_step():
    """Flagship-family model trains one step under the fused SPMD path."""
    from mxnet_tpu import gluon, parallel
    net = vision.resnet18_v1(classes=8, thumbnail=True)
    net.initialize()
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        mesh=parallel.make_mesh({"data": 8}))
    x = np.random.randn(16, 3, 32, 32).astype(np.float32)
    y = np.random.randint(0, 8, (16,))
    l0 = tr.step(x, y).asscalar()
    l1 = tr.step(x, y).asscalar()
    assert np.isfinite(l0) and np.isfinite(l1)

"""The hardware-parity sweep doubles as a CI self-check: on CPU the
"device" and the oracle share a backend, so this validates the sweep's
own oracles (numpy formulas, shapes, tolerances) — the TPU run
(`benchmarks/hw_parity.py` on the chip) then measures real divergence
against known-good math. Ref: SURVEY §4's check_consistency tier."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))        # repo root: benchmarks/ is not
                                        # an installed package
import benchmarks.hw_parity as hw


def test_parity_sweep_oracles_self_consistent():
    assert hw.main() == 0

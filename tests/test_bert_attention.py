"""Tests: flash/ring attention + BERT family (driver config #3 path;
long-context/sequence-parallel capability per SURVEY §5.7)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon.model_zoo import bert
from mxnet_tpu.parallel.ring_attention import (attention_reference,
                                               blockwise_attention,
                                               ring_attention)


def _qkv(B=2, H=4, S=32, D=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    return mk(), mk(), mk()


def test_blockwise_matches_reference():
    q, k, v = _qkv()
    for causal in (False, True):
        ref = attention_reference(q, k, v, causal=causal)
        out = blockwise_attention(q, k, v, block_size=8, causal=causal)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=1e-5, atol=1e-5)


def test_ring_matches_reference():
    q, k, v = _qkv()
    mesh = parallel.make_mesh({"data": 2, "seq": 4})
    for causal in (False, True):
        ref = attention_reference(q, k, v, causal=causal)
        out = ring_attention(q, k, v, mesh=mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=1e-5, atol=1e-5)


def test_ring_gradients_match():
    q, k, v = _qkv(S=16)
    mesh = parallel.make_mesh({"seq": 8})

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_attention_op_via_nd():
    q, k, v = _qkv()
    out = mx.nd.contrib.flash_attention(
        mx.nd.array(np.asarray(q)), mx.nd.array(np.asarray(k)),
        mx.nd.array(np.asarray(v)), block_size=8)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def _tiny_bert(**kw):
    cfg = dict(num_layers=2, units=32, hidden_size=64, num_heads=4,
               max_length=64, vocab_size=100, dropout=0.1)
    cfg.update(kw)
    return bert.BERTModel(**cfg)


def test_bert_forward_shapes():
    net = _tiny_bert()
    net.initialize()
    B, S = 2, 16
    tokens = mx.nd.array(np.random.randint(0, 100, (B, S)))
    types = mx.nd.array(np.zeros((B, S)))
    seq, pooled, nsp, mlm = net(tokens, types)
    assert seq.shape == (B, S, 32)
    assert pooled.shape == (B, 32)
    assert nsp.shape == (B, 2)
    assert mlm.shape == (B, S, 100)


def test_bert_mlm_gather():
    net = _tiny_bert()
    net.initialize()
    B, S, M = 2, 16, 3
    tokens = mx.nd.array(np.random.randint(0, 100, (B, S)))
    types = mx.nd.array(np.zeros((B, S)))
    positions = mx.nd.array(np.array([[1, 5, 7], [0, 2, 9]]))
    seq, pooled, nsp, mlm = net(tokens, types, masked_positions=positions)
    assert mlm.shape == (B, M, 100)


def test_bert_trains_mlm():
    """A tiny BERT must fit a toy MLM batch (loss decreases) through the
    fused SPMD path."""
    net = _tiny_bert(dropout=0.0, use_classifier=False, use_pooler=False)
    net.initialize()
    B, S = 8, 16
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 100, (B, S))
    types = np.zeros((B, S), dtype=np.int32)

    class MLMLoss(gluon.loss.Loss):
        def __init__(self):
            super().__init__(None, 0)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, pred, label):
            return self._ce(F.reshape(pred, (-1, 100)),
                            F.reshape(label, (-1,)))

    class Wrapper(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, tokens):
            seq, mlm = self.inner(tokens)
            return mlm

    wrapper = Wrapper(net)
    tr = parallel.ShardedTrainer(
        wrapper, MLMLoss(), "adam", {"learning_rate": 3e-3},
        mesh=parallel.make_mesh({"data": 8}))
    losses = [tr.step(tokens, tokens).asscalar() for _ in range(8)]
    assert losses[-1] < losses[0] * 0.8, losses


def test_bert_named_configs():
    net = bert.get_bert_model("bert_12_768_12", vocab_size=50)
    assert net.encoder._num_layers == 12
    with pytest.raises(mx.MXNetError):
        bert.get_bert_model("bert_1_2_3")


def test_ulysses_matches_reference():
    from mxnet_tpu.parallel.ring_attention import ulysses_attention
    q, k, v = _qkv(B=2, H=4, S=32, D=16)
    mesh = parallel.make_mesh({"data": 2, "seq": 4})
    for causal in (False, True):
        ref = attention_reference(q, k, v, causal=causal)
        out = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=1e-5, atol=1e-5)


def test_ulysses_gradients():
    from mxnet_tpu.parallel.ring_attention import ulysses_attention
    q, k, v = _qkv(S=16, H=8)
    mesh = parallel.make_mesh({"seq": 8})

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh=mesh,
                                         causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_u = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_u):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)

"""Gluon-level MoE (VERDICT r4 Weak #4 second half): MoEFFN is a drop-in
layer — expert-parallel all-to-all dispatch under an ``expert`` mesh,
dense-fallback math everywhere else, Switch aux loss auto-added by
ShardedTrainer."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.contrib.nn import MoEFFN
from mxnet_tpu.parallel import PartitionSpec as P

U, H, E = 8, 16, 4


def _block(k=2, cf=8.0, w=0.01):
    mx.random.seed(2)
    ffn = MoEFFN(units=U, hidden_size=H, num_experts=E, k=k,
                 capacity_factor=cf, aux_loss_weight=w)
    ffn.initialize()
    return ffn


def test_moe_ffn_eager_dense_fallback():
    ffn = _block()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 6, U)
                    .astype(np.float32))
    y = ffn(x)
    assert y.shape == (4, 6, U)
    aux = float(np.asarray(ffn._last_aux_loss))
    # Switch aux: k at perfect balance, >= k otherwise, <= k*E worst case
    assert 1.0 <= aux <= 2 * E


def test_moe_ffn_a2a_matches_dense():
    # with generous capacity nothing drops, so the all-to-all dispatch and
    # the dense formulation are the same math
    ffn = _block(k=2, cf=8.0)
    rng = np.random.RandomState(1)
    x = mx.nd.array(rng.randn(16, U).astype(np.float32))
    y_dense = ffn(x).asnumpy()
    aux_dense = float(np.asarray(ffn._last_aux_loss))
    mesh = parallel.make_mesh({"data": 2, "expert": 4})
    with parallel.use_mesh(mesh):
        y_a2a = ffn(x).asnumpy()
        aux_a2a = float(np.asarray(ffn._last_aux_loss))
    np.testing.assert_allclose(y_a2a, y_dense, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(aux_a2a, aux_dense, rtol=1e-5, atol=1e-6)


def test_moe_ffn_trains_expert_parallel():
    """A tiny MoE tower under ShardedTrainer on a data x expert mesh:
    expert-sharded params, a2a dispatch inside the fused step, aux loss in
    the objective."""
    class Tower(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.proj = gluon.nn.Dense(U, flatten=False)
                self.moe = MoEFFN(units=U, hidden_size=H, num_experts=E,
                                  k=2, capacity_factor=4.0,
                                  aux_loss_weight=0.01)
                self.head = gluon.nn.Dense(8, flatten=False)

        def hybrid_forward(self, F, x):
            h = self.proj(x)
            return self.head(h + self.moe(h))

    mx.random.seed(4)
    net = Tower()
    net.initialize()
    mesh = parallel.make_mesh({"data": 2, "expert": 4})
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 3e-3}, mesh=mesh,
        param_rules=[(r".*expert_.*", P("expert"))])
    rng = np.random.RandomState(0)
    W = rng.randn(12, 8)
    losses = []
    for i in range(25):
        x = rng.randn(16, 12).astype(np.float32)
        y = (x @ W).argmax(-1)
        losses.append(float(tr.step(x, y).asscalar()))
    assert losses[-1] < losses[0], losses
    # the expert weights really are sharded over the expert axis
    w1 = net.moe.expert_w1._data[0]._data
    spec = w1.sharding.spec
    assert tuple(spec)[0] == "expert", spec

    # aux term is in the objective: cranking its weight changes the loss
    mx.random.seed(4)
    net2 = Tower()
    net2.initialize()
    net2.moe.aux_loss_weight = 10.0
    tr2 = parallel.ShardedTrainer(
        net2, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 3e-3}, mesh=mesh,
        param_rules=[(r".*expert_.*", P("expert"))])
    rng = np.random.RandomState(0)
    x = rng.randn(16, 12).astype(np.float32)
    y = (x @ W).argmax(-1)
    l_big = float(tr2.step(x, y).asscalar())
    assert l_big > losses[0] + 5.0, (l_big, losses[0])


def test_moe_ffn_bad_activation():
    ffn = MoEFFN(units=U, hidden_size=H, num_experts=E,
                 activation="swishish")
    ffn.initialize()
    with pytest.raises(MXNetError, match="activation"):
        ffn(mx.nd.ones((4, U)))


def test_moe_ffn_rejected_a2a_warns_not_silent():
    """ADVICE r5: when the configured expert axis EXISTS in the mesh but
    the a2a path is rejected, the dense fallback must warn — a
    misconfigured large-scale run losing expert parallelism (and
    changing numerics: no capacity dropping) must never be silent."""
    import warnings
    ffn = _block()
    rng = np.random.RandomState(3)
    # axis-size mismatch: expert axis of 2 vs num_experts=4
    mesh = parallel.make_mesh({"data": 4, "expert": 2})
    x = mx.nd.array(rng.randn(16, U).astype(np.float32))
    with parallel.use_mesh(mesh):
        with pytest.warns(RuntimeWarning, match="size 2.*num_experts=4"):
            y = ffn(x)
    assert y.shape == (16, U)
    # indivisible tokens: 4x1 mesh matches num_experts but 6 tokens % 4 != 0
    mesh = parallel.make_mesh({"data": 2, "expert": 4})
    x = mx.nd.array(rng.randn(6, U).astype(np.float32))
    with parallel.use_mesh(mesh):
        with pytest.warns(RuntimeWarning, match="not divisible"):
            y = ffn(x)
    assert y.shape == (6, U)
    # no expert axis at all: plain dense use, NO warning
    mesh = parallel.make_mesh({"data": 8})
    x = mx.nd.array(rng.randn(16, U).astype(np.float32))
    with parallel.use_mesh(mesh):
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            ffn(x)

"""The crash matrix for the directory commit protocol
(resilience.commit + parallel/_ckpt; docs/checkpointing.md): kill the
writer at every phase of a simulated 2-rank shard save — staging, each
rank's shard write (at several byte offsets), manifest write, the
publish rename, the latest pointer, GC — and prove a reader always
recovers the previous committed step (or the new one, after the commit
point), bit-exact and validated. Plus: corrupt-latest fallback with a
journaled skip, keep-last-k retention, and the trainer-level
checkpoint/restore(latest) path.

The ``test_smoke_*`` subset is the CI tier-0.5 chaos smoke
(ci/run_tests.sh): seconds, no trainers, pure file layer."""
import json
import os

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.diagnostics import journal
from mxnet_tpu.resilience import commit
from mxnet_tpu.testing import faults

WORLD = 2


def _rank_arrays(step, rank):
    rng = np.random.RandomState(1000 * step + rank)
    return {f"w{rank}|0:4": nd.NDArray(rng.randn(4, 3).astype(np.float32)),
            f"b{rank}|0:4": nd.NDArray(rng.randn(4).astype(np.float32))}


def _save_step(root, step, keep_last=3, barrier=lambda tag: None):
    """The 2-rank commit protocol, ranks played serially in one process
    (the shared-filesystem model multi-host saves assume). Mirrors
    _ckpt.commit_checkpoint's phase order exactly."""
    commit.prepare_stage(root, step)              # rank 0
    barrier("stage")
    stage = commit.stage_dir(root, step)
    for rank in range(WORLD):                     # each rank, its shard
        nd.save(os.path.join(stage, f"ckpt.shard{rank}"),
                _rank_arrays(step, rank))
    barrier("staged")
    commit.finalize(root, step, keep_last=keep_last,
                    meta={"world": WORLD})        # rank 0 commit point
    barrier("committed")


def _read_step(root):
    """What a restoring job would see: newest valid step, all shards
    loaded through the CRC-verified container."""
    got = commit.find_restorable(root)
    if got is None:
        return None, None
    step, manifest = got
    out = {}
    for name in manifest["files"]:
        loaded = nd.load(os.path.join(commit.step_dir(root, step), name))
        out.update({k: v.asnumpy().tobytes() for k, v in loaded.items()})
    return step, out


def _expect(step):
    out = {}
    for rank in range(WORLD):
        out.update({k: v.asnumpy().tobytes()
                    for k, v in _rank_arrays(step, rank).items()})
    return out


def _shard_nbytes(tmp_path):
    p = str(tmp_path / "probe.params")
    nd.save(p, _rank_arrays(7, 0))
    return os.path.getsize(p)


def _matrix_rules(shard_bytes):
    """One kill per protocol phase; shard writes also at byte offsets."""
    rules = []
    for rank in range(WORLD):
        part = f"ckpt.shard{rank}"
        rules += [faults.crash("open", path_part=part),
                  faults.crash("fsync", path_part=part),
                  faults.crash("replace", path_part=part)]
        rules += [faults.crash("write", path_part=part, after_bytes=n)
                  for n in faults.write_offsets(shard_bytes)]
    rules += [faults.crash("write", path_part=commit.MANIFEST),
              faults.crash("fsync", path_part=commit.MANIFEST),
              faults.crash("replace", path_part=commit.MANIFEST),
              faults.crash("publish"),
              faults.crash("write", path_part=commit.LATEST),
              faults.crash("replace", path_part=commit.LATEST),
              faults.crash("gc")]
    return rules


def test_two_rank_crash_matrix_reader_sees_old_or_new(tmp_path):
    """The acceptance criterion: for every injected kill point in the
    2-rank shard commit, a subsequent restore yields a bit-exact OLD or
    NEW checkpoint — never an exception escape, never partial state."""
    shard_bytes = _shard_nbytes(tmp_path)
    for i, rule in enumerate(_matrix_rules(shard_bytes)):
        root = str(tmp_path / f"root{i}")
        _save_step(root, 1)                        # committed baseline
        with faults.inject(rule) as plan:
            with pytest.raises(faults.SimulatedCrash):
                _save_step(root, 2)
        assert plan.log, f"rule {rule.point}/{rule.path_part} never armed"
        step, got = _read_step(root)
        # the commit point is the publish rename; the latest pointer and
        # GC run after it, so those phases legitimately expose step 2
        if rule.point in ("gc",) or rule.path_part == commit.LATEST:
            assert step == 2 and got == _expect(2), rule.point
        else:
            assert step == 1, (rule.point, rule.path_part, step)
            assert got == _expect(1), "recovered step 1 is not bit-exact"
        # and the NEXT save attempt over the crash litter must succeed
        _save_step(root, 3)
        step, got = _read_step(root)
        assert step == 3 and got == _expect(3)


def test_smoke_crash_at_publish_and_shard_write(tmp_path):
    """CI chaos smoke: one pre-commit kill (mid-shard write) and one
    at the commit edge (publish rename) — old step recovered intact;
    then a post-commit kill (gc) — new step visible."""
    root = str(tmp_path / "root")
    _save_step(root, 1)
    for rule in (faults.crash("write", path_part="ckpt.shard1",
                              after_bytes=20),
                 faults.crash("publish")):
        with faults.inject(rule):
            with pytest.raises(faults.SimulatedCrash):
                _save_step(root, 2)
        step, got = _read_step(root)
        assert step == 1 and got == _expect(1), rule.point
    with faults.inject(faults.crash("gc")):
        with pytest.raises(faults.SimulatedCrash):
            _save_step(root, 2)
    step, got = _read_step(root)
    assert step == 2 and got == _expect(2)


def test_smoke_corrupt_newest_falls_back_to_previous(tmp_path):
    """CI chaos smoke: a bit-flipped shard in the newest committed step
    fails manifest CRC validation and restore lands on the previous
    step."""
    root = str(tmp_path / "root")
    _save_step(root, 1)
    _save_step(root, 2)
    victim = os.path.join(commit.step_dir(root, 2), "ckpt.shard0")
    raw = bytearray(open(victim, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(victim, "wb") as f:
        f.write(bytes(raw))
    skipped = []
    got = commit.find_restorable(root, on_skip=lambda s, r:
                                 skipped.append((s, r)))
    assert got is not None and got[0] == 1
    assert skipped and skipped[0][0] == 2 and "CRC" in skipped[0][1]
    step, data = _read_step(root)
    assert step == 1 and data == _expect(1)


def test_missing_shard_and_manifest_schemas_rejected(tmp_path):
    root = str(tmp_path / "root")
    _save_step(root, 1)
    _save_step(root, 2)
    os.remove(os.path.join(commit.step_dir(root, 2), "ckpt.shard1"))
    got = commit.find_restorable(root)
    assert got is not None and got[0] == 1
    # garbage manifest in the newest: same fallback
    _save_step(root, 3)
    with open(os.path.join(commit.step_dir(root, 3), commit.MANIFEST),
              "w") as f:
        f.write("{not json")
    got = commit.find_restorable(root)
    assert got is not None and got[0] == 1


def test_torn_latest_pointer_never_blocks_restore(tmp_path):
    root = str(tmp_path / "root")
    _save_step(root, 1)
    with open(os.path.join(root, commit.LATEST), "w") as f:
        f.write("step-garbage")
    assert commit.read_latest(root) is None
    step, got = _read_step(root)
    assert step == 1 and got == _expect(1)


def test_gc_keep_last_and_stale_stage_sweep(tmp_path):
    root = str(tmp_path / "root")
    for step in (1, 2, 3, 4, 5):
        _save_step(root, step, keep_last=2)
    assert commit.committed_steps(root) == [4, 5]
    # a crashed older attempt's staging dir is swept by the next commit
    with faults.inject(faults.crash("write", path_part=commit.MANIFEST)):
        with pytest.raises(faults.SimulatedCrash):
            _save_step(root, 6)
    assert os.path.isdir(commit.stage_dir(root, 6))
    _save_step(root, 7, keep_last=2)
    assert not os.path.isdir(commit.stage_dir(root, 6))
    assert commit.committed_steps(root) == [5, 7]


def test_empty_stage_refuses_to_commit(tmp_path):
    root = str(tmp_path / "root")
    commit.prepare_stage(root, 1)
    with pytest.raises(ValueError, match="nothing staged"):
        commit.finalize(root, 1)


# -- trainer-level (single-process, real ShardedTrainer) ---------------------

def _make_trainer():
    from mxnet_tpu import gluon, parallel
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    return parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1})


def _snapshot(tr):
    snap = {}
    for p in tr._trainable:
        snap["arg:" + tr._struct_name(p)] = np.asarray(p._data[0]._data)
    for p, st in zip(tr._trainable, tr._states):
        for j, s in enumerate(st):
            snap[f"state:{tr._struct_name(p)}:{j}"] = np.asarray(s)
    return snap


def test_sharded_trainer_restore_latest_with_corrupt_newest(tmp_path):
    """End-to-end: checkpoint twice via the commit protocol, corrupt
    the newest step, crash a third attempt mid-manifest; a FRESH
    trainer's restore() lands bit-exact on the newest intact step with
    a journaled ckpt_fallback."""
    jf = str(tmp_path / "j.jsonl")
    journal.reset_journal(jf)
    try:
        root = str(tmp_path / "ck")
        rng = np.random.RandomState(0)
        x = rng.randn(8, 6).astype(np.float32)
        y = rng.randint(0, 4, (8,))
        tr = _make_trainer()
        for _ in range(2):
            tr.step(x, y)
        s1 = tr.checkpoint(root, keep_last=3)
        want = _snapshot(tr)
        tr.step(x, y)
        s2 = tr.checkpoint(root, keep_last=3)
        assert commit.committed_steps(root) == [s1, s2]
        # corrupt newest
        sd = commit.step_dir(root, s2)
        victim = os.path.join(
            sd, [n for n in os.listdir(sd) if n.endswith(".params")][0])
        raw = bytearray(open(victim, "rb").read())
        raw[60] ^= 0xFF
        with open(victim, "wb") as f:
            f.write(bytes(raw))
        # crash a third checkpoint at the manifest: changes nothing
        with faults.inject(faults.crash("write",
                                        path_part=commit.MANIFEST)):
            with pytest.raises(faults.SimulatedCrash):
                tr.checkpoint(root, step=99)
        tr2 = _make_trainer()
        tr2.prepare(x)
        got_step = tr2.restore(root)
        assert got_step == s1
        got = _snapshot(tr2)
        assert set(got) == set(want)
        for k in want:
            assert np.array_equal(want[k], got[k]), k
        recs = [json.loads(line) for line in open(jf)]
        assert any(r["kind"] == "ckpt_fallback" and r["step"] == s2
                   for r in recs)
        assert any(r["kind"] == "ckpt_restored" and r["step"] == s1
                   for r in recs)
    finally:
        journal.reset_journal()


def test_restore_errors_are_structured(tmp_path):
    tr = _make_trainer()
    x = np.zeros((8, 6), np.float32)
    tr.prepare(x)
    with pytest.raises(MXNetError, match="no valid committed checkpoint"):
        tr.restore(str(tmp_path / "nowhere"))
    with pytest.raises(MXNetError, match="failed validation"):
        tr.restore(str(tmp_path / "nowhere"), step=4)
    with pytest.raises(MXNetError, match="step=N or latest"):
        tr.restore(str(tmp_path / "nowhere"), latest=False)

"""ShardedTrainer checkpoint/resume: the flagship path must survive a
restart bit-exactly (VERDICT r4 Missing #2; ref: python/mxnet/gluon/
trainer.py save_states/load_states + python/mxnet/model.py save_checkpoint,
lifted to GSPMD-sharded state per SURVEY §5.4).

Protocol: train k steps, save, train m more ("uninterrupted"); then build a
FRESH net+trainer, load, train the same m steps ("resumed") — every master
weight, aux buffer and optimizer-state leaf must match bitwise, including
the dropout RNG stream (the global key is part of the checkpoint)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import PartitionSpec as P


def _make_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.Dropout(0.3),
            gluon.nn.Dense(16))
    net.initialize()
    return net


def _batches(n, batch=8, dim=12, classes=16, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(batch, dim).astype(np.float32),
             rng.randint(0, classes, (batch,)))
            for _ in range(n)]


def _make_trainer(net, mesh, optimizer="sgd", **kw):
    params = {"sgd": {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
              "adam": {"learning_rate": 1e-3}}[optimizer]
    # structural-path rule: matches the head Dense in EVERY net instance
    # (a flat-name rule like ".*dense1_weight" stops matching in a rebuilt
    # net because the auto-name counter moved — the resume trap)
    return parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer,
        optimizer_params=params, mesh=mesh,
        param_rules=[(r"3\.weight", P("model", None))], **kw)


def _snapshot(tr):
    snap = {}
    for p in tr._trainable:
        snap["arg:" + tr._struct_name(p)] = np.asarray(p._data[0]._data)
    for p in tr._aux:
        snap["aux:" + tr._struct_name(p)] = np.asarray(p._data[0]._data)
    for p, st in zip(tr._trainable, tr._states):
        for j, s in enumerate(st):
            snap[f"state:{tr._struct_name(p)}:{j}"] = np.asarray(s)
    return snap


def _run_resume(tmp_path, optimizer, per_shard, **trainer_kw):
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    batches = _batches(7)
    prefix = str(tmp_path / "ck")

    mx.random.seed(7)
    net_a = _make_net()
    tr_a = _make_trainer(net_a, mesh, optimizer, **trainer_kw)
    for x, y in batches[:3]:
        tr_a.step(x, y)
    tr_a.save_checkpoint(prefix, per_shard=per_shard)
    for x, y in batches[3:]:
        tr_a.step(x, y)
    want = _snapshot(tr_a)

    mx.random.seed(999)  # resumed run must NOT depend on the ambient seed
    net_b = _make_net()
    tr_b = _make_trainer(net_b, mesh, optimizer, **trainer_kw)
    tr_b.prepare(batches[0][0])
    tr_b.load_checkpoint(prefix)
    assert tr_b._num_update == 3
    # tensor-parallel rule must have applied in BOTH instances — a
    # replicated fallback would still converge but lose tp (and ULP-diverge)
    assert any(tuple(s) == ("model", None) for s in tr_a._tr_specs)
    assert [tuple(s) for s in tr_a._tr_specs] == \
        [tuple(s) for s in tr_b._tr_specs]
    for x, y in batches[3:]:
        tr_b.step(x, y)
    got = _snapshot(tr_b)

    assert set(want) == set(got)
    for k in want:
        assert want[k].dtype == got[k].dtype, k
        assert np.array_equal(want[k], got[k]), \
            f"{k}: resumed run diverged from uninterrupted run"


def test_resume_bitwise_sgd_momentum(tmp_path):
    _run_resume(tmp_path, "sgd", per_shard=False)


def test_resume_bitwise_adam_bf16_masters(tmp_path):
    # bf16 master weights + bf16 compute: the bench.py flagship config —
    # storage dtype must round-trip exactly (no fp32 re-cast on load)
    _run_resume(tmp_path, "adam", per_shard=False,
                compute_dtype="bfloat16", master_dtype="bfloat16")


def test_resume_bitwise_per_shard_layout(tmp_path):
    # the multi-host file layout (one .shard<rank> file per process) must
    # round-trip on a single process too — same bytes, different packing
    _run_resume(tmp_path, "sgd", per_shard=True)


def test_states_only_roundtrip(tmp_path):
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    batches = _batches(4, seed=3)
    fname = str(tmp_path / "t.states")
    mx.random.seed(11)
    net = _make_net()
    tr = _make_trainer(net, mesh, "adam")
    for x, y in batches[:2]:
        tr.step(x, y)
    before = [np.asarray(s) for st in tr._states for s in st]
    tr.save_states(fname)
    for x, y in batches[2:]:
        tr.step(x, y)
    tr.load_states(fname)
    after = [np.asarray(s) for st in tr._states for s in st]
    assert tr._num_update == 2
    for b, a in zip(before, after):
        assert np.array_equal(b, a)


def test_checkpoint_error_paths(tmp_path):
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    net = _make_net()
    tr = _make_trainer(net, mesh, "sgd")
    with pytest.raises(MXNetError, match="prepare"):
        tr.save_states(str(tmp_path / "x.states"))
    batches = _batches(1)
    tr.prepare(batches[0][0])
    tr.save_checkpoint(str(tmp_path / "ck"))

    # optimizer-class mismatch must be caught, not silently mis-shaped
    net2 = _make_net()
    tr2 = _make_trainer(net2, mesh, "adam")
    tr2.prepare(batches[0][0])
    with pytest.raises(MXNetError, match="optimizer"):
        tr2.load_states(str(tmp_path / "ck.states"))

    # a non-checkpoint .params file is rejected with a clear message
    mx.nd.save(str(tmp_path / "plain.params"), {"w": mx.nd.ones((2,))})
    with pytest.raises(MXNetError, match="__meta__"):
        tr.load_states(str(tmp_path / "plain.params"))

    # master-dtype mismatch: bf16 checkpoint into an fp32 trainer must
    # error, not silently rebind bf16 arrays (a trajectory change)
    net3 = _make_net()
    tr3 = _make_trainer(net3, mesh, "sgd", compute_dtype="bfloat16",
                        master_dtype="bfloat16")
    tr3.prepare(batches[0][0])
    with pytest.raises(MXNetError, match="master_dtype"):
        tr3.load_states(str(tmp_path / "ck.states"))

"""Binary-network (BMXNet fork delta) tests: det_sign STE, QDense/QConv2D
layers, and that a binary MLP actually trains (the BMXNet paper's core
claim, shrunk)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def test_det_sign_values_and_ste():
    x = mx.nd.array([-2.0, -0.5, 0.0, 0.5, 2.0])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.det_sign(x)
        loss = (y * mx.nd.array([1, 1, 1, 1, 1])).sum()
    np.testing.assert_array_equal(y.asnumpy(), [-1, -1, 1, 1, 1])
    loss.backward()
    # straight-through inside |x|<=1, cancelled outside
    np.testing.assert_array_equal(x.grad.asnumpy(), [0, 1, 1, 1, 0])


def test_approx_sign_grad_shape():
    x = mx.nd.array([-0.5, 0.25])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.approx_sign(x)
        y.sum().backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [1.0, 1.5], rtol=1e-6)


def test_qactivation_bits():
    x = mx.nd.array([-0.7, 0.3, 0.9])
    one = mx.nd.QActivation(x, act_bit=1)
    np.testing.assert_array_equal(one.asnumpy(), [-1, 1, 1])
    two = mx.nd.QActivation(x, act_bit=2)
    np.testing.assert_allclose(two.asnumpy(), [0.0, 1 / 3, 1.0], atol=1e-6)


def test_qdense_binary_output():
    layer = gluon.nn.QDense(4, in_units=8, binarize_input=True,
                            scaling=False)
    layer.initialize()
    x = mx.nd.random.normal(shape=(2, 8))
    out = layer(x)
    # output of ±1 @ ±1 matmul over 8 inputs: even integers in [-8, 8]
    vals = out.asnumpy()
    assert np.all(np.abs(vals) <= 8.0)
    assert np.allclose(vals, np.round(vals))


def test_qconv2d_shapes():
    layer = gluon.nn.QConv2D(6, 3, padding=1)
    layer.initialize()
    x = mx.nd.random.normal(shape=(2, 3, 8, 8))
    out = layer(x)
    assert out.shape == (2, 6, 8, 8)


def test_binary_mlp_trains():
    rng = np.random.RandomState(0)
    n, d = 256, 16
    w_true = rng.randn(d, 4)
    x = rng.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1).astype(np.float32)

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(64, activation="tanh"))
        net.add(gluon.nn.QDense(64, binarize_input=True))
        net.add(gluon.nn.BatchNorm())
        net.add(gluon.nn.Activation("tanh"))
        net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    for epoch in range(20):
        with autograd.record():
            out = net(mx.nd.array(x))
            loss = loss_fn(out, mx.nd.array(y))
        loss.backward()
        trainer.step(n)
    metric.update([mx.nd.array(y)], [net(mx.nd.array(x))])
    assert metric.get()[1] > 0.6, metric.get()


def test_xnor_packed_fc_matches_sign_matmul():
    rng = np.random.RandomState(0)
    for k in (64, 70, 17):
        x = rng.randn(5, k).astype(np.float32)
        w = rng.randn(7, k).astype(np.float32)
        xp = mx.nd.contrib.binary_pack(mx.nd.array(x))
        wp = mx.nd.contrib.binary_pack(mx.nd.array(w))
        assert xp.asnumpy().dtype == np.uint32
        assert xp.shape[-1] == -(-k // 32)      # 32x compression
        y = mx.nd.contrib.xnor_fully_connected(
            xp, wp, in_dim=k).asnumpy()
        sx = np.where(x >= 0, 1.0, -1.0)
        sw = np.where(w >= 0, 1.0, -1.0)
        np.testing.assert_allclose(y, sx @ sw.T, atol=1e-5)


def test_xnor_packed_conv_matches_qconv():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 9, 9).astype(np.float32)
    w = rng.randn(6, 4, 3, 3).astype(np.float32)
    wp = mx.nd.contrib.binary_pack(mx.nd.array(w.reshape(6, -1)))
    for pad in ((0, 0), (1, 1)):
        got = mx.nd.contrib.xnor_convolution(
            mx.nd.array(x), wp, kernel=(3, 3), num_filter=6,
            pad=pad).asnumpy()
        # reference semantics: binary conv pads with +1 (BMXNet), so
        # compare against QConvolution on a +1-padded input
        xp1 = np.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]),
                         (pad[1], pad[1])), constant_values=1.0)
        want = mx.nd.QConvolution(
            mx.nd.array(xp1), mx.nd.array(w), kernel=(3, 3),
            num_filter=6, scaling=False, no_bias=True).asnumpy()
        np.testing.assert_allclose(got, want, atol=1e-4)


def test_pack_binary_weights_layer_inference():
    from mxnet_tpu.gluon.nn.binary_layers import pack_binary_weights
    net = mx.gluon.nn.QDense(8, in_units=64)
    net.initialize()
    x = np.random.RandomState(2).randn(4, 64).astype(np.float32)
    want = net(mx.nd.array(x)).asnumpy()
    wp, alpha, bias = pack_binary_weights(net)
    xp = mx.nd.contrib.binary_pack(mx.nd.array(x))
    args = [xp, wp] + ([alpha] if alpha is not None else []) \
        + ([bias] if bias is not None else [])
    got = mx.nd.contrib.xnor_fully_connected(
        *args, in_dim=64).asnumpy()
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_pack_binary_weights_with_bias():
    from mxnet_tpu.gluon.nn.binary_layers import pack_binary_weights
    net = mx.gluon.nn.QDense(8, in_units=64, use_bias=True, scaling=False)
    net.initialize()
    x = np.random.RandomState(3).randn(4, 64).astype(np.float32)
    want = net(mx.nd.array(x)).asnumpy()
    wp, alpha, bias = pack_binary_weights(net)
    assert bias is not None and alpha is not None   # ones placeholder
    got = mx.nd.contrib.xnor_fully_connected(
        mx.nd.contrib.binary_pack(mx.nd.array(x)), wp, alpha, bias,
        in_dim=64).asnumpy()
    np.testing.assert_allclose(got, want, atol=1e-4)

"""Round-5 parity tail: mx.monitor.Monitor (VERDICT r4 Missing #3 — the
fit(monitor=) kwarg must DO something) and contrib PSROIPooling /
MultiProposal (Missing #4)."""
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io, nd
import mxnet_tpu.symbol as sym
from mxnet_tpu.base import MXNetError


# -- PSROIPooling (ref: src/operator/contrib/psroi_pooling.cc) -------------

def _psroi_numpy(data, rois, spatial_scale, output_dim, pooled_size,
                 group_size=0):
    """Direct transcription of the reference kernel's loop."""
    gs = group_size or pooled_size
    n_rois = rois.shape[0]
    _, c, h, w = data.shape
    out = np.zeros((n_rois, output_dim, pooled_size, pooled_size),
                   np.float32)

    def c_round(v):        # C round(): half away from zero (not banker's)
        return np.sign(v) * np.floor(np.abs(v) + 0.5)

    for r in range(n_rois):
        b = int(rois[r, 0])
        x1 = c_round(rois[r, 1]) * spatial_scale
        y1 = c_round(rois[r, 2]) * spatial_scale
        x2 = c_round(rois[r, 3] + 1.0) * spatial_scale
        y2 = c_round(rois[r, 4] + 1.0) * spatial_scale
        bh = max(y2 - y1, 0.1) / pooled_size
        bw = max(x2 - x1, 0.1) / pooled_size
        for d in range(output_dim):
            for i in range(pooled_size):
                for j in range(pooled_size):
                    hstart = int(np.clip(np.floor(y1 + i * bh), 0, h))
                    hend = int(np.clip(np.ceil(y1 + (i + 1) * bh), 0, h))
                    wstart = int(np.clip(np.floor(x1 + j * bw), 0, w))
                    wend = int(np.clip(np.ceil(x1 + (j + 1) * bw), 0, w))
                    gh = min(int(i * gs / pooled_size), gs - 1)
                    gw = min(int(j * gs / pooled_size), gs - 1)
                    cin = (d * gs + gh) * gs + gw
                    patch = data[b, cin, hstart:hend, wstart:wend]
                    out[r, d, i, j] = patch.mean() if patch.size else 0.0
    return out


def test_psroi_pooling_matches_reference_loop():
    rng = np.random.RandomState(0)
    od, gs = 3, 2
    data = rng.randn(2, od * gs * gs, 10, 12).astype(np.float32)
    rois = np.array([[0, 2, 2, 18, 20],
                     [1, 0, 0, 23, 19],
                     [0, 8, 4, 12, 9],
                     [1, 0.5, 1.5, 18.5, 17.5]], np.float32)  # .5 corners:
    # pins C-style half-away-from-zero rounding (banker's would shift bins)
    got = mx.nd.contrib.PSROIPooling(
        nd.array(data), nd.array(rois), spatial_scale=0.5,
        output_dim=od, pooled_size=2, group_size=gs).asnumpy()
    want = _psroi_numpy(data, rois, 0.5, od, 2, gs)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_psroi_pooling_grad_and_validation():
    from mxnet_tpu import autograd
    rng = np.random.RandomState(1)
    data = nd.array(rng.randn(1, 4 * 49, 14, 14).astype(np.float32))
    rois = nd.array(np.array([[0, 0, 0, 27, 27]], np.float32))
    data.attach_grad()
    with autograd.record():
        out = mx.nd.contrib.PSROIPooling(data, rois, spatial_scale=0.5,
                                         output_dim=4, pooled_size=7)
        loss = out.sum()
    loss.backward()
    assert out.shape == (1, 4, 7, 7)
    g = data.grad.asnumpy()
    assert np.abs(g).sum() > 0          # gradient reaches the features
    with pytest.raises(MXNetError, match="channels"):
        mx.nd.contrib.PSROIPooling(data, rois, spatial_scale=0.5,
                                   output_dim=5, pooled_size=7)


def test_multi_proposal_is_batched_proposal():
    rng = np.random.RandomState(2)
    n, a, h, w = 2, 12, 6, 8
    cls = nd.array(rng.rand(n, 2 * a, h, w).astype(np.float32))
    bbox = nd.array(rng.randn(n, 4 * a, h, w).astype(np.float32) * 0.1)
    info = nd.array(np.array([[96, 128, 1.0], [96, 128, 1.0]], np.float32))
    kw = dict(rpn_pre_nms_top_n=200, rpn_post_nms_top_n=30,
              feature_stride=16)
    multi = mx.nd.contrib.MultiProposal(cls, bbox, info, **kw).asnumpy()
    single = mx.nd.contrib.Proposal(cls, bbox, info, **kw).asnumpy()
    np.testing.assert_allclose(multi, single)
    assert multi.shape == (2 * 30, 5)


# -- mx.monitor.Monitor -----------------------------------------------------

def _mlp_module():
    x = sym.var("data")
    fc1 = sym.FullyConnected(x, num_hidden=8, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=4, name="fc2")
    out = sym.SoftmaxOutput(fc2, name="softmax")
    return mx.mod.Module(out, data_names=["data"],
                         label_names=["softmax_label"])


def test_monitor_collects_matched_intermediates():
    mod = _mlp_module()
    batch = io.DataBatch(data=[nd.array(np.random.rand(4, 6))],
                         label=[nd.array(np.array([0, 1, 2, 3]))])
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mon = mx.monitor.Monitor(interval=1, pattern=".*fc.*", sort=True)
    mod.install_monitor(mon)

    mon.tic()
    mod.forward(batch, is_train=True)
    stats = mon.toc()
    names = [name for _, name, _ in stats]
    assert "fc1_output" in names and "fc2_output" in names, names
    assert all("relu" not in n for n in names)       # pattern filtered
    for _, name, stat in stats:
        v = float(np.asarray(stat.asnumpy()))
        assert np.isfinite(v) and v >= 0

    # interval gating: step 2 (not on interval=2 boundary) collects nothing
    mon2 = mx.monitor.Monitor(interval=2, pattern=".*")
    mod.install_monitor(mon2)
    mon2.tic()                                       # step 0: active
    mod.forward(batch, is_train=True)
    assert len(mon2.toc()) > 0
    mon2.tic()                                       # step 1: inactive
    mod.forward(batch, is_train=True)
    assert mon2.toc() == []


def test_monitor_through_fit_and_monitor_all(caplog):
    mod = _mlp_module()
    data = np.random.rand(8, 6).astype(np.float32)
    label = np.array([0, 1, 2, 3] * 2, np.float32)
    it = io.NDArrayIter(data, label, batch_size=4,
                        label_name="softmax_label")
    mon = mx.monitor.Monitor(interval=1, pattern=".*fc.*",
                             monitor_all=True)
    with caplog.at_level(logging.INFO, logger="mxnet_tpu.monitor"):
        mod.fit(it, num_epoch=1, monitor=mon,
                optimizer_params={"learning_rate": 0.01})
    msgs = [r.message for r in caplog.records if "Batch:" in r.message]
    assert any("fc1_output" in m for m in msgs), msgs[:5]
    # monitor_all adds parameters too
    assert any("fc1_weight" in m for m in msgs), msgs[:5]


def test_monitor_through_sequential_module():
    """fit(monitor=) must work on SequentialModule too (the reference
    forwards install_monitor to every sub-module)."""
    x = sym.var("data")
    net1 = sym.FullyConnected(x, num_hidden=8, name="fc1")
    mod1 = mx.mod.Module(net1, data_names=["data"], label_names=[])
    x2 = sym.var("fc1_output")
    net2 = sym.SoftmaxOutput(sym.FullyConnected(x2, num_hidden=4,
                                                name="fc2"),
                             name="softmax")
    mod2 = mx.mod.Module(net2, data_names=["fc1_output"],
                         label_names=["softmax_label"])
    seq = mx.mod.SequentialModule()
    seq.add(mod1).add(mod2, take_labels=True, auto_wiring=True)
    seq.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    seq.init_params()
    mon = mx.monitor.Monitor(interval=1, pattern=".*fc.*", sort=True)
    seq.install_monitor(mon)
    batch = io.DataBatch(data=[nd.array(np.random.rand(4, 6))],
                         label=[nd.array(np.array([0, 1, 2, 3]))])
    mon.tic()
    seq.forward(batch, is_train=True)
    names = [n for _, n, _ in mon.toc()]
    assert "fc1_output" in names and "fc2_output" in names, names

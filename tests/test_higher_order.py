"""Higher-order autograd (ref: tests/python/unittest/test_higher_order_grad.py
— the reference supports partial 2nd order; here create_graph replays
pullbacks under recording so grad-of-grad sees full primal dependence)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd


def test_second_order_cubic():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x * x).sum()
        g = autograd.grad(y, x, create_graph=True)   # 3x^2
        s = g.sum()
    s.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 6 * np.array([1., 2., 3.]),
                               rtol=1e-5)


def test_gradient_penalty_through_layers():
    """WGAN-GP-style: ||dL/dw||^2 differentiated back to w."""
    w = mx.nd.array(np.random.RandomState(0).randn(3, 4).astype(np.float32))
    w.attach_grad()
    x = mx.nd.array(np.random.RandomState(1).randn(2, 4).astype(np.float32))
    with autograd.record():
        out = mx.nd.FullyConnected(x, w, mx.nd.zeros((3,)), num_hidden=3)
        loss = (mx.nd.tanh(out) ** 2).sum()
        gw = autograd.grad(loss, w, create_graph=True)
        gnorm = (gw * gw).sum()
    gnorm.backward()
    g = w.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_second_order_matches_jax():
    import jax
    import jax.numpy as jnp
    xv = np.array([0.3, -0.7, 1.2], dtype=np.float32)

    def f(x):
        return jnp.sum(jnp.sin(x) * x ** 2)
    want = jax.grad(lambda x: jnp.sum(jax.grad(f)(x) ** 2))(jnp.asarray(xv))

    x = mx.nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = (mx.nd.sin(x) * x ** 2).sum()
        g = autograd.grad(y, x, create_graph=True)
        s = (g * g).sum()
    s.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_sin_fourth_derivative_chain():
    """Iterated create_graph: d3/dx3 sin(x) = -cos(x)."""
    xv = np.array([0.5, 1.0], dtype=np.float32)
    x = mx.nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = mx.nd.sin(x).sum()
        g1 = autograd.grad(y, x, create_graph=True)         # cos
        g2 = autograd.grad(g1.sum(), x, create_graph=True)  # -sin
        g3 = g2.sum()
    g3.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), -np.cos(xv), rtol=1e-5)

"""The ENOSPC fuzz matrix (ISSUE satellite of the chaos engine;
docs/chaos.md): every durable writer in the repo driven to disk-full at
each atomic-write phase (``write``, ``fsync``, ``replace``) via the new
``faults.disk_full`` rule, proving the three-part exhaustion contract:

1. **bit-exact old-or-new** — a reader after the failed write observes
   the complete previous bytes (or the complete new ones, never torn);
2. **no litter** — the staged ``.tmp.*`` file is unlinked immediately
   (an ENOSPC cleanup that LEAVES litter feeds the full disk);
3. **degrade record** — one deduped ``disk_full`` journal record lands
   (plus the writer's own structured degrade, for the writers that
   absorb the failure instead of raising).

Writers covered: the ``nd.save`` container, the checkpoint commit
protocol, the AOT store entry, the tuned-table commit, journal sink
rotation, and the flight-recorder dump.  The final test is the
observability hot-path regression: spans + periodic flight flushes on a
disk_full-injected trace dir must degrade to drop-and-count, never
raise into the serving/trainer loop.
"""
import json
import os

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.autotune import table as attable
from mxnet_tpu.chaos.scenarios import commit_scale
from mxnet_tpu.diagnostics import journal
from mxnet_tpu.observability import flight as obflight
from mxnet_tpu.observability import trace as obtrace
from mxnet_tpu.resilience import commit, retry
from mxnet_tpu.testing import faults

PHASES = ("write", "fsync", "replace")


@pytest.fixture
def jpath(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    journal.reset_journal(path)
    retry.reset_disk_full_notes()
    try:
        yield path
    finally:
        journal.reset_journal("stderr")
        retry.reset_disk_full_notes()


def _records(path, kind):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def _no_litter(root):
    litter = []
    for dirpath, _d, names in os.walk(root):
        litter += [os.path.join(dirpath, n) for n in names
                   if ".tmp." in n]
    assert not litter, litter


def _assert_degrade_recorded(jpath):
    assert _records(jpath, "disk_full"), \
        "exhaustion fired but no disk_full journal record landed"


# -- nd.save container -------------------------------------------------------

@pytest.mark.parametrize("phase", PHASES)
def test_nd_save_enospc(tmp_path, jpath, phase):
    path = str(tmp_path / "net.params")
    old = np.arange(6, dtype=np.float32)
    nd.save(path, {"w": nd.array(old)})
    before = open(path, "rb").read()
    with faults.inject(faults.disk_full(phase, times=1)):
        with pytest.raises(OSError) as ei:
            nd.save(path, {"w": nd.array(old * 2)})
    assert retry.is_disk_full(ei.value)
    assert open(path, "rb").read() == before        # bit-exact old
    np.testing.assert_array_equal(nd.load(path)["w"].asnumpy(), old)
    _no_litter(tmp_path)
    _assert_degrade_recorded(jpath)


# -- checkpoint commit protocol ----------------------------------------------

@pytest.mark.parametrize("phase", PHASES)
def test_commit_protocol_enospc(tmp_path, jpath, phase):
    root = str(tmp_path / "ckpt")
    commit_scale(root, 1, 1.0)
    with faults.inject(faults.disk_full(phase, times=1)):
        with pytest.raises(OSError) as ei:
            commit_scale(root, 2, 2.0)
    assert retry.is_disk_full(ei.value)
    # the recovery a restarting trainer runs: stale staging swept, the
    # previous committed step restorable and CRC-valid
    commit.gc_steps(root, keep_last=None)
    found = commit.find_restorable(root)
    assert found is not None and found[0] == 1
    commit.validate_step(root, 1)
    w = nd.load(os.path.join(commit.step_dir(root, 1), "net.params"))["w"]
    assert float(np.asarray(w.asnumpy()).reshape(-1)[0]) == 1.0
    _no_litter(root)
    _assert_degrade_recorded(jpath)


# -- tuned-table commit ------------------------------------------------------

def _table_doc(window_ms):
    return attable.build_table(
        {"serving": {"window_ms": float(window_ms), "max_queue": 64}},
        provenance={"trials": 1},
        envelope={"platform": "cpu", "device_kind": "test", "jax": "0"})


@pytest.mark.parametrize("phase", PHASES)
def test_tuned_table_enospc(tmp_path, jpath, phase):
    path = str(tmp_path / "tuned.json")
    attable.commit_table(_table_doc(2.0), path)
    before = open(path, "rb").read()
    with faults.inject(faults.disk_full(phase, times=1)):
        with pytest.raises(OSError) as ei:
            attable.commit_table(_table_doc(4.0), path)
    assert retry.is_disk_full(ei.value)
    assert open(path, "rb").read() == before
    doc = json.loads(before)
    assert doc["crc32"] == attable.table_crc(doc)   # still CRC-valid
    assert doc["knobs"]["serving"]["window_ms"] == 2.0
    _no_litter(tmp_path)
    _assert_degrade_recorded(jpath)


# -- AOT store entry (degrades, never raises into the compile path) ----------

@pytest.mark.parametrize("phase", PHASES)
def test_aot_store_entry_enospc(tmp_path, jpath, phase):
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.serving import AOTCache

    net = nn.Dense(4, in_units=4)
    net.initialize()
    root = str(tmp_path / "aot")
    cache = AOTCache(root)
    pred1 = cache.load_or_compile(net, (1, 4), np.float32)
    assert pred1 is not None and cache.counters["stores"] == 1
    [entry] = [n for n in os.listdir(root) if not n.startswith(".")]
    before = open(os.path.join(root, entry), "rb").read()
    with faults.inject(faults.disk_full(phase, times=1)):
        pred2 = cache.load_or_compile(net, (2, 4), np.float32)
    # the compile path survives: a working predictor despite the failed
    # store, the failure journaled, the existing entry untouched
    assert pred2 is not None
    assert cache.counters["store_failures"] == 1
    assert _records(jpath, "aot_store_failed")
    assert open(os.path.join(root, entry), "rb").read() == before
    _no_litter(root)
    _assert_degrade_recorded(jpath)


# -- journal sink rotation ---------------------------------------------------

def test_journal_rotation_onto_full_disk_drops_and_counts(tmp_path):
    """Rotating the journal sink onto a full disk must not raise into
    writers: appends degrade to drop-and-count (the ENOSPC analog of
    the dead-sink case in test_chaos.py), the pre-rotation sink's bytes
    stay intact, and the drops metric is incremented."""
    from mxnet_tpu.observability import metrics as obmetrics

    old_sink = str(tmp_path / "j1.jsonl")
    j = journal.reset_journal(old_sink)
    try:
        j.event("before_rotation")
        old_bytes = open(old_sink, "rb").read()

        j = journal.reset_journal(str(tmp_path / "j2.jsonl"))

        class _FullDisk:
            def write(self, _line):
                raise OSError(28, "No space left on device")

            def flush(self):
                pass

            def close(self):
                pass

        j._fh = _FullDisk()
        drops0 = obmetrics.default_registry().counter(
            "mxnet_tpu_journal_write_drops_total", "").labels().value
        j.event("a")                    # must NOT raise into the caller
        j.event("b")
        assert j.write_drops == 2
        assert obmetrics.default_registry().counter(
            "mxnet_tpu_journal_write_drops_total", "").labels().value \
            == drops0 + 2
        # the ring (flight half) kept the records; old sink untouched
        assert "b" in [r["kind"] for r in j.recent()]
        assert open(old_sink, "rb").read() == old_bytes
    finally:
        journal.reset_journal("stderr")


# -- flight-recorder dump (degrades, never raises) ---------------------------

@pytest.mark.parametrize("phase", PHASES)
def test_flight_dump_enospc(tmp_path, jpath, phase):
    out = str(tmp_path / "trace")
    rec = obflight.FlightRecorder(out, label="t", flush_s=0)
    assert rec.dump("baseline") is not None
    before = open(rec.path, "rb").read()
    with faults.inject(faults.disk_full(phase, path_part="flight-",
                                        times=1)):
        assert rec.dump("under_enospc") is None     # degrade, no raise
    assert rec.drops == 1
    # the previous complete dump IS the postmortem — still whole
    assert open(rec.path, "rb").read() == before
    assert obflight.read_flight(rec.path)["reason"] == "baseline"
    assert len(_records(jpath, "flight_dump_failed")) == 1
    _no_litter(out)
    _assert_degrade_recorded(jpath)


# -- the hot-path regression: traffic on a disk_full-injected trace dir ------

def test_observability_hot_path_survives_full_trace_dir(tmp_path, jpath):
    """The serving-worker shape: spans streaming to the journal and
    periodic flight flushes while the trace dir is persistently ENOSPC
    — every write degrades to drop-and-count, nothing raises into the
    request loop, and the degrade trail is deduped (ONE
    flight_dump_failed marker, one stderr note) instead of a record
    per request."""
    from mxnet_tpu.observability import metrics as obmetrics

    out = str(tmp_path / "trace")
    obtrace.reset_tracer()
    obtrace.configure(mode="journal")
    rec = obflight.FlightRecorder(out, label="w", flush_s=0)
    drops0 = obmetrics.default_registry().counter(
        "mxnet_tpu_flight_dump_drops_total", "").labels().value
    try:
        # times=None: the disk stays full for the whole loop
        with faults.inject(faults.disk_full("write", path_part="flight-",
                                            times=None)):
            for i in range(8):          # the request loop
                with obtrace.span("serving_predict", request=i):
                    pass
                rec.dump("periodic")
        assert rec.dumps == 0 and rec.drops == 8
        assert obmetrics.default_registry().counter(
            "mxnet_tpu_flight_dump_drops_total", "").labels().value == drops0 + 8
        assert len(_records(jpath, "flight_dump_failed")) == 1
        # the disk heals: the very next flush lands a complete dump
        assert rec.dump("healed") is not None
        assert obflight.read_flight(rec.path)["reason"] == "healed"
        # span records still reached the (healthy) journal throughout
        spans = [r for r in _records(jpath, "span")
                 if r.get("name") == "serving_predict"]
        assert len(spans) == 8
    finally:
        obtrace.reset_tracer()

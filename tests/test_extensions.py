"""Round-2 extension surface: FuseAttention graph pass, dynamic op
libraries (lib_api.h analog), launcher auto-restart, LibSVMIter
(ref: src/operator/subgraph/, include/mxnet/lib_api.h,
tools/launch.py tracker, src/io/iter_libsvm.cc)."""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio
from mxnet_tpu import nd
from mxnet_tpu.symbol import passes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestFuseAttention:
    def _binds(self, B=3, S=10, D=8):
        r = np.random.RandomState(0)
        return {k: nd.array(r.randn(B, S, D).astype(np.float32))
                for k in ("q", "k", "v")}

    def test_batch_dot_pattern_with_scale(self):
        q, k, v = mx.sym.var("q"), mx.sym.var("k"), mx.sym.var("v")
        out = mx.sym.batch_dot(
            mx.sym.softmax(mx.sym.batch_dot(q, k, transpose_b=True)
                           * (1.0 / np.sqrt(8)), axis=-1), v)
        fused = passes.apply_pass(out, "FuseAttention")
        ops = [n.op for n in fused._topo() if n.op]
        assert "_contrib_flash_attention" in ops
        assert "batch_dot" not in ops
        binds = self._binds()
        want = out.bind(mx.cpu(), dict(binds)).forward()[0].asnumpy()
        got = fused.bind(mx.cpu(), dict(binds)).forward()[0].asnumpy()
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_batch_dot_pattern_no_scale(self):
        q, k, v = mx.sym.var("q"), mx.sym.var("k"), mx.sym.var("v")
        out = mx.sym.batch_dot(
            mx.sym.softmax(mx.sym.batch_dot(q, k, transpose_b=True),
                           axis=-1), v)
        fused = passes.apply_pass(out, "FuseAttention")
        binds = self._binds()
        want = out.bind(mx.cpu(), dict(binds)).forward()[0].asnumpy()
        got = fused.bind(mx.cpu(), dict(binds)).forward()[0].asnumpy()
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_interleaved_pattern(self):
        T, N, E, H = 6, 2, 16, 4
        qkv = mx.sym.var("qkv")
        sc = mx.sym.contrib.interleaved_matmul_selfatt_qk(qkv, heads=H)
        out = mx.sym.contrib.interleaved_matmul_selfatt_valatt(
            qkv, mx.sym.softmax(sc, axis=-1), heads=H)
        fused = passes.apply_pass(out, "FuseAttention")
        ops = [n.op for n in fused._topo() if n.op]
        assert "_contrib_flash_attention" in ops
        x = np.random.RandomState(1).randn(T, N, 3 * E) \
            .astype(np.float32)
        want = out.bind(mx.cpu(),
                        {"qkv": nd.array(x)}).forward()[0].asnumpy()
        got = fused.bind(mx.cpu(),
                         {"qkv": nd.array(x)}).forward()[0].asnumpy()
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_non_matching_graph_unchanged(self):
        a, b = mx.sym.var("a"), mx.sym.var("b")
        out = mx.sym.batch_dot(a, b)        # no softmax: no rewrite
        fused = passes.apply_pass(out, "FuseAttention")
        assert [n.op for n in fused._topo() if n.op] == ["batch_dot"]


class TestLibraryLoad:
    def test_python_plugin(self, tmp_path):
        plug = tmp_path / "plug.py"
        plug.write_text(
            "import jax.numpy as jnp\n"
            "from mxnet_tpu.ops import register\n"
            "@register('plugin_cube_t', doc='x^3')\n"
            "def _cube(x):\n"
            "    return x * x * x\n")
        names = mx.library.load(str(plug), verbose=False)
        assert names == ["plugin_cube_t"]
        x = np.random.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(
            nd.plugin_cube_t(nd.array(x)).asnumpy(), x ** 3, atol=1e-5)
        # also visible in the symbol namespace
        s = mx.sym.plugin_cube_t(mx.sym.var("a"))
        got = s.bind(mx.cpu(), {"a": nd.array(x)}).forward()[0].asnumpy()
        np.testing.assert_allclose(got, x ** 3, atol=1e-5)

    def test_native_plugin(self, tmp_path):
        if shutil.which("g++") is None:
            pytest.skip("no g++")
        so = tmp_path / "libplug.so"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", str(so),
             os.path.join(REPO, "native", "example_plugin.cc")],
            check=True, capture_output=True, timeout=600)
        names = mx.library.load(str(so), verbose=False)
        assert names == ["plugin_gelu_tanh", "plugin_mish"]
        x = np.random.randn(4, 5).astype(np.float32)
        got = nd.plugin_mish(nd.array(x)).asnumpy()
        np.testing.assert_allclose(
            got, x * np.tanh(np.log1p(np.exp(x))), atol=1e-5)

    def test_bad_library(self, tmp_path):
        bad = tmp_path / "x.txt"
        bad.write_text("nope")
        with pytest.raises(mx.MXNetError, match="py or .so"):
            mx.library.load(str(bad))


def test_launcher_auto_restart(tmp_path):
    script = tmp_path / "w.py"
    script.write_text(
        "import os, sys\n"
        "marker = sys.argv[1] + '.' + os.environ['MXTPU_PROC_ID']\n"
        "if os.environ.get('MXTPU_RESTART') == '0' and \\\n"
        "        os.environ['MXTPU_PROC_ID'] == '0':\n"
        "    sys.exit(3)\n"
        "open(marker, 'w').write(os.environ['MXTPU_RESTART'])\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--max-restarts", "2",
         "--heartbeat-interval", "0.2",
         sys.executable, str(script), str(tmp_path / "m")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-500:]
    assert "restarting job" in r.stderr
    assert (tmp_path / "m.0").read_text() == "1"


def test_libsvm_iter(tmp_path):
    f = tmp_path / "t.libsvm"
    f.write_text("1 0:1.5 3:2.0\n0 1:0.5\n1 2:3.0 3:1.0\n0 0:2.0\n")
    it = mio.LibSVMIter(str(f), data_shape=4, batch_size=2)
    b = it.next()
    assert b.data[0].stype == "csr"
    np.testing.assert_allclose(b.data[0].asnumpy(),
                               [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    np.testing.assert_allclose(b.label[0].asnumpy(), [1, 0])
    it.next()
    with pytest.raises(StopIteration):
        it.next()
    it.reset()
    np.testing.assert_allclose(it.next().label[0].asnumpy(), [1, 0])

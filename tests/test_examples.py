"""Examples must keep running end-to-end (the reference's example/ scripts
are exercised by CI the same way — SURVEY §2.7 runtime_functions.sh), and
the training ones must hit NUMERIC floors — round-2 verdict #6: parsing
the printed accuracy, not just the string, so a wrong-but-running model
fails."""
import os
import re
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parse_metric(out, pattern):
    m = re.search(pattern, out)
    assert m, f"metric {pattern!r} not printed:\n{out}"
    return float(m.group(1))


def _run(script, *args, timeout=280):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout + r.stderr


def test_train_mnist_gluon(tmp_path):
    # explicit empty data dir pins the synthetic fallback (hermetic: never
    # trains on a host's real MNIST download); the printed accuracy is
    # parsed and gated — 3 epochs on the separable synthetic set must
    # clear 0.9 (a broken loss/optimizer lands near 0.1)
    out = _run("train_mnist.py", "--epochs", "3", "--batch-size", "256",
               "--data-dir", str(tmp_path))
    acc = _parse_metric(out, r"final accuracy:\s*([0-9.]+)")
    assert acc >= 0.9, f"MNIST example accuracy {acc} below 0.9 floor"


def test_train_nmt_token_accuracy_floor():
    # reversal-task NMT: vocab 16 / seq 6 reaches ~1.0 greedy-decode
    # token accuracy in 300 steps (calibrated; chance is ~0.08) — the
    # 0.6 floor fails any wrong loss/teacher-forcing/decode regression
    out = _run("train_nmt.py", "--steps", "300", "--units", "32",
               "--batch-size", "32", "--num-layers", "1",
               "--vocab", "16", "--seq-len", "6")
    acc = _parse_metric(out, r"greedy-decode token accuracy:\s*([0-9.]+)")
    assert acc >= 0.6, f"NMT token accuracy {acc} below 0.6 floor"


def test_train_ssd_smoke():
    out = _run("train_ssd.py", "--steps", "2", "--batch-size", "2",
               "--data-shape", "64")
    assert "detections" in out


def test_train_faster_rcnn_smoke():
    out = _run("train_faster_rcnn.py", "--steps", "2",
               "--image-size", "96", timeout=280)
    assert "done" in out

"""Examples must keep running end-to-end (the reference's example/ scripts
are exercised by CI the same way — SURVEY §2.7 runtime_functions.sh), and
the training ones must hit NUMERIC floors — round-2 verdict #6: parsing
the printed accuracy, not just the string, so a wrong-but-running model
fails."""
import os
import re
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parse_metric(out, pattern):
    m = re.search(pattern, out)
    assert m, f"metric {pattern!r} not printed:\n{out}"
    return float(m.group(1))


def _run(script, *args, timeout=280):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout + r.stderr


def test_train_mnist_gluon(tmp_path):
    # explicit empty data dir pins the synthetic fallback (hermetic: never
    # trains on a host's real MNIST download); the printed accuracy is
    # parsed and gated — 3 epochs on the separable synthetic set must
    # clear 0.9 (a broken loss/optimizer lands near 0.1)
    out = _run("train_mnist.py", "--epochs", "3", "--batch-size", "256",
               "--data-dir", str(tmp_path))
    acc = _parse_metric(out, r"final accuracy:\s*([0-9.]+)")
    assert acc >= 0.9, f"MNIST example accuracy {acc} below 0.9 floor"


def test_train_nmt_token_accuracy_floor():
    # reversal-task NMT: vocab 16 / seq 6 reaches ~1.0 greedy-decode
    # token accuracy in 300 steps (calibrated; chance is ~0.08) — the
    # 0.6 floor fails any wrong loss/teacher-forcing/decode regression
    out = _run("train_nmt.py", "--steps", "300", "--units", "32",
               "--batch-size", "32", "--num-layers", "1",
               "--vocab", "16", "--seq-len", "6")
    acc = _parse_metric(out, r"greedy-decode token accuracy:\s*([0-9.]+)")
    assert acc >= 0.6, f"NMT token accuracy {acc} below 0.6 floor"


def test_train_ssd_map_floor():
    # round-4 verdict #10: every driver-config example carries a numeric
    # gate. 60 steps on the painted-box synthetic set reach mAP 1.0
    # (calibrated); 0.6 fails any matcher/loss/decoder regression while
    # staying far from flakiness
    out = _run("train_ssd.py", "--steps", "60", "--batch-size", "8",
               "--data-shape", "64", timeout=420)
    val = _parse_metric(out, r"mAP:\s*([0-9.]+)")
    assert val >= 0.6, f"SSD example mAP {val} below 0.6 floor"
    final_loss = _parse_metric(out, r"final loss=([0-9.]+)")
    assert final_loss < 2.5, f"SSD final loss {final_loss} above 2.5"


def test_train_faster_rcnn_loss_decreases():
    # joint RPN+RCNN loss on the painted-box synthetic batch: ~16.9 →
    # ~6-9 in 30 steps (calibrated; proposals are nonstationary so gate
    # on best-of-tail vs start)
    out = _run("train_faster_rcnn.py", "--steps", "30",
               "--image-size", "96", timeout=420)
    losses = [float(v) for v in re.findall(r"loss\s+([0-9.]+)", out)]
    assert len(losses) >= 3, out
    assert min(losses[1:]) < 0.7 * losses[0], losses


def test_pretrain_bert_mlm_loss_floor():
    # tiny BERT memorizes the fixed synthetic batch: mlm_loss ~0.014 in
    # 150 steps (calibrated; ln(512) ≈ 6.2 at init)
    out = _run("pretrain_bert.py", "--vocab-size", "512",
               "--batch-size", "16", "--seq-length", "32",
               "--num-layers", "2", "--units", "64", "--num-heads", "4",
               "--hidden-size", "128", "--steps", "150", "--lr", "3e-3",
               "--no-bf16", timeout=280)
    final = _parse_metric(out, r"final mlm_loss=([0-9.]+)")
    assert final < 0.5, f"BERT example mlm loss {final} above 0.5 floor"


def test_train_word_lm_perplexity_floor():
    # deterministic bigram-chain grammar (vocab 50, chance ppl 50):
    # the 2-layer LSTM reaches ppl ~1.01 in 8 epochs (calibrated) — a 5.0
    # gate fails any RNN/embedding/BPTT regression
    out = _run("train_word_lm.py", "--epochs", "8", "--tokens", "20000",
               "--lr", "5e-3", timeout=280)
    ppl = _parse_metric(out, r"final perplexity=([0-9.]+)")
    assert ppl < 5.0, f"word-LM perplexity {ppl} above the 5.0 gate"


def test_train_imagenet_memorizes():
    # resnet18 on one fixed synthetic batch: loss → ~0 in 60 steps
    # (calibrated) — gates the ShardedTrainer + vision-zoo + SGD path
    out = _run("train_imagenet.py", "--network", "resnet18_v1",
               "--batch-size", "16", "--num-classes", "10",
               "--image-shape", "3,32,32", "--steps-per-epoch", "60",
               "--epochs", "1", "--lr", "0.05", "--no-bf16", timeout=420)
    final = _parse_metric(out, r"final loss=([0-9.]+)")
    assert final < 0.5, f"imagenet example loss {final} above 0.5 floor"


def test_train_dcgan_matches_data_statistics():
    """DCGAN (adversarial family, ref: example/gan/dcgan.py): after a
    short run the generator's pixel-mean map must approach the data's
    radial structure (GAN losses oscillate, so the gate is on sample
    statistics), and both players must still be in the game (neither
    loss collapsed to 0)."""
    out = _run("train_dcgan.py", "--steps", "150")
    # anchor to the FINAL summary line — the per-step logs also contain
    # d_loss/g_loss and re.search would read step 0 otherwise
    l1 = _parse_metric(out, r"pixel-mean-map L1\s*([0-9.]+)")
    d_loss = _parse_metric(
        out, r"pixel-mean-map L1\s*[0-9.]+\s+d_loss\s*([0-9.]+)")
    g_loss = _parse_metric(
        out, r"pixel-mean-map L1\s*[0-9.]+\s+d_loss\s*[0-9.]+\s*"
             r"g_loss\s*([0-9.]+)")
    assert l1 < 0.12, f"generated stats L1 {l1} too far from data"
    assert d_loss > 0.05, "discriminator collapsed (training broken)"
    assert g_loss > 0.05, "generator loss collapsed (D gave up)"


def test_train_vae_elbo_floor():
    """VAE (generative family, ref: example/autoencoder): reconstruction
    must get tight on the blob distribution, the KL must stay in a sane
    band (collapse -> ~0; blowup -> huge), and prior samples must carry
    the data's spatial statistics."""
    out = _run("train_vae.py", "--steps", "400", timeout=420)
    rec = _parse_metric(out, r"final rec\s*([0-9.]+)")
    kl = _parse_metric(out, r"final rec\s*[0-9.]+\s+kl\s*([0-9.]+)")
    l1 = _parse_metric(out, r"prior-sample L1\s*([0-9.]+)")
    assert rec < 0.05, f"reconstruction MSE {rec} too high"
    assert 0.5 < kl < 100, f"KL {kl} collapsed or blew up"
    # calibrated: healthy run lands ~0.03; a decoder whose prior samples
    # collapse to the background constant scores ~0.19 — 0.1 separates
    # them with margin on both sides
    assert l1 < 0.1, f"prior samples L1 {l1} far from data statistics"

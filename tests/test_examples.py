"""Examples must keep running end-to-end (the reference's example/ scripts
are exercised by CI the same way — SURVEY §2.7 runtime_functions.sh)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=280):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout + r.stderr


def test_train_mnist_gluon(tmp_path):
    # explicit empty data dir pins the synthetic fallback (hermetic: never
    # trains on a host's real MNIST download)
    out = _run("train_mnist.py", "--epochs", "1", "--batch-size", "256",
               "--data-dir", str(tmp_path))
    assert "final accuracy" in out


def test_train_nmt_smoke():
    out = _run("train_nmt.py", "--steps", "3", "--units", "32",
               "--batch-size", "4", "--num-layers", "1")
    assert "greedy-decode token accuracy" in out


def test_train_ssd_smoke():
    out = _run("train_ssd.py", "--steps", "2", "--batch-size", "2",
               "--data-shape", "64")
    assert "detections" in out


def test_train_faster_rcnn_smoke():
    out = _run("train_faster_rcnn.py", "--steps", "2",
               "--image-size", "96", timeout=280)
    assert "done" in out

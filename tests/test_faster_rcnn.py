"""Faster R-CNN family end-to-end (driver config #5; ref: the
reference's example/rcnn pipeline over proposal.cc + roi_align.cc)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon.model_zoo.faster_rcnn import (FasterRCNNLoss,
                                                   faster_rcnn_resnet,
                                                   rpn_anchors)


def _setup():
    np.random.seed(0)
    net = faster_rcnn_resnet(classes=3, rpn_pre_nms_top_n=200,
                             rpn_post_nms_top_n=32)
    net.initialize(mx.init.Xavier())
    H = W = 128
    x = np.random.rand(2, 3, H, W).astype(np.float32)
    im_info = np.array([[H, W, 1.0]] * 2, np.float32)
    gt = np.full((2, 2, 5), -1.0, np.float32)
    gt[0, 0] = [0, 16, 16, 80, 96]
    gt[1, 0] = [2, 40, 32, 120, 100]
    return net, x, im_info, gt, H


def test_forward_shapes_and_roi_validity():
    net, x, im_info, gt, H = _setup()
    rois, cls_logits, deltas, rpn_raw, rpn_bbox = net(
        nd.array(x), nd.array(im_info))
    assert rois.shape == (2 * 32, 5)
    assert cls_logits.shape == (64, 4) and deltas.shape == (64, 4)
    r = rois.asnumpy()
    valid = r[r[:, 0] >= 0]
    assert len(valid) > 0
    # valid rois live inside the image
    assert (valid[:, 1:] >= -1e-3).all() and (valid[:, 1:] <= H).all()
    # batch indices are 0/1
    assert set(np.unique(valid[:, 0])) <= {0.0, 1.0}


def test_training_loss_decreases():
    net, x, im_info, gt, H = _setup()
    loss_fn = FasterRCNNLoss(net)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 5e-4})
    losses = []
    for _ in range(40):
        with autograd.record():
            outs = net(nd.array(x), nd.array(im_info))
            loss = loss_fn(outs, nd.array(gt), (H, H))
        loss.backward()
        tr.step(2)
        losses.append(float(loss.asscalar()))
    assert np.isfinite(losses[-1])
    # proposals are nonstationary early on (RPN shifts them as it
    # learns), so compare best-of-tail against the start
    assert min(losses[-5:]) < 0.7 * losses[0], losses


def test_rpn_anchors_match_proposal_generation():
    # same generator as the Proposal op: center (stride-1)/2, legacy
    # (w-1)/2 extents
    anc = rpn_anchors(2, 3, feature_stride=16, scales=(8.0,),
                      ratios=(1.0,))
    assert anc.shape == (6, 4)
    c = (16 - 1) / 2.0
    np.testing.assert_allclose(
        anc[0], [c - 63.5, c - 63.5, c + 63.5, c + 63.5])
    # second cell shifts by one stride in x
    np.testing.assert_allclose(anc[1] - anc[0], [16, 0, 16, 0])


def test_rpn_layout_roundtrips_through_proposal():
    """Encode gt deltas the way FasterRCNNLoss trains them (anchor-major
    channels, variance-free) and check the Proposal op decodes back the
    gt box — the integration contract between loss and decoder."""
    stride, scales, ratios = 16, (4.0, 8.0), (1.0,)
    A = len(scales) * len(ratios)
    fh = fw = 8
    anchors = rpn_anchors(fh, fw, stride, scales, ratios)  # (hw*A, 4)
    gt_box = np.array([24.0, 40.0, 88.0, 104.0], np.float32)
    # pick the anchor with best IoU; compute its legacy-decode deltas
    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    acx = anchors[:, 0] + 0.5 * (aw - 1)
    acy = anchors[:, 1] + 0.5 * (ah - 1)
    gw, gh = gt_box[2] - gt_box[0] + 1, gt_box[3] - gt_box[1] + 1
    gcx, gcy = gt_box[0] + 0.5 * (gw - 1), gt_box[1] + 0.5 * (gh - 1)
    ious = []
    for a_ in anchors:
        ix0, iy0 = max(a_[0], gt_box[0]), max(a_[1], gt_box[1])
        ix1, iy1 = min(a_[2], gt_box[2]), min(a_[3], gt_box[3])
        inter = max(ix1 - ix0, 0) * max(iy1 - iy0, 0)
        ua = ((a_[2] - a_[0]) * (a_[3] - a_[1])
              + (gt_box[2] - gt_box[0]) * (gt_box[3] - gt_box[1])
              - inter)
        ious.append(inter / ua)
    best = int(np.argmax(ious))
    # encode THROUGH the same matcher the loss uses (extended +1 corners,
    # variances 1) so this tests the full loss->decode contract
    norm = np.array([128.0, 128.0, 128.0, 128.0], np.float32)
    ext = anchors + np.array([0, 0, 1, 1], np.float32)
    gt_row = np.array([[[0.0, gt_box[0], gt_box[1],
                         gt_box[2] + 1, gt_box[3] + 1]]], np.float32)
    gt_row[..., 1:5] /= norm
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        nd.array((ext / norm)[None]), nd.array(gt_row),
        nd.array(np.zeros((1, len(anchors), 2), np.float32)),
        overlap_threshold=0.7, negative_mining_ratio=-1.0,
        variances=(1.0, 1.0, 1.0, 1.0))
    t = loc_t.asnumpy().reshape(-1, 4)[best]
    cell, a_idx = divmod(best, A)
    y, x = divmod(cell, fw)
    cls_prob = np.zeros((1, 2 * A, fh, fw), np.float32)
    cls_prob[0, A + a_idx, y, x] = 1.0          # fg block, best anchor
    bbox = np.zeros((1, 4 * A, fh, fw), np.float32)
    bbox[0, a_idx * 4:a_idx * 4 + 4, y, x] = t  # anchor-major channels
    im_info = np.array([[128.0, 128.0, 1.0]], np.float32)
    rois = nd.contrib.Proposal(
        nd.array(cls_prob), nd.array(bbox), nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=5, rpn_min_size=1,
        scales=scales, ratios=ratios,
        feature_stride=stride).asnumpy()
    np.testing.assert_allclose(rois[0, 1:], gt_box, atol=0.6)


def test_loss_hybridizes_with_eager_parity():
    """Round-4 verdict #9: the whole train computation — model forward +
    FasterRCNNLoss (proposal↔gt matching, ROI sampling) — traces under
    hybridize()/jit as ONE program, with the same loss AND gradients as
    the eager path (divergence #12 closed; the reference's equivalent is
    the MXProposalTarget C++ op, src/operator/contrib/proposal_target.cc)."""
    net, x, im_info, gt, H = _setup()
    loss_fn = FasterRCNNLoss(net)

    class TrainStep(gluon.HybridBlock):
        def __init__(self, inner, loss, im_shape):
            super().__init__()
            self.inner = inner
            self.loss = loss
            self._im_shape = im_shape

        def hybrid_forward(self, F, xx, info, lbl):
            outs = self.inner(xx, info)
            return self.loss(outs, lbl, self._im_shape)

    step = TrainStep(net, loss_fn, (H, H))

    def run(hybridize):
        if hybridize:
            step.hybridize()
        with autograd.record():
            loss = step(nd.array(x), nd.array(im_info), nd.array(gt))
        loss.backward()
        grads = {k: p.grad().asnumpy().copy()
                 for k, p in net.collect_params().items()
                 if p.grad_req != "null"}
        return float(loss.asscalar()), grads

    l_eager, g_eager = run(False)
    l_jit, g_jit = run(True)
    assert np.isfinite(l_eager)
    np.testing.assert_allclose(l_jit, l_eager, rtol=2e-4, atol=2e-5)
    assert g_eager.keys() == g_jit.keys() and len(g_eager) > 0
    for k in g_eager:
        # jit-vs-eager fusion changes accumulation order; tolerate noise
        # relative to each tensor's gradient scale, not elementwise
        scale = max(np.abs(g_eager[k]).max(), 1e-6)
        np.testing.assert_allclose(g_jit[k] / scale, g_eager[k] / scale,
                                   rtol=0, atol=5e-3, err_msg=k)

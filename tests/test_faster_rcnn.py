"""Faster R-CNN family end-to-end (driver config #5; ref: the
reference's example/rcnn pipeline over proposal.cc + roi_align.cc)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon.model_zoo.faster_rcnn import (FasterRCNNLoss,
                                                   faster_rcnn_resnet,
                                                   rpn_anchors)


def _setup():
    np.random.seed(0)
    net = faster_rcnn_resnet(classes=3, rpn_pre_nms_top_n=200,
                             rpn_post_nms_top_n=32)
    net.initialize(mx.init.Xavier())
    H = W = 128
    x = np.random.rand(2, 3, H, W).astype(np.float32)
    im_info = np.array([[H, W, 1.0]] * 2, np.float32)
    gt = np.full((2, 2, 5), -1.0, np.float32)
    gt[0, 0] = [0, 16, 16, 80, 96]
    gt[1, 0] = [2, 40, 32, 120, 100]
    return net, x, im_info, gt, H


def test_forward_shapes_and_roi_validity():
    net, x, im_info, gt, H = _setup()
    rois, cls_logits, deltas, rpn_raw, rpn_bbox = net(
        nd.array(x), nd.array(im_info))
    assert rois.shape == (2 * 32, 5)
    assert cls_logits.shape == (64, 4) and deltas.shape == (64, 4)
    r = rois.asnumpy()
    valid = r[r[:, 0] >= 0]
    assert len(valid) > 0
    # valid rois live inside the image
    assert (valid[:, 1:] >= -1e-3).all() and (valid[:, 1:] <= H).all()
    # batch indices are 0/1
    assert set(np.unique(valid[:, 0])) <= {0.0, 1.0}


def test_training_loss_decreases():
    net, x, im_info, gt, H = _setup()
    loss_fn = FasterRCNNLoss(net)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    losses = []
    for _ in range(15):
        with autograd.record():
            outs = net(nd.array(x), nd.array(im_info))
            loss = loss_fn(outs, nd.array(gt), (H, H))
        loss.backward()
        tr.step(2)
        losses.append(float(loss.asscalar()))
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0], losses


def test_rpn_anchors_match_proposal_generation():
    # same generator as the Proposal op: center of cell (stride-1)/2
    anc = rpn_anchors(2, 3, feature_stride=16, scales=(8.0,),
                      ratios=(1.0,))
    assert anc.shape == (6, 4)
    c = (16 - 1) / 2.0
    np.testing.assert_allclose(anc[0], [c - 64, c - 64, c + 64, c + 64])
    # second cell shifts by one stride in x
    np.testing.assert_allclose(anc[1] - anc[0], [16, 0, 16, 0])

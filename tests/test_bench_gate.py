"""The driver-gate contract (VERDICT r4 Weak #1): bench.py must emit ONE
parseable JSON line under every failure mode — a wedged TPU tunnel must
never again produce an information-free rc=124."""
import json
import subprocess
import sys

import bench


def test_diagnostic_shape():
    d = bench._diagnostic("device_unreachable", "probe timed out")
    assert d["metric"] == bench.METRIC
    assert d["value"] is None and d["vs_baseline"] is None
    assert d["error"] == "device_unreachable"
    json.dumps(d)                       # serializable


def test_probe_failure_yields_diagnostic_json(monkeypatch, capsys):
    # make every probe attempt fail instantly (false exits 1)
    monkeypatch.setattr(bench, "PROBE_BACKOFF_S", (0,))
    monkeypatch.setattr(sys, "executable", "/bin/false")
    rc = bench.main()
    assert rc == 0                      # diagnostics exit clean for the driver
    line = [l for l in capsys.readouterr().out.splitlines()
            if l.startswith("{")][-1]
    parsed = json.loads(line)
    assert parsed["error"] == "device_unreachable"
    assert parsed["metric"] == bench.METRIC


def test_probe_timeout_yields_diagnostic_json(monkeypatch, capsys):
    # a probe that HANGS (sleep) must be cut off by the deadline
    monkeypatch.setattr(bench, "PROBE_BACKOFF_S", (0,))
    monkeypatch.setattr(bench, "PROBE_TIMEOUT_S", 1)
    real_run = subprocess.run

    def fake_run(cmd, **kw):
        return real_run(["/bin/sh", "-c", "sleep 30"], **kw)
    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    rc = bench.main()
    assert rc == 0
    out = capsys.readouterr().out
    parsed = json.loads([l for l in out.splitlines()
                         if l.startswith("{")][-1])
    assert parsed["error"] == "device_unreachable"
    assert "within 1s" in parsed["detail"]   # the patched deadline value

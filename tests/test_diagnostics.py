"""Runtime diagnostics subsystem (mxnet_tpu/diagnostics/): the
import-hermeticity CONTRACT (the round-4/5 RED multichip gates were an
import-time backend dial at _rng.py module scope, VERDICT r5), the
device-dial guard's deadline, the watchdog's stall dump, the journal's
SIGTERM breadcrumb, and the driver entry points' artifact contracts."""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code, env_extra=None, timeout=120, cwd=REPO):
    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.run([sys.executable, "-c", code], cwd=cwd, env=env,
                          capture_output=True, text=True, timeout=timeout)


# -- the contract that killed two driver rounds ------------------------------

def test_import_is_hermetic_under_poisoned_backend():
    """`import mxnet_tpu` with a poisoned/unreachable backend platform
    must complete in seconds with ZERO backend init. Any import-time
    device touch (the old module-scope PRNG key) raises against the
    poisoned platform and fails this test."""
    t0 = time.perf_counter()
    out = _run("import mxnet_tpu; print('IMPORT_OK')",
               env_extra={"JAX_PLATFORMS": "poisoned_nonexistent"},
               timeout=60)
    dt = time.perf_counter() - t0
    assert out.returncode == 0, out.stderr[-800:]
    assert "IMPORT_OK" in out.stdout
    # generous CI slack over the observed ~2s; a backend dial would
    # either raise (poisoned platform) or hang into the 60s timeout
    assert dt < 30, f"import took {dt:.1f}s — something heavy moved in"


def test_import_does_not_create_rng_key_eagerly():
    """The global PRNG key must be lazy: importing must not materialize
    it; first use must."""
    out = _run(
        "import mxnet_tpu\n"
        "from mxnet_tpu import _rng\n"
        "assert _rng._key is None, 'key created at import'\n"
        "_rng.next_key()\n"
        "assert _rng._key is not None\n"
        "from mxnet_tpu.diagnostics import backend_dialed\n"
        "assert backend_dialed(), 'dial not routed through the guard'\n"
        "print('LAZY_OK')",
        env_extra={"JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-800:]
    assert "LAZY_OK" in out.stdout


# -- guard -------------------------------------------------------------------

def test_guard_probe_deadline_raises_structured():
    from mxnet_tpu.diagnostics import DeviceUnreachable, probe_backend
    t0 = time.perf_counter()
    with pytest.raises(DeviceUnreachable) as ei:
        probe_backend(deadline_s=1.5, _code="import time; time.sleep(60)")
    assert time.perf_counter() - t0 < 30
    rec = ei.value.to_dict()
    assert rec["error"] == "device_unreachable"
    assert rec["deadline_s"] == 1.5
    assert rec["attempts"] == 1
    json.dumps(rec)                         # artifact-embeddable


def test_guard_probe_survives_malformed_child_stdout():
    """Malformed JSON on the probe child's stdout (ADVICE r5 low,
    bench.py:81) is a failed attempt, never an exception."""
    from mxnet_tpu.diagnostics import DeviceUnreachable, probe_backend
    with pytest.raises(DeviceUnreachable):
        probe_backend(deadline_s=30,
                      _code="print('{\"platform\": truncated garb')")
    # and a parseable line buried in noise still wins
    info = probe_backend(
        deadline_s=30,
        _code="print('noise'); print('{bad json'); "
              "print('{\"platform\": \"fake\", \"n\": 3}')")
    assert (info["platform"], info["n"]) == ("fake", 3)


def test_guard_ensure_backend_caches_and_journals(tmp_path):
    out = _run(
        "from mxnet_tpu.diagnostics import reset_journal, ensure_backend\n"
        f"j = reset_journal({str(tmp_path / 'j.jsonl')!r})\n"
        "a = ensure_backend(tag='t1')\n"
        "b = ensure_backend(tag='t2')\n"
        "assert a is b, 'second call must be the cached record'\n"
        "print('PLATFORM', a['platform'])",
        env_extra={"JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-800:]
    assert "PLATFORM cpu" in out.stdout
    recs = [json.loads(l) for l in open(tmp_path / "j.jsonl")]
    kinds = [r["kind"] for r in recs]
    assert kinds.count("backend_dial_begin") == 1     # cached: ONE dial
    assert kinds.count("backend_ok") == 1
    ok = next(r for r in recs if r["kind"] == "backend_ok")
    assert ok["phase"] == "backend_dial" and ok["tag"] == "t1"


# -- journal -----------------------------------------------------------------

def test_journal_phases_timers_and_crash(tmp_path):
    from mxnet_tpu.diagnostics import Journal
    j = Journal(str(tmp_path / "j.jsonl"))
    with j.phase("outer"):
        with j.phase("inner"):
            j.event("note", x=1)
        with j.timer("fast"):
            pass
        assert j.last_phase == "outer"
    with pytest.raises(ValueError):
        with j.phase("doomed"):
            raise ValueError("boom")
    recs = [json.loads(l) for l in open(j.path)]
    kinds = [r["kind"] for r in recs]
    assert kinds.count("phase_enter") == 3 and kinds.count("phase_exit") == 3
    note = next(r for r in recs if r["kind"] == "note")
    assert note["phase"] == "inner" and note["x"] == 1
    exit_inner = [r for r in recs if r["kind"] == "phase_exit"][0]
    assert exit_inner["dur_s"] >= 0
    crash = next(r for r in recs if r["kind"] == "crash")
    assert crash["error"] == "ValueError" and "boom" in crash["detail"]
    assert "doomed" in crash["phase"]


def test_journal_sigterm_flushes_final_breadcrumb(tmp_path):
    """A driver `timeout` kill (SIGTERM) must leave a final breadcrumb
    with the last-known phase — the no-silent-rc:124 contract."""
    jp = str(tmp_path / "j.jsonl")
    code = (
        "import time, sys\n"
        "from mxnet_tpu.diagnostics import Journal\n"
        f"j = Journal({jp!r})\n"
        "j.install_handlers(final_cb=lambda: print("
        "'{\"event\": \"killed\"}', flush=True))\n"
        "j.set_phase('phase_x')\n"
        "print('READY', flush=True)\n"
        "time.sleep(60)\n")
    p = subprocess.Popen([sys.executable, "-c", code], cwd=REPO,
                         stdout=subprocess.PIPE, text=True,
                         env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        assert p.stdout.readline().strip() == "READY"
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=60)
    finally:
        p.kill()
    out = p.stdout.read()
    assert rc == -signal.SIGTERM          # disposition preserved
    assert json.loads(out)["event"] == "killed"
    recs = [json.loads(l) for l in open(jp)]
    final = [r for r in recs if r["kind"] == "final"]
    assert len(final) == 1
    assert final[0]["reason"] == "sigterm"
    assert final[0]["last_phase"] == "phase_x"


def test_journal_mark_clean_suppresses_final_cb(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    code = (
        "from mxnet_tpu.diagnostics import Journal\n"
        f"j = Journal({jp!r})\n"
        "j.install_handlers(final_cb=lambda: print('SPURIOUS'))\n"
        "j.set_phase('done')\n"
        "j.mark_clean()\n")
    out = _run(code, env_extra={"JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-500:]
    assert "SPURIOUS" not in out.stdout
    final = [json.loads(l) for l in open(jp)][-1]
    assert final["kind"] == "final" and final["clean"] is True


# -- watchdog ----------------------------------------------------------------

def test_watchdog_heartbeats_and_stall_dump(tmp_path):
    from mxnet_tpu.diagnostics import Journal, Watchdog
    j = Journal(str(tmp_path / "j.jsonl"))
    wd = Watchdog(journal=j, interval_s=0.05, stall_s=0.2)
    wd.start()
    time.sleep(0.7)                       # no progress -> stall fires
    j.event("progress")                   # resumes -> re-arms
    time.sleep(0.35)
    wd.stop()
    recs = [json.loads(l) for l in open(j.path)]
    hb = [r for r in recs if r["kind"] == "heartbeat"]
    assert len(hb) >= 3
    assert hb[0]["rss_mb"] > 0 and "wall_s" in hb[0]
    stalls = [r for r in recs if r["kind"] == "stall"]
    assert len(stalls) == 2, "one dump per stall episode, re-armed after"
    assert stalls[0]["idle_s"] >= 0.2
    # the dump pins the hang to actual stacks
    assert "Thread" in stalls[0]["tracebacks"] or \
        "File" in stalls[0]["tracebacks"]


def test_watchdog_beat_defers_stall(tmp_path):
    from mxnet_tpu.diagnostics import Journal, Watchdog
    j = Journal(str(tmp_path / "j.jsonl"))
    wd = Watchdog(journal=j, interval_s=0.05, stall_s=0.3)
    wd.start()
    for _ in range(8):                    # busy loop that beats
        time.sleep(0.05)
        wd.beat()
    wd.stop()
    recs = [json.loads(l) for l in open(j.path)]
    assert not [r for r in recs if r["kind"] == "stall"]


# -- CLI ---------------------------------------------------------------------

def test_cli_probe_emits_one_json_line():
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.diagnostics", "probe",
         "--deadline", "90"], cwd=REPO, capture_output=True, text=True,
        timeout=120, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.splitlines() if l.strip()]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["ok"] is True and rec["platform"] == "cpu"


def test_cli_doctor_reports_import_audit_and_backend():
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.diagnostics", "doctor",
         "--deadline", "120"], cwd=REPO, capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-500:]
    rec = json.loads(out.stdout.splitlines()[-1])
    assert rec["healthy"] is True
    assert rec["import_audit"]["ok"] is True
    assert rec["backend"]["platform"] == "cpu"
    assert rec["mesh"]["devices"] >= 1
    assert any(m["module"] == "mxnet_tpu"
               for m in rec["import_audit"]["slowest_toplevel"])


# -- driver entry points -----------------------------------------------------

def test_bench_probe_parser_rejects_malformed_json():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    from mxnet_tpu.diagnostics.guard import _parse_info_line
    assert _parse_info_line('{"platform": trunc') is None
    assert _parse_info_line("") is None
    assert _parse_info_line('x\n{"platform": "tpu", "n": 8}\n') == \
        {"platform": "tpu", "n": 8}
    # bench's constants still match the documented budget story
    assert bench.PROBE_BACKOFF_S == (0, 20, 45)


def test_dryrun_entry_breadcrumb_and_budget(monkeypatch, capsys):
    """First statement of dryrun_multichip prints an unbuffered
    structured JSON line, and the hermetic-subprocess budget is ONE
    attempt of <= 240s (so worst case lands inside a 300s window,
    VERDICT r5 Weak #7)."""
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.pop(0)
    calls = []

    def fake_run(cmd, **kw):
        calls.append(kw)
        class R:
            returncode = 0
        return R()

    monkeypatch.setattr(g.subprocess, "run", fake_run)
    monkeypatch.setattr(g, "_cpu_mesh_ok", lambda n: False)
    g.dryrun_multichip(8)
    first = capsys.readouterr().out.splitlines()[0]
    rec = json.loads(first)
    assert rec["event"] == "dryrun_multichip_enter" and rec["n"] == 8
    assert len(calls) == 1
    assert calls[0]["timeout"] <= 300

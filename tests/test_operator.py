"""Per-op correctness + gradient checks
(ref test: tests/python/unittest/test_operator.py — the reference's largest
test file; method: numpy forward parity + central-finite-difference grads)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  rand_ndarray)


def test_unary_forward_parity():
    x_np = np.random.uniform(0.1, 2.0, size=(3, 4)).astype(np.float32)
    x = nd.array(x_np)
    cases = {
        "exp": np.exp, "log": np.log, "sqrt": np.sqrt, "square": np.square,
        "abs": np.abs, "sign": np.sign, "floor": np.floor, "ceil": np.ceil,
        "sin": np.sin, "cos": np.cos, "tanh": np.tanh,
        "sigmoid": lambda v: 1 / (1 + np.exp(-v)),
        "relu": lambda v: np.maximum(v, 0),
        "log1p": np.log1p, "expm1": np.expm1, "rsqrt": lambda v: 1 / np.sqrt(v),
    }
    for name, ref in cases.items():
        out = getattr(nd, name)(x)
        assert_almost_equal(out, ref(x_np), rtol=1e-4, atol=1e-5,
                            names=(name, "numpy"))


def test_binary_broadcast():
    a = nd.array(np.random.rand(2, 1, 4).astype(np.float32))
    b = nd.array(np.random.rand(1, 3, 4).astype(np.float32))
    assert_almost_equal(nd.broadcast_add(a, b), a.asnumpy() + b.asnumpy())
    assert_almost_equal(nd.broadcast_mul(a, b), a.asnumpy() * b.asnumpy())
    assert_almost_equal(nd.broadcast_maximum(a, b),
                        np.maximum(a.asnumpy(), b.asnumpy()))


def test_reductions():
    x_np = np.random.rand(2, 3, 4).astype(np.float32)
    x = nd.array(x_np)
    assert_almost_equal(nd.sum(x), x_np.sum())
    assert_almost_equal(nd.sum(x, axis=1), x_np.sum(axis=1))
    assert_almost_equal(nd.sum(x, axis=(0, 2), keepdims=True),
                        x_np.sum(axis=(0, 2), keepdims=True))
    assert_almost_equal(nd.mean(x, axis=1, exclude=True),
                        x_np.mean(axis=(0, 2)))
    assert_almost_equal(nd.max(x, axis=2), x_np.max(axis=2))
    assert_almost_equal(nd.argmax(x, axis=1), x_np.argmax(axis=1))
    assert_almost_equal(nd.norm(x), np.sqrt((x_np ** 2).sum()), rtol=1e-4)


def test_dot():
    a = rand_ndarray((3, 4))
    b = rand_ndarray((4, 5))
    assert_almost_equal(nd.dot(a, b), a.asnumpy() @ b.asnumpy(), rtol=1e-4)
    assert_almost_equal(nd.dot(a, b.T, transpose_b=True)._data.shape,
                        nd.dot(a, b.T, transpose_b=True).asnumpy().shape)
    c = rand_ndarray((2, 3, 4))
    d = rand_ndarray((2, 4, 5))
    assert_almost_equal(nd.batch_dot(c, d),
                        np.matmul(c.asnumpy(), d.asnumpy()), rtol=1e-4)


def test_gradients_numeric():
    check_numeric_gradient(lambda x: nd.tanh(x), [rand_ndarray((3, 3))])
    check_numeric_gradient(lambda x: nd.sigmoid(x), [rand_ndarray((3, 3))])
    check_numeric_gradient(lambda a, b: nd.dot(a, b),
                           [rand_ndarray((3, 4)), rand_ndarray((4, 2))])
    check_numeric_gradient(lambda x: nd.softmax(x), [rand_ndarray((2, 5))])
    check_numeric_gradient(
        lambda x: nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg"),
        [rand_ndarray((1, 2, 4, 4))])


def test_fully_connected():
    x = rand_ndarray((2, 3, 4))
    w = rand_ndarray((8, 12))
    b = rand_ndarray((8,))
    out = nd.FullyConnected(x, w, b, num_hidden=8)
    expect = x.asnumpy().reshape(2, 12) @ w.asnumpy().T + b.asnumpy()
    assert_almost_equal(out, expect, rtol=1e-4)
    out2 = nd.FullyConnected(x, nd.array(np.random.rand(8, 4).astype(np.float32)),
                             b, num_hidden=8, flatten=False)
    assert out2.shape == (2, 3, 8)


def test_convolution_vs_numpy():
    # naive conv reference
    x = np.random.rand(1, 2, 5, 5).astype(np.float32)
    w = np.random.rand(3, 2, 3, 3).astype(np.float32)
    out = nd.Convolution(nd.array(x), nd.array(w), nd.zeros((3,)),
                         kernel=(3, 3), num_filter=3).asnumpy()
    ref = np.zeros((1, 3, 3, 3), dtype=np.float32)
    for o in range(3):
        for i in range(3):
            for j in range(3):
                ref[0, o, i, j] = (x[0, :, i:i+3, j:j+3] * w[o]).sum()
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)


def test_conv_grad():
    check_numeric_gradient(
        lambda x, w: nd.Convolution(x, w, kernel=(3, 3), num_filter=2,
                                    no_bias=True, pad=(1, 1)),
        [rand_ndarray((1, 2, 4, 4)), rand_ndarray((2, 2, 3, 3))],
        rtol=2e-2, atol=1e-2)


def test_pooling_modes():
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    mp = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert mp.asnumpy().ravel().tolist() == [5, 7, 13, 15]
    ap = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    assert ap.asnumpy().ravel().tolist() == [2.5, 4.5, 10.5, 12.5]
    gp = nd.Pooling(x, pool_type="max", global_pool=True)
    assert gp.asnumpy().ravel().tolist() == [15]


def test_batchnorm_inference_and_training():
    x = rand_ndarray((4, 3, 2, 2))
    gamma, beta = nd.ones((3,)), nd.zeros((3,))
    mean, var = nd.zeros((3,)), nd.ones((3,))
    out, m, v = nd.BatchNorm(x, gamma, beta, mean, var, fix_gamma=False)
    assert_almost_equal(out, x.asnumpy() / np.sqrt(1 + 1e-3), rtol=1e-3)
    with autograd.record():
        out_t, m_t, v_t = nd.BatchNorm(x, gamma, beta, mean, var,
                                       fix_gamma=False)
    x_np = x.asnumpy()
    assert_almost_equal(m_t, x_np.mean(axis=(0, 2, 3)), rtol=1e-4)


def test_layernorm():
    x = rand_ndarray((2, 5))
    g, b = nd.ones((5,)), nd.zeros((5,))
    out = nd.LayerNorm(x, g, b)
    x_np = x.asnumpy()
    ref = (x_np - x_np.mean(-1, keepdims=True)) / np.sqrt(
        x_np.var(-1, keepdims=True) + 1e-5)
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)
    check_numeric_gradient(lambda a: nd.LayerNorm(a, g, b), [x], rtol=2e-2)


def test_softmax_ce_gradient():
    # SoftmaxOutput backward = softmax - onehot
    x = rand_ndarray((3, 5))
    label = nd.array([0, 2, 4])
    x.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(x, label)
    out.backward()
    p = out.asnumpy()
    onehot = np.eye(5, dtype=np.float32)[[0, 2, 4]]
    assert_almost_equal(x.grad, p - onehot, rtol=1e-4, atol=1e-5)


def test_take_embedding():
    w = rand_ndarray((10, 4))
    idx = nd.array([1, 5, 9])
    out = nd.Embedding(idx, w, input_dim=10, output_dim=4)
    assert_almost_equal(out, w.asnumpy()[[1, 5, 9]])
    out2 = nd.take(w, idx)
    assert_almost_equal(out2, w.asnumpy()[[1, 5, 9]])


def test_embedding_grad_accumulates():
    w = rand_ndarray((5, 3))
    w.attach_grad()
    idx = nd.array([1, 1, 2])
    with autograd.record():
        out = nd.Embedding(idx, w, input_dim=5, output_dim=3).sum()
    out.backward()
    g = w.grad.asnumpy()
    assert g[1].tolist() == [2, 2, 2]   # index 1 used twice
    assert g[2].tolist() == [1, 1, 1]
    assert g[0].tolist() == [0, 0, 0]


def test_ordering():
    x = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    assert nd.sort(x).asnumpy()[0].tolist() == [1, 2, 3]
    assert nd.argsort(x).asnumpy()[0].tolist() == [1, 2, 0]
    vals, idx = nd.topk(x, k=2, ret_typ="both")
    assert vals.asnumpy()[0].tolist() == [3, 2]
    assert idx.asnumpy()[0].tolist() == [0, 2]


def test_where_clip_onehot():
    cond = nd.array([1.0, 0.0, 1.0])
    x, y = nd.ones((3,)), nd.zeros((3,))
    assert nd.where(cond, x, y).asnumpy().tolist() == [1, 0, 1]
    assert nd.clip(nd.array([-2.0, 0.5, 9.0]), 0.0, 1.0).asnumpy().tolist() == [0, 0.5, 1]
    oh = nd.one_hot(nd.array([0, 2]), 3)
    assert oh.asnumpy().tolist() == [[1, 0, 0], [0, 0, 1]]


def test_slicing_ops():
    x = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    s = nd.slice(x, begin=(0, 1, 0), end=(2, 3, 2))
    assert s.shape == (2, 2, 2)
    sa = nd.slice_axis(x, axis=2, begin=1, end=3)
    assert sa.shape == (2, 3, 2)
    sl = nd.slice_like(x, nd.zeros((1, 2, 2)))
    assert sl.shape == (1, 2, 2)


def test_gather_scatter():
    data = nd.array(np.arange(9, dtype=np.float32).reshape(3, 3))
    idx = nd.array([[0, 2], [1, 0]])   # (2 index dims, 2 points)
    out = nd.gather_nd(data, idx)
    assert out.asnumpy().tolist() == [1, 6]
    scat = nd.scatter_nd(nd.array([5.0, 7.0]), idx, shape=(3, 3))
    assert scat.asnumpy()[0, 1] == 5 and scat.asnumpy()[2, 0] == 7


def test_rnn_lstm_shapes_and_grad():
    T, N, C, H, L = 3, 2, 4, 5, 1
    g = 4
    nparams = g * H * (C + H) + 2 * g * H
    data = rand_ndarray((T, N, C))
    params = rand_ndarray((nparams,), scale=0.1)
    h0 = nd.zeros((L, N, H))
    c0 = nd.zeros((L, N, H))
    out = nd.RNN(data, params, h0, c0, state_size=H, num_layers=L, mode="lstm")
    assert out.shape == (T, N, H)
    outs = nd.RNN(data, params, h0, c0, state_size=H, num_layers=L,
                  mode="lstm", state_outputs=True)
    assert outs[1].shape == (L, N, H) and outs[2].shape == (L, N, H)
    # bidirectional
    nparams_bi = 2 * (g * H * (C + H) + 2 * g * H) + 0
    # layer0 reverse dir input is C too
    out_bi = nd.RNN(data, rand_ndarray((nparams_bi,), scale=0.1),
                    nd.zeros((2, N, H)), nd.zeros((2, N, H)),
                    state_size=H, num_layers=1, mode="lstm", bidirectional=True)
    assert out_bi.shape == (T, N, 2 * H)


def test_sequence_ops():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 2, 2))
    lens = nd.array([2, 3])
    m = nd.SequenceMask(x, lens, use_sequence_length=True, value=-1)
    assert m.asnumpy()[2, 0].tolist() == [-1, -1]   # seq 0 len 2 -> step 2 masked
    assert m.asnumpy()[2, 1].tolist() == [10, 11]
    last = nd.SequenceLast(x, lens, use_sequence_length=True)
    assert last.asnumpy()[0].tolist() == [4, 5]     # step 1 of seq 0
    rev = nd.SequenceReverse(x, lens, use_sequence_length=True)
    assert rev.asnumpy()[0, 0].tolist() == [4, 5]


def test_optimizer_update_ops():
    w = nd.ones((4,))
    g = nd.full((4,), 0.5)
    out = nd.sgd_update(w, g, lr=0.1)
    assert_almost_equal(out, np.full(4, 1 - 0.05), rtol=1e-5)
    mom = nd.zeros((4,))
    w2, m2 = nd.sgd_mom_update(w, g, mom, lr=0.1, momentum=0.9)
    assert_almost_equal(w2, np.full(4, 0.95), rtol=1e-5)
    mean, var = nd.zeros((4,)), nd.zeros((4,))
    w3, m3, v3 = nd.adam_update(w, g, mean, var, lr=0.1)
    assert w3.shape == (4,)


def test_contrib_box_ops():
    boxes = nd.array([[0, 0, 1, 1], [0.5, 0.5, 1.5, 1.5], [5, 5, 6, 6]])
    iou = nd.contrib.box_iou(boxes, boxes)
    assert_almost_equal(np.diag(iou.asnumpy()), np.ones(3), rtol=1e-5)
    assert abs(iou.asnumpy()[0, 1] - 0.25 / 1.75) < 1e-5
    # NMS: rows [cls, score, x1, y1, x2, y2]
    dets = nd.array([[0, 0.9, 0, 0, 1, 1],
                     [0, 0.8, 0.05, 0.05, 1.05, 1.05],
                     [0, 0.7, 5, 5, 6, 6]])
    kept = nd.contrib.box_nms(dets, overlap_thresh=0.5)
    k = kept.asnumpy()
    assert k[0, 1] == pytest.approx(0.9)
    assert k[1, 1] == pytest.approx(0.7)    # overlapping 0.8 suppressed
    assert (k[2] == -1).all()


def test_smooth_l1_and_makeloss():
    x = nd.array([-2.0, -0.5, 0.5, 2.0])
    out = nd.smooth_l1(x, scalar=1.0)
    ref = np.where(np.abs(x.asnumpy()) < 1, 0.5 * x.asnumpy() ** 2,
                   np.abs(x.asnumpy()) - 0.5)
    assert_almost_equal(out, ref, rtol=1e-5)


def test_cast_and_amp_cast():
    x = nd.array([1.7, 2.3])
    assert nd.Cast(x, dtype="int32").asnumpy().tolist() == [1, 2]
    assert "bfloat16" in str(nd.amp_cast(x, dtype="bfloat16").dtype)


def test_dropout_modes():
    x = nd.ones((100, 100))
    out = nd.Dropout(x, p=0.5)          # not training: identity
    assert (out.asnumpy() == 1).all()
    with autograd.record():
        out_t = nd.Dropout(x, p=0.5)
    frac = (out_t.asnumpy() == 0).mean()
    assert 0.4 < frac < 0.6
    kept = out_t.asnumpy()[out_t.asnumpy() != 0]
    assert np.allclose(kept, 2.0)       # inverted dropout scaling


def test_random_ops_distributions():
    u = nd.random.uniform(0, 1, shape=(1000,))
    assert 0 <= u.asnumpy().min() and u.asnumpy().max() < 1
    n = nd.random.normal(0, 1, shape=(5000,))
    assert abs(n.asnumpy().mean()) < 0.1
    r = nd.random.randint(0, 10, shape=(100,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10


def test_activation_variants():
    x = nd.array([-1.0, 0.0, 1.0])
    assert_almost_equal(nd.Activation(x, act_type="relu"), [0, 0, 1])
    assert_almost_equal(nd.LeakyReLU(x, act_type="leaky", slope=0.1),
                        [-0.1, 0, 1], rtol=1e-5)
    elu = nd.LeakyReLU(x, act_type="elu", slope=1.0)
    assert_almost_equal(elu, [np.expm1(-1), 0, 1], rtol=1e-4)
    gelu = nd.LeakyReLU(x, act_type="gelu")
    assert abs(gelu.asnumpy()[2] - 0.8413) < 1e-3


def test_norm_layers_large_mean_precision():
    # moments must accumulate in >= fp32 and stay cancellation-safe for
    # |mean| >> std inputs (the raw one-pass E[x^2]-E[x]^2 fails this)
    rng = np.random.RandomState(0)
    x = (rng.randn(32, 8) * 0.01 + 1000).astype(np.float32)
    g = np.ones(8, np.float32)
    b = np.zeros(8, np.float32)
    o = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g),
                        mx.nd.array(b)).asnumpy()
    assert 0.5 < o.std() < 2.0, o.std()
    x4 = (rng.randn(2, 4, 5, 5) * 0.1 + 1000).astype(np.float32)
    g4 = np.ones(4, np.float32)
    b4 = np.zeros(4, np.float32)
    og = mx.nd.GroupNorm(mx.nd.array(x4), mx.nd.array(g4),
                         mx.nd.array(b4), num_groups=2).asnumpy()
    assert 0.5 < og.std() < 2.0, og.std()
    oi = mx.nd.InstanceNorm(mx.nd.array(x4), mx.nd.array(g4),
                            mx.nd.array(b4)).asnumpy()
    assert 0.5 < oi.std() < 2.0, oi.std()


def test_batch_norm_large_mean_cold_start():
    """Round-2 advisor finding: training-mode BN on |mean|>>std input
    with cold (init) running stats. The design (ops/nn.py _batch_norm +
    gluon BatchNorm cold-start adoption): step 1 output is BOUNDED (the
    e2 fallback normalizer — no rsqrt(garbage) explosion; the advisor
    measured output std 158), and from step 2 the running-mean shift is
    near the true mean so normalization is exact."""
    from mxnet_tpu import autograd, gluon, nd
    rng = np.random.RandomState(1)
    x = (rng.randn(16, 4, 6, 6) + 1e4).astype(np.float32)
    bn = gluon.nn.BatchNorm(in_channels=4)
    bn.initialize()
    with autograd.record(train_mode=True):
        out1 = bn(nd.array(x)).asnumpy()
    assert np.isfinite(out1).all()
    assert out1.std() < 2.0, f"cold-start output exploded: {out1.std()}"
    # cold-start adoption: moving stats == first batch stats exactly
    np.testing.assert_allclose(bn.running_mean.data().asnumpy(),
                               x.mean(axis=(0, 2, 3)), rtol=1e-5)
    # the e2 fallback variance must NOT poison the running stats
    # (review repro: adopting it put running_var at ~1e8 and eval std
    # at 1e-4): running_var keeps its init scale on suspicious channels
    assert bn.running_var.data().asnumpy().max() < 1e3, \
        bn.running_var.data().asnumpy().max()
    with autograd.record(train_mode=True):
        out2 = bn(nd.array(x)).asnumpy()
    assert 0.9 < out2.std() < 1.1, \
        f"warm-shift normalization wrong: std {out2.std()}"
    # eval mode right after warmup normalizes sanely too
    out_eval = bn(nd.array(x)).asnumpy()
    assert 0.5 < out_eval.std() < 2.0, \
        f"eval-mode normalization broken: std {out_eval.std()}"
    # large-mean AND std != 1 (review repro): running_var must WARM to
    # the true batch variance over steps, not freeze at its init value
    bn2 = gluon.nn.BatchNorm(in_channels=4)
    bn2.initialize()
    x2 = (rng.randn(16, 4, 6, 6) * 10 + 1000).astype(np.float32)
    for _ in range(30):
        with autograd.record(train_mode=True):
            bn2(nd.array(x2))
    rv = bn2.running_var.data().asnumpy()
    true_var = x2.var(axis=(0, 2, 3))
    assert np.all(rv > 0.5 * true_var), (rv, true_var)
    out_eval2 = bn2(nd.array(x2)).asnumpy()
    assert 0.5 < out_eval2.std() < 2.0, \
        f"eval std after warm training: {out_eval2.std()}"
    # op level: the batch-mean OUTPUT is exact even at cold start (the
    # shift cancels analytically in the mean), and var never explodes
    zeros = np.zeros(4, np.float32)
    with mx.autograd.record(train_mode=True):
        _, bmean, bvar = mx.nd.BatchNorm(
            mx.nd.array(x), mx.nd.array(np.ones(4, np.float32)),
            mx.nd.array(zeros), mx.nd.array(zeros), mx.nd.array(zeros),
            fix_gamma=False, output_mean_var=True)
    np.testing.assert_allclose(bmean.asnumpy(),
                               x.mean(axis=(0, 2, 3)), rtol=1e-5)
    assert np.isfinite(bvar.asnumpy()).all()


def test_public_binary_helpers_dispatch():
    """Round-4: the python-layer scalar-or-array binary helpers (ref:
    python/mxnet/ndarray/ndarray.py maximum/minimum/power/equal/...) —
    array⊕array → broadcast op, array⊕scalar → _*_scalar, scalar⊕array →
    reflected scalar op, scalar⊕scalar → plain python."""
    import mxnet_tpu.symbol as sym
    a = mx.nd.array(np.array([[0.2, 0.8], [1.5, -0.3]], np.float32))
    b = mx.nd.array(np.array([[1.0, 0.5], [0.5, 0.5]], np.float32))
    np.testing.assert_allclose(mx.nd.maximum(a, b).asnumpy(),
                               np.maximum(a.asnumpy(), b.asnumpy()))
    np.testing.assert_allclose(mx.nd.maximum(a, 0.5).asnumpy(),
                               np.maximum(a.asnumpy(), 0.5))
    np.testing.assert_allclose(mx.nd.minimum(0.5, a).asnumpy(),
                               np.minimum(0.5, a.asnumpy()))
    assert mx.nd.maximum(2, 3) == 3
    # non-commutative reflected forms
    np.testing.assert_allclose(mx.nd.power(2.0, a).asnumpy(),
                               2.0 ** a.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(mx.nd.greater(1.0, a).asnumpy(),
                               (1.0 > a.asnumpy()).astype(np.float32))
    np.testing.assert_allclose(mx.nd.modulo(0.7, b).asnumpy(),
                               np.mod(0.7, b.asnumpy()), rtol=1e-6)
    np.testing.assert_allclose(
        mx.nd.hypot(a, 0.5).asnumpy(),
        np.hypot(a.asnumpy(), 0.5), rtol=1e-6)
    # the same helpers exist on the symbol namespace and trace
    x = sym.var("x")
    out = sym.maximum(x, 0.25)
    got = out.eval(x=a)[0].asnumpy()
    np.testing.assert_allclose(got, np.maximum(a.asnumpy(), 0.25))


def test_contrib_straggler_ops_round5():
    """quadratic/allclose/index_copy/boolean_mask/BatchNormWithReLU
    (ref: src/operator/contrib/{quadratic_op,allclose_op,index_copy,
    boolean_mask}.cc, src/operator/nn/batch_norm_relu.cc)."""
    import pytest
    from mxnet_tpu.base import MXNetError
    x = nd.array(np.array([1.0, 2.0, -1.0], np.float32))
    np.testing.assert_allclose(
        mx.nd.contrib.quadratic(x, a=1.0, b=2.0, c=3.0).asnumpy(),
        [6.0, 11.0, 2.0])
    assert float(mx.nd.contrib.allclose(x, x).asnumpy()) == 1.0
    assert float(mx.nd.contrib.allclose(x, x + 1.0).asnumpy()) == 0.0

    old = nd.array(np.zeros((4, 2), np.float32))
    new = nd.array(np.ones((2, 2), np.float32) * 7)
    idx = nd.array(np.array([1, 3], np.int32))
    out = mx.nd.contrib.index_copy(old, idx, new).asnumpy()
    assert out[1, 0] == 7 and out[3, 1] == 7 and out[0, 0] == 0
    assert old.asnumpy()[1, 0] == 0          # functional: input untouched

    d = nd.array(np.arange(8).reshape(4, 2).astype(np.float32))
    m = nd.array(np.array([1, 0, 1, 0], np.float32))
    bm = mx.nd.contrib.boolean_mask(d, m).asnumpy()
    np.testing.assert_allclose(bm, [[0, 1], [4, 5]])
    # inside jit the data-dependent shape must error clearly
    from mxnet_tpu import gluon

    class BM(gluon.HybridBlock):
        def hybrid_forward(self, F, data, mask):
            return F.contrib.boolean_mask(data, mask)

    net = BM()
    net.hybridize()
    with pytest.raises(MXNetError, match="jit"):
        net(d, m)

    g, b = nd.ones((3,)), nd.zeros((3,))
    rm, rv = nd.zeros((3,)), nd.ones((3,))
    xx = nd.array(np.random.RandomState(0).randn(2, 3, 4, 4)
                  .astype(np.float32))
    bnr = mx.nd.contrib.BatchNormWithReLU(xx, g, b, rm, rv)
    out0 = (bnr[0] if isinstance(bnr, list) else bnr).asnumpy()
    ref = (mx.nd.BatchNorm(xx, g, b, rm, rv)[0]
           if isinstance(mx.nd.BatchNorm(xx, g, b, rm, rv), list)
           else mx.nd.BatchNorm(xx, g, b, rm, rv)).asnumpy()
    np.testing.assert_allclose(out0, np.maximum(ref, 0.0), rtol=1e-6)


def test_contrib_straggler_validation_round5():
    """Bounds/shape validation the reference performs must error, not
    silently drop (review-pinned)."""
    import pytest
    from mxnet_tpu.base import MXNetError
    old = nd.array(np.zeros((4, 2), np.float32))
    new = nd.array(np.ones((1, 2), np.float32))
    with pytest.raises(MXNetError, match="out of range"):
        mx.nd.contrib.index_copy(old, nd.array(np.array([9], np.int32)),
                                 new)
    d = nd.array(np.arange(8).reshape(4, 2).astype(np.float32))
    with pytest.raises(MXNetError, match="mask length"):
        mx.nd.contrib.boolean_mask(
            d, nd.array(np.array([1, 0, 1, 0, 1, 1], np.float32)))

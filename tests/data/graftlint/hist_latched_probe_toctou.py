# graftlint: scope=library
"""Historical fixture — the PR-9 half-open probe admission, PRE-fix,
seen through G24's lens: the breaker admits exactly ONE probe per
quarantined replica, but membership in the probing set was checked
during candidate enumeration and the slot claimed later, with no lock
spanning the two.  Under hedged load two dispatch threads both passed
the ``not in`` test and both admitted a probe — the "exactly one"
invariant silently broke (the companion hist_latched_probe.py fixture
shows the same bug's leak-on-exception face, G17's territory).
Parsed only, never executed."""
import threading


class PreFixProbeAdmission:
    def __init__(self):
        self._probing = set()
        self._stop = threading.Event()
        self._sweeper = None

    def start(self):
        self._sweeper = threading.Thread(target=self._sweep, daemon=True)
        self._sweeper.start()

    def _sweep(self):
        while not self._stop.wait(0.05):
            for rid in ("a", "b"):
                self.try_admit_probe(rid)

    def try_admit_probe(self, rid):
        # request threads race the sweeper through this same gate
        if rid not in self._probing:
            self._probing.add(rid)  # expect: G24
            return True
        return False

    def probing(self):
        return set(self._probing)

# graftlint: scope=library
"""G18 fixture: host-level collectives guarded by conditions whose
rank-taint flows through FUNCTION RETURNS — the shapes per-function G12
structurally cannot see (no ``process_index`` text in the guarded
scope).  Parsed only, never executed."""
import jax
from jax.experimental import multihost_utils


def _is_coordinator():
    return jax.process_index() == 0


def _is_leader_deep():
    # taint through a second hop: the fixpoint must propagate it
    return _is_coordinator()


def bad_helper_guard(tree):
    if _is_coordinator():
        multihost_utils.process_allgather(tree)  # expect: G18


def bad_deep_helper_guard(tag):
    if _is_leader_deep():
        multihost_utils.sync_global_devices(tag)  # expect: G18


def bad_assigned_verdict(tree):
    main = _is_coordinator()
    if main:
        multihost_utils.process_allgather(tree)  # expect: G18


def good_world_size_guard(tree):
    # world-SIZE conditionals are rank-uniform: every rank agrees
    if jax.process_count() > 1:
        multihost_utils.process_allgather(tree)


def good_unconditional(tag):
    multihost_utils.sync_global_devices(tag)


def _shard_count():
    return jax.device_count()


def good_untainted_helper(tree):
    # a helper that does NOT derive from process_index is no guard
    if _shard_count() > 8:
        multihost_utils.process_allgather(tree)


def good_disable_twin(tree):
    if _is_coordinator():
        # graftlint: disable=G18 fixture twin: justified exception
        multihost_utils.process_allgather(tree)

# graftlint: scope=library
"""G2 fixture: PRNG discipline in library code (constant keys, key
reuse without split/fold_in). Parsed only, never imported."""
import jax
import jax.random as jr


def constant_key(shape):
    key = jax.random.PRNGKey(0)                     # expect: G2
    return jax.random.uniform(key, shape)


def constant_key_keyword(shape):
    key = jax.random.PRNGKey(seed=3)                # expect: G2
    return jax.random.uniform(key, shape)


def split_result_dropped(key, shape):
    # split whose result is never bound does NOT freshen `key`
    a = jax.random.normal(key, shape)
    jax.random.split(key, 2)
    b = jax.random.normal(key, shape)               # expect: G2
    return a + b


def constant_key_twin(shape):
    key = jax.random.PRNGKey(1)  # graftlint: disable=G2 fixture twin
    return jax.random.uniform(key, shape)


def reuse(key, shape):
    a = jax.random.normal(key, shape)
    b = jax.random.normal(key, shape)               # expect: G2
    return a + b


def reuse_via_alias(key, shape):
    a = jr.uniform(key, shape)
    b = jr.uniform(key, shape)                      # expect: G2
    return a + b


def split_between(key, shape):
    # refreshed key between draws: must not flag
    a = jax.random.normal(key, shape)
    key = jax.random.fold_in(key, 1)
    b = jax.random.normal(key, shape)
    return a + b


def split_two(key, shape):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, shape)
    b = jax.random.normal(k2, shape)
    return a + b


def exclusive_branches(key, shape, training):
    # one draw per if/else arm: only one executes — must not flag
    if training:
        return jax.random.bernoulli(key, 0.5, shape)
    else:
        return jax.random.normal(key, shape)


def branch_then_reuse(key, shape, training):
    if training:
        a = jax.random.normal(key, shape)
    else:
        a = jax.random.uniform(key, shape)
    b = jax.random.normal(key, shape)               # expect: G2
    return a + b


def walrus_refresh(key, shape):
    # a walrus rebind refreshes the key: must not flag
    a = jax.random.normal(key, shape)
    if (key := jax.random.fold_in(key, 1)) is not None:
        a = a + jax.random.normal(key, shape)
    return a


def guard_clause(key, shape, training):
    # the early return never rejoins the fall-through: must not flag
    if training:
        return jax.random.bernoulli(key, 0.5, shape)
    return jax.random.normal(key, shape)


def exclusive_handlers(key, shape, fn):
    # at most one except arm runs: must not flag
    try:
        return fn()
    except ValueError:
        return jax.random.normal(key, shape)
    except TypeError:
        return jax.random.uniform(key, shape)


def loop_reuse(key, shape, n):
    out = []
    for _ in range(n):
        # same key every iteration: identical bits per tick
        out.append(jax.random.normal(key, shape))   # expect: G2
    return out


def loop_fold(key, shape, n):
    # per-iteration fold_in refreshes the key: must not flag
    out = []
    for i in range(n):
        key = jax.random.fold_in(key, i)
        out.append(jax.random.normal(key, shape))
    return out


def loop_split_target(key, shape, n):
    # the canonical idiom: the loop target binds a FRESH key per
    # iteration — must not flag
    out = []
    for k in jax.random.split(key, n):
        out.append(jax.random.normal(k, shape))
    return out


def exclusive_match_arms(key, shape, mode):
    # match arms are exclusive, like if/else: must not flag
    match mode:
        case "normal":
            return jax.random.normal(key, shape)
        case _:
            return jax.random.uniform(key, shape)


def exclusive_ternary(key, shape, training):
    # conditional-expression arms are exclusive too: must not flag
    return (jax.random.normal(key, shape) if training
            else jax.random.uniform(key, shape))


def ternary_then_reuse(key, shape, training):
    a = (jax.random.normal(key, shape) if training
         else jax.random.uniform(key, shape))
    b = jax.random.normal(key, shape)               # expect: G2
    return a + b


def match_then_reuse(key, shape, mode):
    match mode:
        case "normal":
            a = jax.random.normal(key, shape)
        case _:
            a = jax.random.uniform(key, shape)
    b = jax.random.bernoulli(key, 0.5, shape)       # expect: G2
    return a + b

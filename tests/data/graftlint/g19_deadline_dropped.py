# graftlint: scope=library
"""G19 fixture: public APIs that accept a deadline/timeout parameter,
never read it, and still (transitively) block — the signature promises
a bounded wait and delivers an unbounded one.  Parsed only, never
executed."""
import queue
import subprocess
import time

_q = queue.Queue(maxsize=4)


def bad_dropped_timeout(x, timeout_s):  # expect: G19
    _q.put(x, timeout=1.0)
    # fixed constants: the caller's budget never arrives at the wait
    return _q.get(timeout=5.0)


def bad_dropped_deadline_via_helper(cmd, deadline_ms):  # expect: G19
    # the blocking wait is a call-graph hop away: still this API's lie
    return _spin(cmd)


def _spin(cmd):
    return subprocess.run(cmd, timeout=30.0)


def good_threaded(x, timeout_s):
    return _q.get(timeout=timeout_s)


def good_deadline_loop(flag, deadline_s):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if flag():
            return True
        time.sleep(0.01)
    return False


def good_closure_read(x, timeout_s):
    # reads inside nested closures count as threading the budget
    def attempt():
        return _q.get(timeout=timeout_s)
    return attempt()


def good_no_blocking(config, timeout_s):
    config["timeout_s"] = timeout_s      # stored, and nothing blocks
    return config


def good_disable_twin(x, timeout_s):  # graftlint: disable=G19 twin
    return _q.get(timeout=5.0)

# graftlint: scope=library
"""G4 fixture: unguarded runtime device probe in library code (the
engine.waitall / runtime._detect / mesh default-path class). Parsed
only, never imported."""
import jax


def pick(n):
    return jax.devices()[:n]                        # expect: G4


def pick_local():
    return jax.local_devices()                      # expect: G4


def sanctioned():
    return jax.devices()  # graftlint: disable=G4 fixture twin


def guarded():
    # the pattern the rule points at — no direct probe here
    from mxnet_tpu.diagnostics import guard
    return guard.devices()

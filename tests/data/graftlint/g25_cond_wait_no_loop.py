# graftlint: scope=library
"""G25 fixture: ``Condition.wait()`` not re-checked in a ``while``
predicate loop — spurious wakeups and consumed notifies resume with
the predicate false.  ``wait_for`` embeds the loop and is the
recommended spelling; ``Event.wait`` is level-triggered and exempt.
Parsed only, never executed."""
import threading


class BadWaiter:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def put(self, x):
        with self._cv:
            self._items.append(x)
            self._cv.notify()

    def take(self):
        with self._cv:
            if not self._items:
                self._cv.wait(timeout=1.0)  # expect: G25
            return self._items.pop(0) if self._items else None


class GoodWaiter:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []
        self._halt = threading.Event()

    def put(self, x):
        with self._cv:
            self._items.append(x)
            self._cv.notify()

    def take_loop(self):
        with self._cv:
            while not self._items:
                self._cv.wait(timeout=1.0)
            return self._items.pop(0)

    def take_wait_for(self):
        with self._cv:
            self._cv.wait_for(lambda: len(self._items) > 0, timeout=1.0)
            return self._items.pop(0) if self._items else None

    def event_wait_is_exempt(self):
        # level-triggered: no predicate loop required
        return self._halt.wait(timeout=1.0)


class DisabledTwin:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def take(self):
        with self._cv:
            if not self._items:
                # graftlint: disable=G25 single waiter, timeout re-derives
                self._cv.wait(timeout=1.0)
            return self._items.pop(0) if self._items else None

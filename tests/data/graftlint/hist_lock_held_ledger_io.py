# graftlint: scope=library
"""Historical fixture — the PR-9 router, PRE-fix: the placement
decision read the heartbeat ledger (one beacon file per replica) while
holding the router lock, so one slow shared-filesystem read stalled
every router thread behind the front door.  The shipped fix hoisted
``pool.view()`` out of the critical section guarded only by a code
comment; G15's interprocedural reach now enforces it (the I/O sits two
call edges below the ``with``).  Parsed only, never executed."""
import json
import os
import threading


class PreFixRouter:
    def __init__(self, hb_dir):
        self._lock = threading.Lock()
        self.hb_dir = hb_dir

    def _read_beacon(self, rid):
        path = os.path.join(self.hb_dir, f"replica-{rid}.json")
        with open(path, encoding="utf-8") as f:
            return json.load(f)

    def _view(self):
        out = []
        for name in os.listdir(self.hb_dir):
            out.append(self._read_beacon(name.split("-", 1)[1]))
        return out

    def pick(self, exclude):
        with self._lock:
            candidates = [s for s in self._view()  # expect: G15
                          if s["id"] not in exclude]
        return min(candidates, key=lambda s: s["queue_depth"],
                   default=None)

# graftlint: scope=library
# graftlint: scope=training
"""G9 fixture: per-step host-synced finiteness checks — the class the
fused guard replaced (gluon/utils.py's old per-array asscalar() loop,
amp's per-step has_overflow pull). Parsed only, never executed."""
import jax.numpy as jnp
import numpy as np

from mxnet_tpu.guardrails import fused


def old_clip_global_norm_shape(grads, max_norm):
    total = 0.0
    for g in grads:
        grad_sq = (g * g).sum()
        total += grad_sq.asscalar()  # expect: G9
    norm = float(np.sqrt(total))
    if not np.isfinite(norm):  # expect: G9
        return norm
    return max_norm / norm


def old_has_overflow_shape(grads):
    ok = None
    for g in grads:
        fin = jnp.all(jnp.isfinite(g))
        ok = fin if ok is None else jnp.logical_and(ok, fin)
    return not bool(ok)  # expect: G9


def per_step_host_pulls(grad_total, loss_arr):
    overflow = float(grad_total)  # expect: G9
    bad = np.isnan(loss_arr)  # expect: G9
    per_grad_val = grad_total.item()  # expect: G9
    return overflow, bad, per_grad_val


def fused_guard_is_clean(grads, loss):
    # device-side: the flag/norm stay in-program, selection is data flow
    finite, gnorm = fused.guard_stats(grads, loss)
    scaled = [jnp.where(finite, g, jnp.zeros_like(g)) for g in grads]
    device_fin = jnp.isfinite(gnorm)          # no host pull: silent
    return scaled, device_fin


def sanctioned_fetch_is_clean(finite, gnorm):
    # the ONE sanctioned chokepoint: a single fetch of step outputs
    ok, gn = fused.host_fetch(finite, gnorm)
    norm_f = float(fused.host_fetch(gnorm)[0])
    return ok, gn, norm_f


def fetched_results_are_blessed(finite, gnorm_dev):
    # the rule's own recommended two-statement shape: host_fetch results
    # are host values — checking/converting them later costs no sync
    ok_flag, norm = fused.host_fetch(finite, gnorm_dev)
    if not np.isfinite(norm):                 # blessed: silent
        return float(norm), bool(ok_flag)     # blessed: silent
    still_bad = np.isfinite(gnorm_dev)  # expect: G9
    return still_bad


def tuple_unpack_taints_elementwise(g, num_steps):
    # only `flag` is tainted by the unpacking — `count` rides along in
    # the same Assign and must NOT be flagged when host-read later
    flag, count = jnp.isfinite(g).all(), num_steps
    steps_done = int(count)                   # benign: no G9
    overflowed = not bool(flag)  # expect: G9
    return steps_done, overflowed


def suppressed(loss_val):
    # value was already fetched once at episode end, not per step
    return np.isfinite(loss_val)  # graftlint: disable=G9 episode-end check

# graftlint: scope=library
"""G13 fixture: unbounded while-True poll loops (time.sleep with no
deadline/budget check inside the loop) — the router/breaker/drain
wait-loop hazard class.  Parsed only, never executed."""
import time
from time import sleep


def bad_poll_forever(flag):
    while True:  # expect: G13
        if flag():
            break
        time.sleep(0.05)


def bad_while_one(q):
    while 1:  # expect: G13
        sleep(0.1)
        if q.empty():
            break


def bad_deadline_outside_loop(flag):
    # the deadline EXISTS but the loop never checks it: still unbounded
    deadline = time.monotonic() + 5.0
    _stamp(deadline)
    while True:  # expect: G13
        if flag():
            break
        time.sleep(0.05)


def good_clock_compare_in_loop(flag):
    deadline = time.monotonic() + 5.0
    while True:
        if flag():
            return True
        if time.monotonic() > deadline:
            raise TimeoutError("poll budget exhausted")
        time.sleep(0.05)


def good_elapsed_compare(flag):
    t0 = time.monotonic()
    while True:
        if flag():
            return True
        if time.monotonic() - t0 > 5.0:
            return False
        time.sleep(0.05)


def good_deadline_names_only(flag):
    deadline = time.monotonic() + 5.0
    while True:
        now = time.monotonic()
        if now > deadline:
            return False
        if flag():
            return True
        time.sleep(0.05)


def good_bounded_condition(flag):
    # not a while-True: the loop condition itself is the budget
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if flag():
            return True
        time.sleep(0.05)
    return False


def good_no_sleep(q):
    # event-driven consumption with bounded gets is not a poll loop
    while True:
        item = q.get(timeout=1.0)
        if item is None:
            break


def good_nested_function_owns_its_sleep(flag):
    # the sleep lives in a nested function with its own budget story
    def poll_once():
        time.sleep(0.05)
        return flag()

    while True:
        if poll_once():
            break
        if time.monotonic() > _deadline():
            break


def suppressed(flag):
    while True:  # graftlint: disable=G13 fixture twin
        if flag():
            break
        time.sleep(0.05)


def _stamp(ts):
    return ts


def _deadline():
    return 0.0

# graftlint: scope=library
"""G7 fixture: non-atomic durable writes (torn-checkpoint class —
docs/checkpointing.md). A direct ``open(path, "wb")`` on a .params/
.json-style artifact, or a bare-path write inside a save/checkpoint/
export/dump-named function, must route through
``resilience.atomic.atomic_write``. Parsed only, never executed."""


def save_weights(path, blob):
    with open(path, "wb") as f:  # expect: G7
        f.write(blob)


def write_meta(prefix, text):
    # suffix evidence inside an f-string constant
    with open(f"{prefix}-manifest.json", "w") as f:  # expect: G7
        f.write(text)


def dump_profile(path, text):
    f = open(path, mode="w")  # expect: G7
    f.write(text)
    f.close()


def save_suppressed(path, blob):
    # staging path: the caller renames it into place
    with open(path, "wb") as f:  # graftlint: disable=G7 staged by caller
        f.write(blob)


def append_log(path, text):
    # append mode is not a durable-artifact rewrite: silent
    with open(path, "a") as f:
        f.write(text)


def rotate_scratch(path, text):
    # bare path in a non-save-named function, no suffix evidence: silent
    with open(path, "w") as f:
        f.write(text)


def load_params(path):
    # read mode: silent
    with open("model.params", "rb") as f:
        return f.read()


def save_atomic(path, blob):
    from mxnet_tpu.resilience.atomic import atomic_write
    with atomic_write(path, "wb") as f:  # sanctioned path: silent
        f.write(blob)

"""G6 fixture: silent exception swallow on a device/runtime path (the
engine.waitall defect: a dead barrier that vanished without a trace).
Parsed only, never imported."""
import jax


def swallow(x):
    try:
        jax.block_until_ready(x)
    except Exception:                               # expect: G6
        pass
    return x


def swallow_bare(x):
    try:
        jax.device_put(0)
    except:                                         # expect: G6
        pass


def swallow_tuple(x):
    try:
        jax.device_put(0)
    except (Exception, ValueError):                 # expect: G6
        pass


def journaled(x, journal):
    # the sanctioned shape: narrow catch + breadcrumb
    try:
        jax.block_until_ready(x)
    except RuntimeError as exc:
        journal.event("sync_failed", detail=str(exc)[:200])
    return x


def host_only():
    # no backend touch in the try: broad-swallow is W-territory, not G6
    try:
        return int("nope")
    except Exception:
        pass


def device_only_in_sibling_handler(path):
    # the PROTECTED code touches no device; the jax call lives in a
    # sibling handler — must not flag
    try:
        return open(path).read()
    except OSError:
        jax.debug.print("read failed")
    except Exception:
        pass


def suppressed(x):
    try:
        jax.block_until_ready(x)
    except Exception:  # graftlint: disable=G6 fixture twin
        pass
    return x

# graftlint: scope=library
"""G23 fixture: two sites protect the SAME attribute with DISJOINT
locks — each site is individually "locked" but no common lock orders
the accesses, so they interleave exactly as if unlocked (the PR-11
``Heartbeat.beat()`` stale-overwrite class).  Parsed only, never
executed."""
import threading


class BadSplitLocks:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._doc = {"seq": 0}
        self._stop = threading.Event()
        self._daemon = None

    def start(self):
        self._daemon = threading.Thread(target=self._run, daemon=True)
        self._daemon.start()

    def _run(self):
        while not self._stop.wait(0.01):
            with self._io_lock:
                self._doc["staged"] = True

    def publish(self, doc):
        with self._state_lock:
            self._doc = dict(doc)  # expect: G23


class GoodOneLock:
    """Same split between daemon and caller, ONE lock: silent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._doc = {"seq": 0}
        self._stop = threading.Event()
        self._daemon = None

    def start(self):
        self._daemon = threading.Thread(target=self._run, daemon=True)
        self._daemon.start()

    def _run(self):
        while not self._stop.wait(0.01):
            with self._lock:
                self._doc["staged"] = True

    def publish(self, doc):
        with self._lock:
            self._doc = dict(doc)


class DisabledTwin:
    """The violation with a reasoned suppression: stays silent."""

    def __init__(self):
        self._state_lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._doc = {"seq": 0}
        self._stop = threading.Event()
        self._daemon = None

    def start(self):
        self._daemon = threading.Thread(target=self._run, daemon=True)
        self._daemon.start()

    def _run(self):
        while not self._stop.wait(0.01):
            with self._io_lock:
                self._doc["staged"] = True

    def publish(self, doc):
        with self._state_lock:
            # graftlint: disable=G23 doc swap is an atomic ref replace
            self._doc = dict(doc)

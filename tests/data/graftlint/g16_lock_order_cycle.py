# graftlint: scope=library
"""G16 fixture: two locks acquired in opposite orders in one module —
nested ``with`` on one path, a call-under-lock into a lock-taking
helper on the other.  Two threads each holding their first lock
deadlock with no timeout.  Parsed only, never executed."""
import threading


class BadCycle:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._io_lock = threading.Lock()

    def path_one(self):
        with self._state_lock:
            with self._io_lock:  # expect: G16
                return 1

    def _take_state(self):
        with self._state_lock:
            return 2

    def path_two(self):
        # the inverse order arrives INTERPROCEDURALLY: io_lock held,
        # then a helper that takes state_lock
        with self._io_lock:
            return self._take_state()


class GoodOrder:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._io_lock = threading.Lock()

    def one(self):
        with self._state_lock:
            with self._io_lock:
                return 1

    def two(self):
        # same global order everywhere: no cycle
        with self._state_lock:
            with self._io_lock:
                return 2

    def reentrant(self):
        # same-lock nesting (RLock style) is not a cycle
        with self._state_lock:
            with self._state_lock:
                return 3


class GoodDisableTwin:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._io_lock = threading.Lock()

    def path_one(self):
        with self._state_lock:
            # graftlint: disable=G16 fixture twin: justified exception
            with self._io_lock:
                return 1

    def path_two(self):
        with self._io_lock:
            with self._state_lock:
                return 2

# graftlint: scope=library
"""G15 fixture: blocking operations reached while holding a lock —
directly, and transitively through same-module helper chains (the
summary engine's reach set).  Parsed only, never executed."""
import json
import queue
import threading
import time


class BadDirect:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue(maxsize=8)

    def bad_sleep_under_lock(self):
        with self._lock:
            time.sleep(0.1)  # expect: G15

    def bad_read_under_lock(self, path):
        with self._lock:
            with open(path, encoding="utf-8") as f:  # expect: G15
                return f.read()

    def bad_deadlined_wait_under_lock(self):
        # a timeout does not excuse the wait: every peer stalls on the
        # lock for the full budget
        with self._lock:
            return self._q.get(timeout=1.0)  # expect: G15


class BadTransitive:
    def __init__(self):
        self._lock = threading.RLock()

    def _load(self, path):
        with open(path, encoding="utf-8") as f:
            return json.load(f)

    def _hop(self, path):
        # one more hop: the reach set must cross TWO call edges
        return self._load(path)

    def bad_reaches_file_io(self, path):
        with self._lock:
            return self._hop(path)  # expect: G15


class GoodShapes:
    def __init__(self):
        self._lock = threading.Lock()
        self._staged = None

    def good_mutate_then_read(self, path):
        # the fixed shape: mutate under the lock, do the I/O after
        with self._lock:
            doc = dict(self._staged or ())
        with open(path, encoding="utf-8") as f:
            return doc, f.read()

    def good_disable_twin(self):
        with self._lock:
            # init-once-style sanctioned exception
            # graftlint: disable=G15 fixture twin: justified exception
            time.sleep(0.01)

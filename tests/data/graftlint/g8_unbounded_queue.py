# graftlint: scope=library
"""G8 fixture: unbounded queues and undeadlined get/join (the in-process
rc:124 class — docs/serving.md admission contract). Parsed only, never
executed."""
import queue
import threading
from queue import Queue


def make_unbounded():
    q = queue.Queue()  # expect: G8
    lifo = queue.LifoQueue(0)  # expect: G8
    pri = queue.PriorityQueue(maxsize=-1)  # expect: G8
    simple = queue.SimpleQueue()  # expect: G8
    aliased = Queue()  # expect: G8
    return q, lifo, pri, simple, aliased


def make_bounded(depth):
    ok1 = queue.Queue(maxsize=8)
    ok2 = queue.Queue(depth)          # non-constant: trusted
    ok3 = queue.PriorityQueue(maxsize=depth)
    return ok1, ok2, ok3


def blocking_consumer():
    q = queue.Queue(maxsize=4)
    q.get()  # expect: G8
    q.join()  # expect: G8
    t = threading.Thread(target=blocking_consumer)
    t.join()  # expect: G8
    return q, t


def bounded_consumer():
    q = queue.Queue(maxsize=4)
    q.get(timeout=1.0)                # deadlined: silent
    q.get(True, 5)                    # positional timeout: silent
    q.get(block=False)                # non-blocking: silent
    q.get_nowait()                    # non-blocking: silent
    t = threading.Thread(target=bounded_consumer)
    t.join(timeout=5)                 # deadlined: silent
    t.join(5)                         # positional deadline: silent
    return q, t


class Holder:
    def __init__(self):
        self._q = queue.Queue()  # expect: G8
        self._t = threading.Thread(target=self.drain)

    def drain(self):
        self._q.get()  # expect: G8
        self._t.join()  # expect: G8

    def drain_bounded(self):
        self._q.get(timeout=0.5)
        self._t.join(timeout=0.5)


def not_a_queue(mapping, other):
    mapping.get("key")                # dict.get: silent (untracked recv)
    other.join()                      # untracked receiver: silent


def suppressed():
    # staging queue is drained synchronously right below
    q = queue.Queue()  # graftlint: disable=G8 drained before return
    q.get()  # graftlint: disable=G8 producer completed above
    return q

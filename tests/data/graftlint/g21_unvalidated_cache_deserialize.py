# graftlint: scope=library
"""G21 fixture: unvalidated cache deserialize (read path without a
CRC/version-envelope check).  Lines marked BAD must be flagged; GOOD
lines must not.  The disable-twin documents the suppression syntax."""
import pickle
import zlib

from jax.experimental import serialize_executable


def bad_pickle_read(path):
    with open(path, "rb") as f:
        return pickle.load(f)  # expect: G21


def bad_executable_read(path, in_tree, out_tree):
    with open(path, "rb") as f:
        payload = f.read()
    return serialize_executable.deserialize_and_load(  # expect: G21
        payload, in_tree, out_tree)


def bad_unpickler_read(path):
    f = open(path, "rb")
    return pickle.Unpickler(f).load()  # expect: G21


def good_crc_checked_read(path, expect_crc):
    with open(path, "rb") as f:
        payload = f.read()
    if zlib.crc32(payload) != expect_crc:           # GOOD: CRC evidence
        raise ValueError("torn cache entry")
    return pickle.loads(payload)


def good_envelope_checked_read(path, current_envelope):
    with open(path, "rb") as f:
        blob = f.read()
    envelope, body = blob[:64], blob[64:]           # GOOD: envelope token
    if envelope != current_envelope:
        raise ValueError("stale toolchain")
    return pickle.loads(body)


def good_caller_supplied(blob):
    # GOOD: no file read here — whoever pulled these bytes off disk
    # owns the validation (the aotcache.load -> from_serialized split)
    return pickle.loads(blob)


def disable_twin_read(path):
    with open(path, "rb") as f:
        # the entry below is length-framed by a checked container
        return pickle.load(f)  # graftlint: disable=G21 container validated upstream

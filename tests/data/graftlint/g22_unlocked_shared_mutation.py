# graftlint: scope=library
"""G22 fixture: a class attribute mutated with NO lock on a
thread-shared path while other sites of the same attribute take a lock
for it — the Eraser empty-intersection signal.  The worker thread is
the escape root (``Thread(target=self._run)``); the snapshot method's
locked read proves the author considers the field shared.  Parsed
only, never executed."""
import threading


class BadCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats = {"served": 0}
        self._stop = threading.Event()
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while not self._stop.wait(0.01):
            self._stats["served"] += 1  # expect: G22

    def snapshot(self):
        with self._lock:
            return dict(self._stats)


class GoodCounter:
    """Same shape, the same lock at every site: silent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats = {"served": 0}
        self._stop = threading.Event()
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while not self._stop.wait(0.01):
            with self._lock:
                self._stats["served"] += 1

    def snapshot(self):
        with self._lock:
            return dict(self._stats)


class GoodHelperUnderEntryLock:
    """The bare-looking write lives in a private helper only ever
    called under the lock — the entry-lock analysis credits it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats = {"served": 0}
        self._stop = threading.Event()
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _bump(self):
        self._stats["served"] += 1      # entry lock: always under _lock

    def _run(self):
        while not self._stop.wait(0.01):
            with self._lock:
                self._bump()

    def snapshot(self):
        with self._lock:
            return dict(self._stats)


class DisabledTwin:
    """The violation with a reasoned suppression: stays silent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats = {"served": 0}
        self._stop = threading.Event()
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while not self._stop.wait(0.01):
            # graftlint: disable=G22 single-writer: only this thread mutates
            self._stats["served"] += 1

    def snapshot(self):
        with self._lock:
            return dict(self._stats)

# graftlint: scope=library
"""G17 fixture: explicit ``.acquire()`` with no exception-safe release
— straight-line release only (the first raise in between latches the
slot forever), vs the finally / finally-called-helper shapes that pass.
Parsed only, never executed."""
import threading


class BadLatch:
    def __init__(self):
        self._slot_sem = threading.BoundedSemaphore(1)
        self._lock = threading.Lock()

    def bad_straight_line(self, work):
        self._slot_sem.acquire()  # expect: G17
        result = work()           # a raise here latches the slot
        self._slot_sem.release()
        return result

    def bad_no_release_at_all(self):
        self._lock.acquire()  # expect: G17
        return True


class GoodShapes:
    def __init__(self):
        self._slot_sem = threading.BoundedSemaphore(1)

    def good_finally(self, work):
        self._slot_sem.acquire()
        try:
            return work()
        finally:
            self._slot_sem.release()

    def _cleanup(self):
        self._slot_sem.release()

    def good_helper_release(self, work):
        # the release lives in a helper the finally always calls — the
        # summary engine's transitive release set must see it
        self._slot_sem.acquire()
        try:
            return work()
        finally:
            self._cleanup()

    def good_with_statement(self, work):
        with self._slot_sem:
            return work()

    def good_disable_twin(self, work):
        # ownership handoff: another thread releases by design
        # graftlint: disable=G17 fixture twin: justified exception
        self._slot_sem.acquire()
        return work()

# graftlint: scope=library
"""G11 fixture: wall-clock durations (time.time() subtraction) in
library code — NTP steps make them go negative.  Parsed only, never
executed."""
import time


def bad_direct(t0):
    return time.time() - t0  # expect: G11


def bad_tainted_name():
    start = time.time()
    _work()
    return time.time() - start  # expect: G11


def bad_tainted_right_operand(now_mono):
    begin = time.time()
    _work()
    return now_mono - begin  # expect: G11


def good_monotonic():
    t0 = time.monotonic()
    _work()
    return time.monotonic() - t0


def good_perf_counter():
    t0 = time.perf_counter()
    _work()
    return time.perf_counter() - t0


def good_timestamp_only():
    # wall clock as a timestamp (no subtraction) is exactly what
    # time.time() is for
    return {"ts": round(time.time(), 3)}


def good_deadline_arithmetic():
    # addition/comparison is not a duration
    deadline = time.time() + 5.0
    while time.time() < deadline:
        _work()


def good_rebound_to_monotonic():
    # a wall-clock name REASSIGNED from a monotonic source is clean —
    # the taint follows line order, not the whole scope
    t = time.time()          # timestamp, used as-is
    _stamp(t)
    t = time.monotonic()
    _work()
    return time.monotonic() - t


def _stamp(ts):
    return ts


def suppressed(t0):
    return time.time() - t0  # graftlint: disable=G11 fixture twin


def _work():
    pass

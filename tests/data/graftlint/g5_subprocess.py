"""G5 fixture: blocking subprocess calls without a deadline (the PR-1
lesson: an undeadlined child that dials a wedged backend is an
information-free rc:124). Parsed only, never executed."""
import subprocess
import sys


def undeadlined(cmd):
    return subprocess.run(cmd, capture_output=True)  # expect: G5


def undeadlined_output():
    return subprocess.check_output([sys.executable, "-V"])  # expect: G5


def deadlined(cmd):
    return subprocess.run(cmd, capture_output=True, timeout=60)


def forwarded(cmd, **kw):
    # timeout may ride in **kw — unknowable statically, must not flag
    return subprocess.run(cmd, **kw)


def suppressed(cmd):
    return subprocess.call(cmd)  # graftlint: disable=G5 fixture twin


def suppressed_multiline(cmd):
    # disable on the CLOSING line covers the whole statement
    return subprocess.run(
        cmd,
        capture_output=True)  # graftlint: disable=G5 fixture twin

"""G1 fixture: with ``from __future__ import annotations`` (the repo's
house style) annotations are strings — they never evaluate, so a dial
in an annotation must NOT flag, while real module-scope dials still
do. Parsed only, never imported."""
from __future__ import annotations

import jax

ANNOTATED: jax.devices() = None
DIAL = jax.devices()                                # expect: G1


def f(x: jax.device_count() = 1) -> jax.devices():
    return x

"""G3 fixture: host synchronization inside traced code (jit-decorated
functions and lax.scan bodies). Parsed only, never imported."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def jitted_item(x):
    return x.sum().item()                           # expect: G3


@partial(jax.jit, static_argnums=0)
def jitted_float(n, x):
    return float(x[0]) + n                          # expect: G3


def scan_body(carry, x):
    host = np.asarray(x)                            # expect: G3
    return carry + x, host


def run_scan(xs):
    return jax.lax.scan(scan_body, 0.0, xs)


@jax.jit
def jitted_twin(x):
    return x.sum().item()  # graftlint: disable=G3 fixture twin


@jax.jit
def shape_metadata(x):
    # .shape/.ndim/len() are static Python values under trace:
    # int()/float() over them is trace-safe, must not flag
    n = int(x.shape[0])
    d = float(x.ndim)
    m = int(len(x))
    return x.reshape(n, -1) * d * m


@jax.jit
def with_host_callback(x):
    # a nested def is its own (host) scope — pure_callback helpers
    # legitimately sync and must not flag
    def host_fn(v):
        return np.asarray(v).item()

    return jax.pure_callback(host_fn, jax.ShapeDtypeStruct((), x.dtype), x)


def eager_host(x):
    # not traced: float()/item() here are fine
    return float(x.sum().item())


def eager_asarray(x):
    return jnp.asarray(np.asarray(x))

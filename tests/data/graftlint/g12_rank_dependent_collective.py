# graftlint: scope=library
"""G12 fixture: host collectives entered under rank-local conditions —
the cross-rank deadlock class (docs/elastic.md). Parsed only, never
executed."""
import jax
from jax.experimental import multihost_utils


def bad_direct_rank_guard(x):
    if jax.process_index() == 0:
        multihost_utils.sync_global_devices("tag")  # expect: G12
    return x


def bad_tainted_rank_name(x):
    rank = jax.process_index()
    if rank == 0:
        return multihost_utils.process_allgather(x)  # expect: G12
    return x


def bad_else_branch_is_also_rank_dependent(x):
    if jax.process_index() == 0:
        y = x
    else:
        y = multihost_utils.broadcast_one_to_all(x)  # expect: G12
    return y


def bad_derived_flag(x):
    is_main = jax.process_index() == 0
    while is_main:
        multihost_utils.sync_global_devices("spin")  # expect: G12
    return x


def bad_short_circuit(x):
    return jax.process_index() == 0 and \
        multihost_utils.process_allgather(x)  # expect: G12


def bad_conditional_expression(x):
    return (multihost_utils.process_allgather(x)  # expect: G12
            if jax.process_index() == 0 else x)


def good_world_size_guard(x):
    # process_count is the same on every rank: rank-uniform, fine
    if jax.process_count() == 1:
        return x
    return multihost_utils.process_allgather(x)


def good_unconditional_with_rank_local_work(x):
    # rank-local work under the guard, collective OUTSIDE it — the
    # commit-protocol shape (parallel/_ckpt.py)
    if jax.process_index() == 0:
        x = x + 1
    multihost_utils.sync_global_devices("staged")
    return x


def good_decide_once_then_broadcast(step):
    # the sanctioned pattern: one rank decides, everyone broadcasts
    found = -1
    if jax.process_index() == 0:
        found = int(step)
    return int(multihost_utils.broadcast_one_to_all(found))


def suppressed(x):
    if jax.process_index() == 0:
        multihost_utils.sync_global_devices("t")  # graftlint: disable=G12 fixture twin
    return x

"""G1 fixture: module-scope backend dial — the exact round-4/5 wedge
class (``_rng.py`` created a PRNGKey at import). Never imported by
tests; only parsed. Excluded from the repo scan via tests/data."""
import jax
import jax.numpy as jnp

DEVICES = jax.devices()                             # expect: G1
KEY = jax.random.PRNGKey(0)                         # expect: G1
SCALE = jnp.ones(8)                                 # expect: G1
TWIN = jax.devices()   # graftlint: disable=G1 fixture twin, must not flag


class Config:
    # class bodies execute at import time too
    n_dev = jax.device_count()                      # expect: G1


def runtime_dial(n=3):
    # inside a function body: NOT import-time, must not flag
    return jax.devices()[:n]


def default_arg_dial(devs=jax.devices()):           # expect: G1
    # default argument values evaluate at import time
    return devs


# lambda defaults evaluate when the lambda expression is built — import
# time here (the body, by contrast, is deferred)
probe = lambda devs=jax.devices(): devs             # expect: G1
deferred = lambda: jax.devices()


# a genexp body is deferred until iteration — but its FIRST iterable
# evaluates eagerly when the expression is built
LAZY = (d.platform for d in jax.devices())          # expect: G1
DEFERRED = (jax.devices() for _ in range(2))


def annotated(n: jax.device_count() = 1):           # expect: G1
    # without `from __future__ import annotations`, parameter
    # annotations evaluate at def time (= import time)
    return n


if __name__ == "__main__":
    # script body, never runs at import: must not flag
    print(jax.devices())
else:
    IMPORTED_DIAL = jax.devices()                   # expect: G1

# graftlint: scope=library
"""G26 fixture: swallowed durable-write error — a broad except around
a commit-point call chain whose handler neither re-raises nor
journals.  Lines marked BAD must be flagged; GOOD lines must not.
The disable-twin documents the suppression syntax."""
import json
import os

from mxnet_tpu.diagnostics.journal import get_journal
from mxnet_tpu.resilience.atomic import atomic_write


def _stage_then_replace(path, doc):
    tmp = path + ".tmp.1"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)       # the commit point, one helper deep


def bad_bare_pass(path, doc):
    try:
        with atomic_write(path, "w") as f:
            json.dump(doc, f)
    except Exception:  # expect: G26
        pass


def bad_helper_chain(path, doc):
    try:
        _stage_then_replace(path, doc)
    except:  # expect: G26
        return None
    return path


def good_typed_handler(path, doc):
    try:
        _stage_then_replace(path, doc)
    except OSError:        # GOOD: typed — the visible failure contract
        return None
    return path


def good_journaled(path, doc):
    try:
        _stage_then_replace(path, doc)
    except Exception as exc:    # GOOD: the failure is journaled
        get_journal().event("write_failed", path=path, error=repr(exc))
        return None
    return path


def good_reraise(path, doc):
    try:
        _stage_then_replace(path, doc)
    except Exception as exc:    # GOOD: annotate-and-reraise
        doc["error"] = repr(exc)
        raise


def good_no_durable_write(path):
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except Exception:     # GOOD: a read path — no commit point inside
        return None


def disable_twin(path, doc):
    try:
        _stage_then_replace(path, doc)
    except Exception:  # graftlint: disable=G26 caller checks the returned marker
        return None
    return path

# graftlint: scope=library
"""Historical fixture — the PR-9 half-open probe slot, PRE-fix: the
breaker admits exactly ONE probe request; the slot was claimed at
placement and released only on the success path, so the first
exception between claim and release latched it forever — the replica
silently never re-admitted until restart (found by chaos archaeology,
fixed by hand in PR 10's hedge-path sweep).  The shipped code models
the slot as a boolean under the router lock; this fixture models it as
the semaphore it behaves as, the shape G17 catches statically.
Parsed only, never executed."""
import threading


class PreFixBreaker:
    def __init__(self):
        self._probe_sem = threading.BoundedSemaphore(1)

    def probe(self, replica, request):
        self._probe_sem.acquire()  # expect: G17
        value = replica.predict(request)   # raises on a failed probe...
        self._probe_sem.release()          # ...and the slot never frees
        return value

# graftlint: scope=library
"""G14 fixture: dict/set class attributes indexed by externally-supplied
keys (request ids, tenant names, step numbers, file names) with inserts
in public methods but no eviction/cap anywhere in the class — the
long-lived-server memory-growth hazard class.  Parsed only, never
executed."""
from collections import OrderedDict


class BadSessionTable:
    """Grows one entry per novel request/tenant forever."""

    def __init__(self):
        self._by_request = {}
        self._seen_steps = set()
        self._tenant_rows = OrderedDict()

    def admit(self, request_id, doc):
        self._by_request[request_id] = doc  # expect: G14

    def remember(self, step):
        self._seen_steps.add(step)  # expect: G14

    def observe(self, tenant):
        self._tenant_rows.setdefault(tenant, 0)  # expect: G14


class BadFileScanner:
    """The churning-commit-root shape: keys are file names scanned off
    disk, remembered without bound."""

    def __init__(self):
        self._bad_files = set()

    def scan(self, names):
        for fname in names:
            self._bad_files.add(fname)  # expect: G14


class GoodLruCapped:
    """Same insert, but the class caps the container (len compare +
    popitem) — the ParamStore bad-step LRU shape."""

    def __init__(self, cap=64):
        self._by_request = OrderedDict()
        self._cap = cap

    def admit(self, request_id, doc):
        self._by_request[request_id] = doc
        while len(self._by_request) > self._cap:
            self._by_request.popitem(last=False)


class GoodEvictsOnCompletion:
    """The container has a pop path: entries leave when work finishes."""

    def __init__(self):
        self._inflight = {}

    def admit(self, request_id, doc):
        self._inflight[request_id] = doc

    def complete(self, request_id):
        return self._inflight.pop(request_id, None)


class GoodLifecycleReset:
    """Reassigned on a lifecycle path: bounded per run, not per key."""

    def __init__(self):
        self._by_request = {}

    def admit(self, request_id, doc):
        self._by_request[request_id] = doc

    def start_epoch(self):
        self._by_request = {}


class GoodPrivateInsertOnly:
    """Inserts only in private methods: the class's own callers own the
    key space (a construction-time registry), out of scope."""

    def __init__(self):
        self._by_request = {}

    def _admit(self, request_id, doc):
        self._by_request[request_id] = doc


class GoodOperatorKeys:
    """Key name outside the request-shaped vocabulary: an
    operator-bounded registry (models, modes, kernels)."""

    def __init__(self):
        self._by_mode = {}

    def register(self, mode, fn):
        self._by_mode[mode] = fn


class SuppressedTwin:
    """The disable-comment twin stays silent."""

    def __init__(self):
        self._by_request = {}

    def admit(self, request_id, doc):
        self._by_request[request_id] = doc  # graftlint: disable=G14 fixture twin

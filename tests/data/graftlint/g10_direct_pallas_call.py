# graftlint: scope=library
"""G10 fixture: direct pl.pallas_call outside mxnet_tpu/pallas/ — a raw
kernel that bypasses the registry's parity gate and journaled fallback
(docs/pallas.md). Parsed only, never executed."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import pallas_call as direct_call


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def unguarded_kernel(x):
    return pl.pallas_call(  # expect: G10
        _kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def unguarded_kernel_via_from_import(x):
    return direct_call(  # expect: G10
        _kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def disabled_twin(x):
    # interop shim pinned to a prebuilt upstream kernel, parity-tested
    # in its own suite
    return pl.pallas_call(  # graftlint: disable=G10 vetted interop shim
        _kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def registry_path_is_clean(x):
    # the sanctioned route: registered kernel + guarded dispatch
    from mxnet_tpu.pallas import dispatch
    return dispatch("conv_epilogue", x, jnp.ones((1, x.shape[1])),
                    jnp.zeros((1, x.shape[1])), None, act_type="relu")

# graftlint: scope=library
"""G24 fixture: a membership test over a shared dict/set guards a
mutation of the same attribute, but no single lock spans BOTH the
check and the act — between them a concurrent peer invalidates the
answer and two threads both mutate (TOCTOU; the latched half-open
probe class).  Parsed only, never executed."""
import threading


class BadCheckThenAct:
    def __init__(self):
        self._lock = threading.Lock()
        self._cache = {}
        self._stop = threading.Event()
        self._refresher = None

    def start(self):
        self._refresher = threading.Thread(target=self._refresh,
                                           daemon=True)
        self._refresher.start()

    def _refresh(self):
        while not self._stop.wait(0.01):
            self.ensure("hot")

    def ensure(self, key):
        if key not in self._cache:      # the answer goes stale here...
            with self._lock:
                self._cache[key] = object()  # expect: G24

    def get(self, key):
        with self._lock:
            return self._cache.get(key)


class GoodLockSpansBoth:
    """Check AND act under one critical section: silent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache = {}
        self._stop = threading.Event()
        self._refresher = None

    def start(self):
        self._refresher = threading.Thread(target=self._refresh,
                                           daemon=True)
        self._refresher.start()

    def _refresh(self):
        while not self._stop.wait(0.01):
            self.ensure("hot")

    def ensure(self, key):
        with self._lock:
            if key not in self._cache:
                self._cache[key] = object()

    def get(self, key):
        with self._lock:
            return self._cache.get(key)


class DisabledTwin:
    """The violation with a reasoned suppression: stays silent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache = {}
        self._stop = threading.Event()
        self._refresher = None

    def start(self):
        self._refresher = threading.Thread(target=self._refresh,
                                           daemon=True)
        self._refresher.start()

    def _refresh(self):
        while not self._stop.wait(0.01):
            self.ensure("hot")

    def ensure(self, key):
        if key not in self._cache:
            with self._lock:
                # graftlint: disable=G24 idempotent insert, losers overwrite
                self._cache[key] = object()

    def get(self, key):
        with self._lock:
            return self._cache.get(key)

# graftlint: scope=library
"""G20 fixture: ``start_span()`` with no exception-safe ``.end()`` —
the first raise loses the span (and its children) from the assembled
timeline — vs the with / finally / finally-called-helper / ownership-
transfer shapes that pass.  Parsed only, never executed."""
from mxnet_tpu.observability import trace


class BadSpans:
    def bad_straight_line(self, work):
        sp = trace.start_span("attempt")  # expect: G20
        result = work()           # a raise here leaks the span
        sp.end(status="ok")
        return result

    def bad_no_end_at_all(self, work):
        sp = trace.start_span("attempt")  # expect: G20
        sp.set_attrs(step=1)
        return work()

    def bad_try_except_no_finally(self, work):
        # the pre-fix router hedge-arm shape: ended on BOTH branches,
        # but an exception inside the except body (or one neither
        # branch catches) still leaks it — only finally is safe
        sp = trace.start_span("attempt")  # expect: G20
        try:
            out = work()
            sp.end(status="ok")
            return out
        except ValueError as e:
            sp.end(status=type(e).__name__)
            raise


class GoodShapes:
    def good_with(self, work):
        with trace.start_span("attempt") as sp:
            sp.set_attrs(phase="run")
            return work()

    def good_finally(self, work):
        sp = trace.start_span("attempt")
        try:
            return work()
        finally:
            sp.end()

    def _close(self, span, status="ok"):
        span.end(status=status)

    def good_helper_end(self, work):
        # the finally-called helper ends the span it is handed — the
        # param-position fixpoint must see it (the G17 helper shape)
        sp = trace.start_span("attempt")
        try:
            return work()
        finally:
            self._close(sp)

    def good_ownership_transfer(self, req):
        # stored on the request: whoever resolves the request ends it
        # (the serving_request cross-thread lifecycle) — not a leak
        req.trace = trace.start_span("serving_request")
        return req

    def good_returned(self):
        sp = trace.start_span("attempt")
        return sp                  # the caller owns the end now

    def good_disable_twin(self, registry, work):
        # handed to a registry another thread drains and ends
        # graftlint: disable=G20 fixture twin: justified exception
        sp = trace.start_span("attempt")
        return work()

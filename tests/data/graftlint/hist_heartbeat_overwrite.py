# graftlint: scope=library
"""Historical fixture — the PR-11 ``Heartbeat.beat()`` stale-overwrite,
PRE-fix: the beacon daemon staged the shared document under its own
I/O lock while ``beat()`` advanced the same document under the state
lock.  Each site was "locked", but with no common lock between them
the daemon's already-sampled (stale) document could land AFTER a
fresher ``beat()`` write and roll the published state backwards — the
inconsistent-lockset class G23 exists for.  Parsed only, never
executed."""
import threading


class PreFixHeartbeat:
    def __init__(self, interval_s=0.5):
        self._interval_s = interval_s
        self._state_lock = threading.Lock()   # beat()'s mutations
        self._io_lock = threading.Lock()      # the daemon's staging
        self._doc = {"seq": 0, "ready": False}
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self._interval_s):
            with self._io_lock:
                # sampled here, stale by the time a concurrent beat()
                # lands under the OTHER lock
                self._doc = dict(self._doc, staged=True)

    def beat(self, ready):
        with self._state_lock:
            self._doc["seq"] += 1  # expect: G23
            self._doc["ready"] = bool(ready)

"""NDArray basics (ref test: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, same


def test_creation():
    x = nd.zeros((2, 3))
    assert x.shape == (2, 3)
    assert x.dtype == np.float32
    assert x.ctx == mx.cpu(0)
    assert same(x, np.zeros((2, 3)))

    y = nd.ones((4,), dtype="int32")
    assert y.dtype == np.int32

    z = nd.full((2, 2), 7)
    assert z.asnumpy().ravel().tolist() == [7, 7, 7, 7]

    a = nd.arange(0, 10, 2)
    assert a.asnumpy().tolist() == [0, 2, 4, 6, 8]

    assert nd.eye(3).asnumpy()[1, 1] == 1.0
    assert nd.linspace(0, 1, 5).shape == (5,)


def test_array_dtype_defaults():
    assert nd.array([1, 2, 3]).dtype == np.float32
    # documented divergence: 64-bit ints downcast to 32-bit (TPU-native build)
    assert nd.array(np.array([1, 2, 3], dtype=np.int64)).dtype == np.int32
    assert nd.array(np.array([1, 2], dtype=np.int16)).dtype == np.int16
    assert nd.array(np.zeros((2, 2))).dtype == np.float32  # f64 -> f32


def test_arithmetic():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    y = nd.array([[10.0, 20.0], [30.0, 40.0]])
    assert_almost_equal(x + y, np.array([[11, 22], [33, 44]]))
    assert_almost_equal(x * 2, np.array([[2, 4], [6, 8]]))
    assert_almost_equal(2 - x, np.array([[1, 0], [-1, -2]]))
    assert_almost_equal(1.0 / x, 1.0 / x.asnumpy())
    assert_almost_equal(x ** 2, x.asnumpy() ** 2)
    assert_almost_equal(-x, -x.asnumpy())
    assert_almost_equal(abs(x - 2.5), np.abs(x.asnumpy() - 2.5))


def test_inplace_rebinds():
    x = nd.ones((3,))
    x += 1
    assert x.asnumpy().tolist() == [2, 2, 2]
    x *= 3
    assert x.asnumpy().tolist() == [6, 6, 6]


def test_comparison_ops():
    x = nd.array([1.0, 2.0, 3.0])
    assert (x > 2).asnumpy().tolist() == [0, 0, 1]
    assert (x == 2).asnumpy().tolist() == [0, 1, 0]
    assert (x <= 2).asnumpy().tolist() == [1, 1, 0]


def test_indexing():
    x = nd.array(np.arange(12).reshape(3, 4))
    assert x[1].shape == (4,)
    assert x[1, 2].asscalar() == 6
    assert x[0:2].shape == (2, 4)
    assert x[:, 1].asnumpy().tolist() == [1, 5, 9]
    x[0, 0] = 99
    assert x[0, 0].asscalar() == 99
    x[1] = 0
    assert x[1].asnumpy().tolist() == [0, 0, 0, 0]


def test_shape_methods():
    x = nd.array(np.arange(24).reshape(2, 3, 4))
    assert x.reshape(6, 4).shape == (6, 4)
    assert x.reshape((-1, 4)).shape == (6, 4)
    assert x.reshape(0, -1).shape == (2, 12)        # reference code 0 = copy
    assert x.transpose().shape == (4, 3, 2)
    assert x.T.shape == (4, 3, 2)
    assert x.swapaxes(0, 2).shape == (4, 3, 2)
    assert x.flatten().shape == (2, 12)
    assert x.expand_dims(0).shape == (1, 2, 3, 4)
    assert nd.moveaxis(x, 0, 2).shape == (3, 4, 2)


def test_scalar_conversions():
    x = nd.array([3.5])
    assert x.asscalar() == 3.5
    assert float(x) == 3.5
    assert int(nd.array([7])) == 7
    with pytest.raises(Exception):
        nd.zeros((2, 2)).asscalar()


def test_copy_and_context():
    x = nd.ones((2, 2))
    y = x.copy()
    y += 1
    assert x.asnumpy()[0, 0] == 1  # copy is independent
    z = x.as_in_context(mx.cpu(0))
    assert z is x                   # same-context no-op, like the reference
    w = nd.zeros((2, 2))
    x.copyto(w)
    assert same(w, x)


def test_astype():
    x = nd.array([1.5, 2.5])
    assert x.astype("int32").dtype == np.int32
    assert x.astype(np.float16).dtype == np.float16


def test_concat_stack_split():
    x = nd.ones((2, 3))
    y = nd.zeros((2, 3))
    c = nd.concat(x, y, dim=0)
    assert c.shape == (4, 3)
    s = nd.stack(x, y, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(nd.array(np.arange(12).reshape(2, 6)), 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 2)


def test_save_load_roundtrip(tmp_path):
    fname = str(tmp_path / "x.params")
    d = {"weight": nd.random.normal(shape=(3, 4)),
         "bias": nd.zeros((4,), dtype="float32")}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded) == {"weight", "bias"}
    assert_almost_equal(loaded["weight"], d["weight"])

    lst = [nd.ones((2,)), nd.arange(0, 3)]
    nd.save(fname, lst)
    back = nd.load(fname)
    assert len(back) == 2 and same(back[0], lst[0])


def test_wait_and_iter():
    x = nd.ones((4, 2))
    x.wait_to_read()
    nd.waitall()
    rows = list(x)
    assert len(rows) == 4 and rows[0].shape == (2,)


def test_dtype_bf16():
    x = nd.zeros((2, 2), dtype="bfloat16")
    assert "bfloat16" in str(x.dtype)
    y = (x + 1.5) * 2
    assert y.asnumpy().astype(np.float32)[0, 0] == 3.0


def test_randn_positional_shape():
    x = mx.random.randn(2, 3)
    assert x.shape == (2, 3)
    assert abs(float(x.asnumpy().mean())) < 3.0


def test_random_ctx_placement():
    x = nd.random.uniform(0, 1, shape=(2, 2), ctx=mx.cpu(0))
    assert x.ctx == mx.cpu(0)

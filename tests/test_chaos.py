"""The chaos campaign engine (mxnet_tpu/chaos/; docs/chaos.md): seeded
fault-schedule generation, the conductor's execute/judge/shrink loop,
``CHAOS_rNN.json`` artifacts, and the resource-exhaustion fault family.

Two tests run the conductor END TO END:

- ``test_pool_campaign_end_to_end`` — a seeded campaign composing all
  four fault classes against the live 3-replica pool scenario, every
  declared invariant evaluated, artifact written and report-readable;
- ``test_planted_invariant_shrinks_and_replays`` — a scenario with a
  deliberately unsatisfiable invariant: the campaign must FAIL, ddmin
  must shrink the schedule to a tiny reproducer, the artifact's seed
  must regenerate the exact schedule, and replaying the shrunk subset
  must still fail.

The rest is unit coverage: generator determinism + class composition,
ddmin 1-minimality and probe cap, artifact revisioning + schema
rejection, the doctor reporter, ENOSPC fail-fast + deduped journal
records, and journal drop-and-count under a dead sink.
"""
import errno
import json
import os
import time

import pytest

from mxnet_tpu.chaos import artifact as art
from mxnet_tpu.chaos import invariants as inv
from mxnet_tpu.chaos import report
from mxnet_tpu.chaos import scenarios as scen
from mxnet_tpu.chaos import schedule as sched
from mxnet_tpu.chaos.conductor import run_campaign
from mxnet_tpu.chaos.shrink import ddmin
from mxnet_tpu.diagnostics import journal
from mxnet_tpu.resilience import atomic, retry
from mxnet_tpu.testing import faults

POOL_SEED = 11          # verified green: every invariant passes


def _records(path, kind):
    return inv.journal_records(path, kind)


# -- registry ----------------------------------------------------------------

def test_registry_holds_the_five_drill_scenarios():
    got = set(scen.names())
    assert {"pool", "crash_matrix", "fleet", "deploy",
            "elastic"} <= got
    with pytest.raises(ValueError, match="unknown scenario"):
        scen.get("nope")


# -- schedule generation -----------------------------------------------------

def test_generate_is_deterministic_and_composes_all_classes():
    targets = scen.get("pool").targets
    a = sched.generate(17, targets, n_faults=4)
    b = sched.generate(17, targets, n_faults=4)
    assert a == b                       # the reproducer contract
    assert {s["cls"] for s in a} == set(sched.FAULT_CLASSES)
    for s in a:
        assert s["kind"] in sched.CATALOG
        assert s["at_s"] > 0
    c = sched.generate(18, targets, n_faults=4)
    assert c != a                       # the seed actually matters


def test_generate_respects_declared_classes():
    targets = scen.get("crash_matrix").targets      # no process/latency
    specs = sched.generate(3, targets, n_faults=4)
    assert {s["cls"] for s in specs} <= {"durability", "resource"}
    assert not any(s["kind"] == "kill" for s in specs)


def test_build_kill_spec_requires_a_kill_lever():
    spec = {"kind": "kill", "cls": "process", "at_s": 1.0,
            "target": "r0"}
    with pytest.raises(ValueError, match="no kill lever"):
        sched.build([spec], kill=None)
    fired = []
    built = sched.build([spec], kill=fired.append)
    assert not built.rules
    [(at_s, label, action)] = built.timed
    action()
    assert fired == ["r0"] and label == "kill:r0"


# -- ddmin -------------------------------------------------------------------

def test_ddmin_is_one_minimal():
    items = list(range(8))

    def still_fails(subset):
        return {2, 5} <= set(subset)    # the failure needs exactly two

    out = ddmin(items, still_fails)
    assert sorted(out) == [2, 5]


def test_ddmin_probe_cap_returns_a_valid_reproducer():
    items = list(range(8))

    def still_fails(subset):
        return {2, 5} <= set(subset)

    out = ddmin(items, still_fails, max_probes=1)
    assert still_fails(out)             # maybe not minimal, still fails


# -- artifacts ---------------------------------------------------------------

def _doc(**over):
    doc = {"kind": "chaos", "scenario": "pool", "seed": 7, "ok": True,
           "schedule": [], "verdicts": []}
    doc.update(over)
    return doc


def test_artifact_revisioning_and_roundtrip(tmp_path):
    d = str(tmp_path)
    p1 = art.write_artifact(d, _doc(seed=1))
    p2 = art.write_artifact(d, _doc(seed=2))
    assert os.path.basename(p1) == "CHAOS_r01.json"
    assert os.path.basename(p2) == "CHAOS_r02.json"
    assert art.latest_artifact(d) == p2
    assert art.read_artifact(p2)["seed"] == 2


def test_read_artifact_rejects_torn_and_foreign_files(tmp_path):
    bad = tmp_path / "CHAOS_r01.json"
    bad.write_text("{ torn")
    with pytest.raises(ValueError, match="not valid JSON"):
        art.read_artifact(str(bad))
    bad.write_text(json.dumps({"kind": "bench"}))
    with pytest.raises(ValueError, match="not a chaos artifact"):
        art.read_artifact(str(bad))
    doc = _doc()
    del doc["verdicts"]
    bad.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="missing 'verdicts'"):
        art.read_artifact(str(bad))


def test_report_digest_and_no_artifacts(tmp_path):
    d = str(tmp_path)
    rep = report.chaos_report(d)
    assert rep["ok"] is False and rep["error"] == "no_artifacts"
    art.write_artifact(d, _doc(seed=1))
    art.write_artifact(d, _doc(
        seed=2, ok=False, failed=["progress"],
        verdicts=[{"name": "progress", "ok": False, "detail": "x"}],
        schedule=[{"kind": "kill", "cls": "process", "at_s": 1.0}],
        shrunk=[{"kind": "kill", "cls": "process", "at_s": 1.0}]))
    rep = report.chaos_report(d)
    assert rep["ok"] and rep["campaigns"] == 2 and rep["failures"] == 1
    assert rep["last_failure"]["failed"] == ["progress"]
    assert rep["last_failure"]["shrunk_to"] == 1
    line = report.summarize(rep)
    assert "chaos: 2 campaign(s), 1 failed" in line
    assert "shrunk to 1 fault(s)" in line


# -- invariant evaluation ----------------------------------------------------

def test_evaluate_fails_loudly_on_unknown_or_crashing_invariant():
    [v] = inv.evaluate([("tpyo", {})], {})
    assert v["ok"] is False and v["detail"] == "unknown invariant"

    @inv.register("_chaos_test_boom")
    def _boom(obs):
        raise RuntimeError("no")

    try:
        [v] = inv.evaluate([("_chaos_test_boom", {})], {})
        assert v["ok"] is False and "evaluator crashed" in v["detail"]
    finally:
        inv.INVARIANTS.pop("_chaos_test_boom", None)


# -- resource-exhaustion fault family ----------------------------------------

def test_disk_full_fails_fast_old_preserved_no_litter(tmp_path):
    target = tmp_path / "state.json"
    target.write_text('{"v": "old"}')
    jpath = str(tmp_path / "journal.jsonl")
    journal.reset_journal(jpath)
    retry.reset_disk_full_notes()
    try:
        with faults.inject(faults.disk_full("replace", times=1)):
            with pytest.raises(faults.DiskFullError) as ei:
                with atomic.atomic_write(str(target), "w") as f:
                    f.write('{"v": "new"}')
        assert ei.value.errno == errno.ENOSPC
        # old bytes intact, no staged temp litter, ONE deduped record
        assert json.loads(target.read_text()) == {"v": "old"}
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]
        recs = _records(jpath, "disk_full")
        assert len(recs) == 1 and recs[0]["op"].startswith("replace")
    finally:
        journal.reset_journal("stderr")
        retry.reset_disk_full_notes()


def test_fd_exhaust_trips_open_with_emfile(tmp_path):
    target = str(tmp_path / "x.json")
    with faults.inject(faults.fd_exhaust("open", times=1)):
        with pytest.raises(faults.FdExhaustError) as ei:
            with atomic.atomic_write(target, "w") as f:
                f.write("{}")
    assert ei.value.errno == errno.EMFILE
    assert not os.listdir(tmp_path)     # nothing was ever staged


def test_disk_budget_draw_exhaust_heal():
    b = faults.DiskBudget(10)
    assert b.draw(4) is False and b.exhausted() is False
    assert b.draw(7) is True and b.exhausted() is True
    b.heal(100)
    assert b.exhausted() is False

    rule = faults.disk_budget(5)
    assert rule.matches("fsync", "p", None, None) is False
    assert rule.matches("write", "p", 0, 6) is True     # the exhausting draw
    for point in faults._BudgetRule._POINTS:
        assert rule.matches(point, "p", None, 0) is True
    assert rule.matches("publish", "p", None, None) is False
    rule.budget.heal(1 << 20)
    assert rule.matches("fsync", "p", None, None) is False


def test_partition_stalls_only_the_matched_peer():
    rule = faults.partition(peer="r1", stall_s=0.15, times=1)
    with faults.inject(rule):
        t0 = time.monotonic()
        atomic.trip("wire_send", "r2")          # other peer: no stall
        assert time.monotonic() - t0 < 0.1
        t0 = time.monotonic()
        atomic.trip("wire_send", "r1")
        assert time.monotonic() - t0 >= 0.15
        t0 = time.monotonic()
        atomic.trip("wire_send", "r1")          # window over (times=1)
        assert time.monotonic() - t0 < 0.1


# -- ENOSPC fail-fast + dedup (resilience.retry) -----------------------------

def test_retry_fails_fast_on_enospc_with_one_deduped_record(tmp_path):
    jpath = str(tmp_path / "journal.jsonl")
    journal.reset_journal(jpath)
    retry.reset_disk_full_notes()
    calls = []

    def full_disk():
        calls.append(1)
        raise OSError(errno.ENOSPC, "no space", str(tmp_path / "t"))

    try:
        with pytest.raises(OSError):
            retry.retry_call(full_disk, retries=3, base_s=0.001)
        assert len(calls) == 1          # no retry budget burned
        assert len(_records(jpath, "disk_full")) == 1
        with pytest.raises(OSError):    # same path: record deduped
            retry.retry_call(full_disk, retries=3, base_s=0.001)
        assert len(_records(jpath, "disk_full")) == 1
        retry.reset_disk_full_notes()   # space verified freed: re-arm
        with pytest.raises(OSError):
            retry.retry_call(full_disk, retries=3, base_s=0.001)
        assert len(_records(jpath, "disk_full")) == 2
    finally:
        journal.reset_journal("stderr")
        retry.reset_disk_full_notes()


def test_is_disk_full_classification():
    assert retry.is_disk_full(OSError(errno.ENOSPC, "x"))
    assert retry.is_disk_full(faults.DiskFullError("write", "p"))
    assert not retry.is_disk_full(OSError(errno.EIO, "x"))
    assert not retry.is_disk_full(ValueError("x"))


# -- journal sink degrade: drop-and-count ------------------------------------

def test_journal_drops_and_counts_when_sink_dies(tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    j = journal.reset_journal(jpath)
    try:
        j.event("alive")
        j._fh.close()                   # the sink dies under the process
        j.event("dropped_1")            # must NOT raise into the caller
        j.event("dropped_2")
        assert j.write_drops == 2
        # only the durable lines are lost — the recent ring (the flight
        # recorder's journal half) kept every record
        kinds = [r["kind"] for r in j.recent()]
        assert "dropped_2" in kinds
    finally:
        journal.reset_journal("stderr")


# -- the conductor, end to end -----------------------------------------------

def test_pool_campaign_end_to_end(tmp_path):
    scenario = scen.get("pool")
    doc = run_campaign("pool", POOL_SEED, budget_s=6.0,
                       out_dir=str(tmp_path))
    # every declared invariant got a verdict — no silent skips
    declared = [name for name, _p in scenario.invariants]
    assert [v["name"] for v in doc["verdicts"]] == declared
    assert doc["ok"] is True, doc["verdicts"]
    # the schedule composed all four fault classes in ONE window
    assert {s["cls"] for s in doc["schedule"]} == set(sched.FAULT_CLASSES)
    # the artifact is on disk, schema-valid, and report-readable
    got = art.read_artifact(doc["path"])
    assert got["seed"] == POOL_SEED and got["scenario"] == "pool"
    assert got["schedule"] == doc["schedule"]
    rep = report.chaos_report(str(tmp_path))
    assert rep["campaigns"] == 1 and rep["failures"] == 0
    assert len(rep["last"]["classes"]) == 4
    # the snapshot carries the degrade trail the invariants judged
    snap = doc["observability"]
    assert snap["counters"]["ok"] >= 1
    assert "journal_kinds" in snap


class _PlantedRun(scen.ScenarioRun):
    """Minimal durable-writer scenario for the planted-failure test: every
    tick stages a ~4KB document through atomic_write (so budget-mode
    disk_full exhausts within the window) behind its own trip point."""

    def start(self):
        pass

    def tick(self):
        p = os.path.join(self.workdir, "planted.json")
        try:
            atomic.trip("planted_op", p)
            with atomic.atomic_write(p, "w") as f:
                json.dump({"ok": True, "pad": "x" * 4096}, f)
            self.counters.bump("ok")
        except OSError:
            self.counters.bump("degraded")
        time.sleep(0.005)

    def stop(self):
        pass


def test_planted_invariant_shrinks_and_replays(tmp_path):
    targets = {"classes": ("durability", "resource")}

    @inv.register("planted_no_degrades")
    def _planted(obs):
        d = obs["counters"]["degraded"]
        return d == 0, f"{d} degraded ticks"

    scen.register(scen.Scenario(
        "planted", "durable writer whose declared invariant forbids the "
        "degrades the schedule is guaranteed to cause",
        _PlantedRun, targets=targets,
        invariants=[("progress", {}), ("planted_no_degrades", {})],
        clients=1))
    try:
        # short window: generate against it so every at_s lands inside
        specs = sched.generate(7, targets, n_faults=4, window_s=1.0)
        assert {s["cls"] for s in specs} == {"durability", "resource"}
        doc = run_campaign("planted", 7, schedule=specs, budget_s=1.2,
                           out_dir=str(tmp_path))
        assert doc["ok"] is False
        assert "planted_no_degrades" in doc["failed"]
        # ddmin shrank the 4-fault schedule to a tiny reproducer
        assert doc["shrunk"] is not None
        assert 1 <= len(doc["shrunk"]) <= 2, doc["shrunk_human"]
        # the artifact seed regenerates the exact schedule (determinism
        # is what makes the artifact a reproducer, not a war story)
        regen = sched.generate(doc["seed"], targets, n_faults=4,
                               window_s=1.0)
        assert regen == doc["schedule"]
        # replaying ONLY the shrunk subset still violates the invariant
        redo = run_campaign("planted", doc["seed"],
                            schedule=doc["shrunk"], shrink=False,
                            budget_s=1.2, out_dir=str(tmp_path))
        assert redo["ok"] is False
        assert "planted_no_degrades" in redo["failed"]
    finally:
        scen.SCENARIOS.pop("planted", None)
        inv.INVARIANTS.pop("planted_no_degrades", None)

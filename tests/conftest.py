"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (the reference's analog:
`tools/launch.py --launcher local` fakes a cluster with local processes,
SURVEY §4 'Distributed/nightly' row).

The environment may pre-import jax at interpreter startup (a site hook that
registers the single-chip TPU tunnel and force-selects it) — env vars set
here are too late in that case, so the suite re-runs itself once in a clean
subprocess with the right env and without the site hook.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

_ENV = {"JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def _env_ok():
    """Decide re-exec from env + jax config ALONE — never ``jax.devices()``.

    Probing devices here can dial a wedged TPU tunnel and hang the whole
    suite for the driver's window (VERDICT r5 Weak #6: a site hook that
    pre-imports jax and force-pins the tunnel platform cost a 45-minute
    run). A pre-imported jax is trusted only if its *config* — readable
    without any backend touch — says cpu; a tunnel site hook on
    PYTHONPATH always forces the clean re-exec that strips it."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return False
    # parse the flag VALUE (backend-free): a pre-set count < 8 must
    # force the clean re-exec, not run the mesh suite under-provisioned
    flag_count = 0
    for part in os.environ.get("XLA_FLAGS", "").split():
        if part.startswith("--xla_force_host_platform_device_count="):
            try:
                flag_count = int(part.split("=", 1)[1])
            except ValueError:
                flag_count = 0
    if flag_count < 8:
        return False
    if any(".axon_site" in p
           for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)):
        return False
    if "jax" in sys.modules:
        import jax
        try:
            return (jax.config.jax_platforms or "cpu") == "cpu"
        except Exception:
            return False
    return True


def pytest_configure(config):
    if _env_ok():
        return
    if os.environ.get("_MXTPU_TEST_REEXEC") == "1":
        raise RuntimeError("could not obtain an 8-device CPU mesh even "
                           "after re-exec; check JAX_PLATFORMS/XLA_FLAGS")
    env = dict(os.environ)
    env.update(_ENV)
    env["_MXTPU_TEST_REEXEC"] = "1"
    # drop the TPU-tunnel site hook so the child interpreter starts clean
    path = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and ".axon_site" not in p]
    env["PYTHONPATH"] = os.pathsep.join(path)
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.suspend_global_capture(in_=True)
    # graftlint: disable=G5 the child IS the suite; the CI driver owns its deadline
    rc = subprocess.run([sys.executable, "-m", "pytest"] + sys.argv[1:],
                        env=env).returncode
    os._exit(rc)


@pytest.fixture(autouse=True)
def _seed_rng():
    """ref: tests/python/unittest/common.py @with_seed — reproducible RNG
    per test."""
    np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield

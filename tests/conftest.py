"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (the reference's analog:
`tools/launch.py --launcher local` fakes a cluster with local processes,
SURVEY §4 'Distributed/nightly' row)."""
import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
# hard override (not setdefault): the environment may pin JAX_PLATFORMS to a
# TPU tunnel; unit tests must run on the virtual CPU mesh and must not claim
# the (single-client) TPU.
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_rng():
    """ref: tests/python/unittest/common.py @with_seed — reproducible RNG
    per test."""
    np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield

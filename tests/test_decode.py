"""mxnet_tpu.serving.decode — the continuous-batching decode engine.

Covers the acceptance criteria of the decode story (docs/serving.md):
bit-identical autoregressive output vs the pure-python reference under
concurrent staggered streams, ZERO XLA compiles outside the warmed
program set (the dedicated single-cell decode lattice + pow2 prefill
buckets), slot admission (SlotsExhausted vs queue), per-sequence
deadlines (admit-stage miss and mid-stream preempt), cancellation
freeing its slot mid-stream, drain-on-stop completing queued work, the
journal/doctor ``decode`` reduction, and the Server/Router integration
(retryable SlotsExhausted moves a stream to another replica).

The ``smoke`` test runs in CI tier 0.5 (ci/run_tests.sh) on a 2-device
CPU mesh; the subprocess-worker test is marked ``slow``.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.diagnostics.journal import reset_journal
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving import (BucketGrid, DeadlineExceeded, RequestError,
                               Server, ServerConfig, SlotsExhausted)
from mxnet_tpu.serving.decode import DecodeConfig, DecodeEngine, TinyLM
from mxnet_tpu.serving.report import serving_report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def journal_file(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    reset_journal(path)
    try:
        yield path
    finally:
        reset_journal("stderr")


def _records(path, kind=None):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def _engine(**kw):
    cfg_kw = {"slots": kw.pop("slots", 4),
              "window_ms": kw.pop("window_ms", 1.0)}
    cfg_kw.update({k: kw.pop(k) for k in list(kw)
                   if k in ("queue_on_busy", "max_queue", "max_new_tokens",
                            "default_deadline_ms", "prefill_chunk")})
    model = kw.pop("model", None) or TinyLM()
    eng = DecodeEngine(model, DecodeConfig(**cfg_kw), **kw)
    eng.start()
    eng.warmup()
    return eng, model


def _mkblock(dim=4):
    net = nn.Dense(dim, in_units=dim)
    net.initialize()
    return net


# -- the bucket-lattice pin (decode never snaps to a prefill bucket) ---------

def test_for_decode_lattice_is_single_cell():
    grid = BucketGrid.for_decode(8)
    assert grid.grid_bound() == 1
    # the ONE shape decode steps ever present snaps to the one cell
    assert grid.batch_bucket(8) == 8
    assert grid.feature_key((1,)) == (1,)


def test_decode_step_shape_never_lands_in_a_prefill_bucket():
    """The regression this pins: a (slots, 1) decode-step tensor fed to
    a generic serving grid snaps to the smallest PREFILL bucket (batch
    rounded up, feature dim bucketed), which would add a per-step
    compile for every slot-count; the dedicated decode grid maps it to
    exactly its own cell, so step recompiles are impossible by
    construction."""
    serving_grid = BucketGrid(max_batch=16, batch_buckets=(4, 8, 16),
                              dim_buckets={0: (32, 64)})
    decode_grid = BucketGrid.for_decode(6)
    # the generic grid distorts the decode shape: batch 6 -> bucket 8,
    # feature 1 -> bucket 32 — a different executable per distortion
    assert serving_grid.batch_bucket(6) == 8
    assert serving_grid.feature_key((1,)) == (32,)
    # the decode grid is the identity on its one shape...
    assert decode_grid.batch_bucket(6) == 6
    assert decode_grid.feature_key((1,)) == (1,)
    # ...and bounds compiles at exactly one executable
    assert decode_grid.grid_bound() == 1


def test_for_decode_rejects_degenerate_pools():
    with pytest.raises(ValueError):
        BucketGrid.for_decode(0)
    with pytest.raises(ValueError):
        BucketGrid.for_decode(4, step_width=0)


# -- bit-exactness + the zero-mid-run-compile guarantee ----------------------

def test_streams_bit_identical_and_zero_midrun_compiles():
    eng, model = _engine(slots=4)
    try:
        warm = eng.counters["compiles"]
        streams = []
        for i in range(10):            # staggered prompts + lengths
            prompt = [(i * 13 + j) % model.vocab
                      for j in range(1 + (i % 7))]
            n = 5 + (i * 3) % 20
            streams.append((eng.submit(prompt, max_new_tokens=n),
                            prompt, n))
        for s, prompt, n in streams:
            assert s.result(timeout_s=60) == model.reference(prompt, n)
        assert eng.counters["compiles"] == warm, \
            "decode compiled outside the warmed program set"
        assert eng.counters["completed"] == 10
    finally:
        eng.stop()


def test_prefill_chunking_covers_long_prompts():
    """A prompt longer than every prefill bucket runs as a chain of
    bucket-sized chunks (start offsets thread the absorb position) —
    output must equal the reference exactly, with no new compiles."""
    eng, model = _engine(slots=2, prefill_chunk=8)
    try:
        warm = eng.counters["compiles"]
        prompt = list(range(1, 60))    # 59 tokens over 8-wide buckets
        got = eng.generate(prompt, max_new_tokens=12)
        assert got == model.reference(prompt, 12)
        assert eng.counters["compiles"] == warm
    finally:
        eng.stop()


# -- slot admission ----------------------------------------------------------

def test_slots_exhausted_is_retryable_and_queue_path_completes():
    model = TinyLM(max_len=20000)
    eng, _ = _engine(model=model, slots=1, queue_on_busy=False)
    try:
        long_stream = eng.submit([1, 2, 3], max_new_tokens=15000)
        deadline = time.monotonic() + 30
        while eng.occupancy() < 1:     # wait for the slot to be taken
            assert time.monotonic() < deadline
            time.sleep(0.005)
        with pytest.raises(SlotsExhausted) as ei:
            eng.submit([4, 5], max_new_tokens=4)
        assert ei.value.retryable     # router moves it to another replica
        assert ei.value.slots == 1
        long_stream.cancel()
        with pytest.raises(RequestError):
            long_stream.result(timeout_s=60)
    finally:
        eng.stop()


def test_cancel_mid_stream_frees_slot_with_partial_tokens():
    model = TinyLM(max_len=20000)
    eng, _ = _engine(model=model, slots=2)
    try:
        victim = eng.submit([7, 8, 9], max_new_tokens=15000)
        deadline = time.monotonic() + 30
        while not victim.tokens:       # stream is actively generating
            assert time.monotonic() < deadline
            time.sleep(0.005)
        victim.cancel()
        with pytest.raises(RequestError) as ei:
            victim.result(timeout_s=60)
        assert not ei.value.retryable  # caller asked; not a router retry
        got = len(victim.tokens)
        assert 0 < got < 15000
        # the slot is free again: a fresh stream admits and completes
        deadline = time.monotonic() + 30
        while eng.occupancy() > 0:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        assert eng.generate([1], max_new_tokens=3) == \
            model.reference([1], 3)
        assert eng.counters["cancelled"] >= 1
    finally:
        eng.stop()


def test_cancel_hammer_slots_freed_exactly_once_no_stale_tokens():
    """The G22-G25 audit's dynamic companion: hammer ``cancel()`` from
    racing caller threads against slot admission and per-step
    rebatching.  Every stream must terminate decisively (tokens or
    RequestError, never limbo), every slot must be freed exactly once
    (counter conservation: completed + cancelled == submitted), and no
    freed slot may serve a stale sequence — every SURVIVING stream's
    tokens must still be bit-identical to the pure-python reference."""
    import random
    model = TinyLM()
    eng, _ = _engine(model=model, slots=2, queue_on_busy=True,
                     max_queue=64)
    results = []                           # (stream, prompt, max_new)
    res_lock = threading.Lock()

    def submitter(seed):
        rng = random.Random(seed)
        for i in range(8):
            prompt = [rng.randrange(1, 200)
                      for _ in range(rng.randrange(1, 5))]
            max_new = rng.randrange(3, 9)
            st = eng.submit(prompt, max_new_tokens=max_new)
            with res_lock:
                results.append((st, prompt, max_new))
            if rng.random() < 0.5:         # racing cancel: sometimes
                time.sleep(rng.random() * 0.01)   # queued, sometimes
                st.cancel()                       # active, sometimes done
            time.sleep(rng.random() * 0.002)

    try:
        threads = [threading.Thread(target=submitter, args=(s,))
                   for s in (11, 23, 47)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "submitter wedged"
        survived = cancelled = 0
        for st, prompt, max_new in results:
            try:
                toks = st.result(timeout_s=120)
            except RequestError:
                cancelled += 1             # decisive: failed, not limbo
                continue
            survived += 1
            # a stale slot (freed twice, or serving the predecessor's
            # sequence) would break bit-identity with the reference
            assert toks == model.reference(prompt, max_new)
        assert survived + cancelled == len(results) == 24
        # every slot freed exactly once: the ledger balances with no
        # shed/rejected/preempted leakage and the pool drains empty
        deadline = time.monotonic() + 30
        while eng.occupancy() > 0 or eng.queue_depth() > 0:
            assert time.monotonic() < deadline, "slot never freed"
            time.sleep(0.005)
        c = eng.stats()
        assert c["submitted"] == 24
        assert c["completed"] + c["cancelled"] == 24
        assert c["completed"] == survived
        assert c["shed"] == c["rejected"] == c["preempted"] == 0
        # freed slots stay serviceable after the storm
        assert eng.generate([5], max_new_tokens=3) == \
            model.reference([5], 3)
    finally:
        eng.stop()


def test_deadline_preempts_mid_stream(journal_file):
    model = TinyLM(max_len=200000)
    eng, _ = _engine(model=model, slots=1)
    try:
        s = eng.submit([1], max_new_tokens=150000, deadline_ms=80.0)
        with pytest.raises(DeadlineExceeded):
            s.result(timeout_s=60)
        assert eng.counters["preempted"] == 1
        # the preempted stream's slot is reusable immediately
        assert eng.generate([2], max_new_tokens=3) == \
            model.reference([2], 3)
    finally:
        eng.stop()
    assert _records(journal_file, "decode_preempt")


def test_drain_on_stop_completes_queued_streams():
    eng, model = _engine(slots=1, queue_on_busy=True)
    streams = [(eng.submit([i + 1], max_new_tokens=6), [i + 1])
               for i in range(5)]
    eng.stop(drain=True)               # queued streams must still finish
    for s, prompt in streams:
        assert s.result(timeout_s=1) == model.reference(prompt, 6)


def test_submit_validation_rejects_oversized_request():
    eng, model = _engine(slots=2)
    try:
        with pytest.raises(RequestError) as ei:
            eng.submit([1, 2], max_new_tokens=model.max_len)
        assert not ei.value.retryable  # malformed everywhere, don't retry
        with pytest.raises(RequestError):
            eng.submit([], max_new_tokens=4)
    finally:
        eng.stop()


# -- journal + doctor reduction ---------------------------------------------

def test_serving_report_decode_section(journal_file):
    eng, model = _engine(slots=4)
    try:
        for i in range(6):
            eng.generate([i + 1], max_new_tokens=4 + i)
    finally:
        eng.stop()
    rep = serving_report(journal_file)
    dec = rep.get("decode")
    assert dec is not None
    assert dec["finished"] == 6
    assert dec["admitted"] == 6
    assert dec["tokens_out"] == sum(4 + i for i in range(6))
    assert dec["steps"] > 0
    assert sum(dec["occupancy_hist"].values()) == dec["steps"]
    assert dec["warmup_programs"] > 0
    assert dec["clean_stop"]


# -- Server + Router integration --------------------------------------------

def test_server_decode_beside_predict(journal_file):
    model = TinyLM()
    srv = Server(_mkblock(), config=ServerConfig(
        window_ms=1.0, decode_model=model,
        decode=DecodeConfig(slots=2, window_ms=1.0)))
    srv.start()
    try:
        x = np.ones(4, dtype=np.float32)
        y = np.asarray(srv.predict(x))          # one-shot path still up
        assert y.shape == (4,)
        assert srv.decode([3, 1, 4], max_new_tokens=9) == \
            model.reference([3, 1, 4], 9)
        assert "decode" in srv.stats()
    finally:
        srv.stop()
    # the engine stops WITH the server, journaled
    assert _records(journal_file, "decode_stop")


def test_server_without_decode_model_rejects():
    srv = Server(_mkblock(), config=ServerConfig(window_ms=1.0))
    srv.start()
    try:
        with pytest.raises(RequestError) as ei:
            srv.decode([1], max_new_tokens=2)
        assert not ei.value.retryable
    finally:
        srv.stop()


def test_router_decode_retries_exhausted_replica_onto_free_one(
        tmp_path, journal_file):
    """Replica A's single slot is pinned by a long stream; the router
    must land the new stream on B (SlotsExhausted = placement miss,
    retryable) — and A's breaker must NOT count it as a failure."""
    from mxnet_tpu.serving.pool import PoolConfig, ReplicaPool
    from mxnet_tpu.serving.router import Router, RouterConfig

    model = TinyLM(max_len=20000)

    def factory():
        return Server(_mkblock(), config=ServerConfig(
            window_ms=1.0, decode_model=model,
            decode=DecodeConfig(slots=1, window_ms=1.0,
                                queue_on_busy=False)))

    pool = ReplicaPool(str(tmp_path / "pool"),
                       PoolConfig(heartbeat_s=0.1, deadline_s=2.0))
    pool.add_local("a", factory)
    pool.add_local("b", factory)
    pool.start()
    router = Router(pool, RouterConfig(hedge_ms=-1.0, retries=3))
    try:
        # pin BOTH replicas' slots, then free one: the router may try
        # the busy one first but must settle on the free one
        pins = {rid: pool.replicas[rid].server.decode_submit(
            [9], max_new_tokens=15000) for rid in ("a", "b")}
        deadline = time.monotonic() + 30
        while any(pool.replicas[r].server.decoder.occupancy() < 1
                  for r in ("a", "b")):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        pins["b"].cancel()
        with pytest.raises(RequestError):
            pins["b"].result(timeout_s=60)
        deadline = time.monotonic() + 30
        while pool.replicas["b"].server.decoder.occupancy() > 0:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        got = router.decode([2, 7], max_new_tokens=8, deadline_ms=20000)
        assert got == model.reference([2, 7], 8)
        pins["a"].cancel()
        # busy-is-not-broken: no breaker transition was recorded
        assert not _records(journal_file, "router_breaker")
    finally:
        router.stop()
        pool.stop()


# -- CI tier-0.5 smoke -------------------------------------------------------

def test_decode_smoke_sharded_continuous_batching(journal_file):
    """The tier-0.5 decode smoke (ci/run_tests.sh): a tensor-parallel
    server on a 2-device CPU mesh runs 8 concurrent autoregressive
    streams with staggered prompt/generation lengths through the
    continuous batcher — every stream bit-identical to the reference
    within its deadline, ZERO XLA compiles outside the warmed program
    set, and a cancelled stream frees its slot for a successor."""
    import jax

    from mxnet_tpu.serving.shardplan import ShardPlan
    model = TinyLM(max_len=20000)
    plan = ShardPlan(axes={"model": 2}, devices=jax.devices()[:2])
    srv = Server(_mkblock(8), config=ServerConfig(
        window_ms=1.0, shard_plan=plan, decode_model=model,
        decode=DecodeConfig(slots=4, window_ms=1.0)))
    srv.start()
    try:
        eng = srv.decoder
        warm = eng.counters["compiles"]
        assert warm > 0                # warmup really built the set

        results = {}
        def client(i):
            prompt = [(i * 11 + j) % model.vocab
                      for j in range(1 + (i % 5))]
            n = 6 + (i * 5) % 24
            got = srv.decode(prompt, max_new_tokens=n,
                             deadline_ms=30000)
            results[i] = (got == model.reference(prompt, n))

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(results) == 8 and all(results.values()), results

        # cancellation frees its slot: pin a long stream, cancel it,
        # then a successor admits and completes on the freed slot
        victim = srv.decode_submit([5], max_new_tokens=15000)
        deadline = time.monotonic() + 30
        while not victim.tokens:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        victim.cancel()
        with pytest.raises(RequestError):
            victim.result(timeout_s=60)
        assert srv.decode([6], max_new_tokens=4) == \
            model.reference([6], 4)

        assert eng.counters["compiles"] == warm, \
            "decode compiled mid-run"
        assert eng.counters["cancelled"] >= 1
    finally:
        srv.stop()
    # the journal tells the same story through the doctor reduction
    rep = serving_report(journal_file)
    assert rep["decode"]["finished"] >= 9
    assert rep["decode"]["cancelled_total"] >= 1
    assert rep["sharding"]["params"] >= 1


# -- subprocess worker (wire protocol) ---------------------------------------

@pytest.mark.slow
def test_proc_worker_decode_roundtrip(tmp_path):
    """A real subprocess replica with --decode-slots serves decode over
    the wire protocol bit-identically; a second concurrent stream rides
    the same worker."""
    from mxnet_tpu import nd
    from mxnet_tpu.resilience import commit
    from mxnet_tpu.serving.pool import PoolConfig, ReplicaPool
    from mxnet_tpu.serving.router import Router, RouterConfig

    model = TinyLM()
    ck = str(tmp_path / "ckpt")
    stage = commit.prepare_stage(ck, 1)
    nd.save(os.path.join(stage, "net.params"),
            {"w": nd.array(np.asarray([3.0], np.float32))})
    commit.finalize(ck, 1)
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
           "MXNET_TPU_TRACE": "off"}
    env.pop("XLA_FLAGS", None)
    pool = ReplicaPool(str(tmp_path / "pool"),
                       PoolConfig(heartbeat_s=0.25, deadline_s=2.5))
    pool.add_proc("p0", {"--model": "scale", "--ckpt-root": ck,
                         "--window-ms": 1.0, "--reload-poll-s": -1.0,
                         "--decode-slots": 2}, env=env)
    pool.start()
    router = Router(pool, RouterConfig(hedge_ms=-1.0))
    try:
        import concurrent.futures as cf
        def one(i):
            p = [i + 1, i + 2, i + 3]
            n = 10 + i
            return router.decode(p, max_new_tokens=n) == \
                model.reference(p, n)
        with cf.ThreadPoolExecutor(4) as ex:
            assert all(ex.map(one, range(4)))
        # predict still serves on the same worker
        x = np.arange(4, dtype=np.float32)
        resp = router.call(x, deadline_ms=8000)
        assert np.allclose(np.asarray(resp.value), x * 3.0, atol=1e-5)
    finally:
        router.stop()
        pool.stop()

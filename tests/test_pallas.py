"""The guarded custom-kernel tier (mxnet_tpu/pallas/, docs/pallas.md):
interpret-mode parity for EVERY registered kernel vs its XLA reference
(the registration-time numerics gate), fallback selection (non-TPU
backend, unsupported shape, env kill-switch — each journaled with a
reason), gradient parity through the custom_vjp paths, dropout-key
independence under the PR-1 (layer, tick, shard) fold discipline, and
the gluon/ops wiring (Dense epilogue, BatchNorm act_type, resnet
residual epilogue, blockwise-attention routing, bench A/B flag)."""
import json
import os

import numpy as np
import pytest

from mxnet_tpu import nd, pallas
from mxnet_tpu.base import MXNetError


@pytest.fixture
def clean_tier(monkeypatch):
    """Pristine tier state: auto mode, empty provenance."""
    monkeypatch.delenv("MXNET_TPU_PALLAS", raising=False)
    pallas.set_mode(None)
    pallas.reset_provenance()
    yield
    pallas.set_mode(None)
    pallas.reset_provenance()


# -- the registration-time parity gate ---------------------------------------

def _cases():
    out = []
    for name, spec in pallas.kernels().items():
        assert spec.example is not None, \
            f"kernel {name!r} registered without example() — the parity " \
            f"gate cannot cover it"
        for i, (args, params) in enumerate(spec.example()):
            out.append(pytest.param(name, i, id=f"{name}-{i}"))
    return out


@pytest.mark.parametrize("name,case", _cases())
def test_parity_gate_smoke(name, case, clean_tier):
    """EVERY registered kernel passes its CPU interpret-mode parity gate
    vs the XLA reference within the registered tolerance — the contract
    that lets the tier claim it can never silently change numerics."""
    spec = pallas.get_kernel(name)
    args, params = spec.example()[case]
    got = np.asarray(spec.pallas_impl(*args, interpret=True, **params),
                     np.float32)
    want = np.asarray(spec.xla_reference(*args, **params), np.float32)
    err = float(np.abs(got - want).max())
    assert err <= spec.tolerance, \
        f"{name} case {case}: max err {err} > tolerance {spec.tolerance}"


def test_parity_gate_covers_shape_and_dtype(clean_tier):
    import jax.numpy as jnp
    for name, spec in pallas.kernels().items():
        for args, params in spec.example():
            got = spec.pallas_impl(*args, interpret=True, **params)
            want = spec.xla_reference(*args, **params)
            assert got.shape == want.shape
            assert jnp.result_type(got) == jnp.result_type(want)


def test_grads_match_reference_smoke(clean_tier):
    """The custom_vjp paths (pallas forward, reference VJP backward)
    agree with differentiating the reference end-to-end — scale/bias
    vectors included, so BN's gamma/beta gradients are covered."""
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(3)
    y = jnp.asarray(rng.randn(16, 128), jnp.float32)
    s = jnp.asarray(rng.rand(1, 128) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(1, 128) * 0.1, jnp.float32)
    res = jnp.asarray(rng.randn(16, 128), jnp.float32)
    spec = pallas.get_kernel("conv_epilogue")

    def loss_p(y, s, b, res):
        return (spec.pallas_impl(y, s, b, res, interpret=True,
                                 act_type="relu") ** 2).sum()

    def loss_r(y, s, b, res):
        return (spec.xla_reference(y, s, b, res, act_type="relu") ** 2).sum()

    gp = jax.grad(loss_p, argnums=(0, 1, 2, 3))(y, s, b, res)
    gr = jax.grad(loss_r, argnums=(0, 1, 2, 3))(y, s, b, res)
    for a, bb in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-4)
    # matmul epilogue with dropout folded in
    mspec = pallas.get_kernel("matmul_epilogue")
    bits = pallas.dropout_bits(jax.random.key(5), (16, 128))
    gp = jax.grad(lambda v: (mspec.pallas_impl(
        v, b, bits, interpret=True, act_type="gelu", p=0.3) ** 2).sum())(y)
    gr = jax.grad(lambda v: (mspec.xla_reference(
        v, b, bits, act_type="gelu", p=0.3) ** 2).sum())(y)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                               rtol=1e-4, atol=1e-4)


# -- fallback selection (the guard half of the tier) -------------------------

def _journal_records(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def test_fallback_non_tpu_backend_is_journaled_smoke(clean_tier, tmp_path):
    """The default CPU path never executes the unverified kernel: the
    dispatch falls back to the reference and journals why."""
    import jax.numpy as jnp
    from mxnet_tpu.diagnostics import reset_journal
    jpath = str(tmp_path / "journal.jsonl")
    reset_journal(jpath)
    try:
        y = jnp.ones((16, 128))
        s, b = jnp.ones((1, 128)), jnp.zeros((1, 128))
        out = pallas.dispatch("conv_epilogue", y, s, b, None,
                              act_type="relu")
        assert out.shape == (16, 128)
    finally:
        reset_journal(None)
    prov = pallas.tier_provenance()["conv_epilogue"]
    assert prov["pallas"] == 0 and prov["xla"] == 1
    assert prov["fallback_reasons"] == {"backend:cpu": 1}
    recs = [r for r in _journal_records(jpath)
            if r["kind"] == "pallas_fallback"]
    assert len(recs) == 1
    assert recs[0]["kernel"] == "conv_epilogue"
    assert recs[0]["reason"] == "backend:cpu"
    # dedupe: a second identical fallback journals nothing new but counts
    pallas.dispatch("conv_epilogue", y, s, b, None, act_type="relu")
    assert pallas.tier_provenance()["conv_epilogue"]["xla"] == 2


def test_fallback_unsupported_shape(clean_tier):
    """supports() rejection falls back with the concrete reason — even
    when interpret would otherwise force the custom path."""
    import jax.numpy as jnp
    y = jnp.ones((4, 2))          # minor dim below the tier's floor
    s, b = jnp.ones((1, 2)), jnp.zeros((1, 2))
    out = pallas.dispatch("conv_epilogue", y, s, b, None,
                          act_type="relu", interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.ones((4, 2)))
    reasons = pallas.tier_provenance()["conv_epilogue"]["fallback_reasons"]
    assert any(r.startswith("minor_dim_tiny") for r in reasons)
    # int input: dtype gate
    pallas.dispatch("conv_epilogue", jnp.ones((16, 128), jnp.int32),
                    jnp.ones((1, 128), jnp.int32),
                    jnp.zeros((1, 128), jnp.int32), None, act_type="relu",
                    interpret=True)
    reasons = pallas.tier_provenance()["conv_epilogue"]["fallback_reasons"]
    assert any(r.startswith("dtype") for r in reasons)


def test_kill_switch_env_beats_interpret(clean_tier, monkeypatch):
    """MXNET_TPU_PALLAS=off is absolute: even a forced interpret dispatch
    gets the reference."""
    import jax.numpy as jnp
    monkeypatch.setenv("MXNET_TPU_PALLAS", "off")
    y = jnp.ones((16, 128))
    s, b = jnp.ones((1, 128)), jnp.zeros((1, 128))
    pallas.dispatch("conv_epilogue", y, s, b, None, act_type="relu",
                    interpret=True)
    prov = pallas.tier_provenance()["conv_epilogue"]
    assert prov["pallas"] == 0
    assert prov["fallback_reasons"] == {"mode_off": 1}


def test_malformed_mode_degrades_to_auto(clean_tier, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PALLAS", "bogus")
    assert pallas.mode() == "auto"
    with pytest.raises(MXNetError):
        pallas.set_mode("bogus")


def test_mode_on_makes_fallback_loud(clean_tier):
    import jax.numpy as jnp
    pallas.set_mode("on")
    y = jnp.ones((16, 128))
    s, b = jnp.ones((1, 128)), jnp.zeros((1, 128))
    with pytest.warns(RuntimeWarning, match="fell back"):
        pallas.dispatch("conv_epilogue", y, s, b, None, act_type="relu")


def test_duplicate_registration_rejected(clean_tier):
    spec = pallas.get_kernel("conv_epilogue")
    with pytest.raises(MXNetError, match="duplicate"):
        pallas.register_kernel(
            "conv_epilogue", xla_reference=spec.xla_reference,
            tolerance=1.0)(spec.pallas_impl)


# -- dropout-key independence (PR-1 fold discipline) -------------------------

def test_dropout_key_independence_smoke(clean_tier):
    """(layer, tick, shard) fold into the key: any identity change gives
    an independent mask; the same identity is deterministic."""
    import jax
    key = jax.random.key(11)
    base = np.asarray(pallas.dropout_bits(key, (64, 128)))
    same = np.asarray(pallas.dropout_bits(key, (64, 128)))
    np.testing.assert_array_equal(base, same)
    varied = [np.asarray(pallas.dropout_bits(key, (64, 128), **kw))
              for kw in ({"layer": 1}, {"tick": 1}, {"shard": 1},
                         {"layer": 1, "tick": 2, "shard": 3})]
    for v in varied:
        frac = float((v != base).mean())
        assert frac > 0.9          # independent uint8 draws differ a.s.
    # and through the fused epilogue: different ticks -> different masks
    import jax.numpy as jnp
    y = jnp.ones((64, 128))
    b = jnp.zeros((1, 128))
    outs = [np.asarray(pallas.fused_matmul_epilogue(
        y, b, act_type="identity", p=0.5, rng=key, training=True,
        tick=t, interpret=True)) for t in (0, 1)]
    assert (outs[0] != outs[1]).mean() > 0.3
    kept = outs[0] != 0
    np.testing.assert_allclose(outs[0][kept], 2.0)   # inverted scaling


# -- wiring: gluon / ops / model-zoo surfaces --------------------------------

def test_dense_fused_epilogue_matches_unfused(clean_tier):
    from mxnet_tpu import gluon
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 32).astype(np.float32))
    fused = gluon.nn.Dense(16, activation="relu", in_units=32)
    fused.initialize()
    y = fused(x).asnumpy()
    w = fused.weight.data().asnumpy()
    b = fused.bias.data().asnumpy()
    want = np.maximum(x.asnumpy() @ w.T + b, 0.0)
    np.testing.assert_allclose(y, want, rtol=1e-5, atol=1e-6)
    # gelu is epilogue-only (plain Activation has no gelu mode) — new
    # capability unlocked by the tier
    import jax
    g = gluon.nn.Dense(16, activation="gelu", in_units=32)
    g.initialize()
    yg = g(x).asnumpy()
    wantg = np.asarray(jax.nn.gelu(
        x.asnumpy() @ g.weight.data().asnumpy().T
        + g.bias.data().asnumpy(), approximate=False))
    np.testing.assert_allclose(yg, wantg, rtol=1e-5, atol=1e-6)


def test_dense_epilogue_dropout_train_eval(clean_tier):
    from mxnet_tpu import autograd, gluon
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(8, 32).astype(np.float32))
    net = gluon.nn.Dense(64, activation="relu", in_units=32,
                         epilogue_dropout=0.5)
    net.initialize()
    y_eval = net(x).asnumpy()           # inference: dropout is a no-op
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    np.testing.assert_allclose(
        y_eval, np.maximum(x.asnumpy() @ w.T + b, 0.0),
        rtol=1e-5, atol=1e-6)
    with autograd.record():
        y_tr = net(x).asnumpy()
    kept = y_tr != 0
    # inverted dropout: kept activations are scaled by 1/(1-p)
    np.testing.assert_allclose(y_tr[kept], (y_eval * 2.0)[kept],
                               rtol=1e-5, atol=1e-6)
    assert 0.2 < float(kept.mean()) < 0.9


def test_batchnorm_activation_fused_parity(clean_tier):
    """BatchNorm(activation=...) == BatchNorm() + Activation, train and
    eval, NCHW (row-broadcast path) and channel-last (col-broadcast)."""
    from mxnet_tpu import autograd, gluon
    rng = np.random.RandomState(2)
    for axis, shape in ((1, (4, 8, 6, 6)), (-1, (4, 6, 8))):
        x = nd.array(rng.randn(*shape).astype(np.float32))
        fused = gluon.nn.BatchNorm(axis=axis, activation="relu")
        plain = gluon.nn.BatchNorm(axis=axis)
        fused.initialize()
        plain.initialize()
        for train in (True, False):
            if train:
                with autograd.record():
                    a = fused(x).asnumpy()
                with autograd.record():
                    b = nd.relu(plain(x)).asnumpy()
            else:
                a = fused(x).asnumpy()
                b = nd.relu(plain(x)).asnumpy()
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_contrib_conv_epilogue_matches_add_relu(clean_tier):
    rng = np.random.RandomState(4)
    x = nd.array(rng.randn(2, 8, 4, 4).astype(np.float32))
    r = nd.array(rng.randn(2, 8, 4, 4).astype(np.float32))
    got = nd.contrib.conv_epilogue(x, r).asnumpy()
    want = np.maximum(x.asnumpy() + r.asnumpy(), 0.0)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_positionwise_ffn_fused_parity_eval(clean_tier):
    """The fused FFN (bias+gelu epilogue on ffn_1, bias+dropout epilogue
    on ffn_2) equals the classic composition in eval mode."""
    import jax
    from mxnet_tpu.gluon.model_zoo.bert import PositionwiseFFN
    ffn = PositionwiseFFN(units=16, hidden_size=32, dropout=0.4)
    ffn.initialize()
    assert ffn.ffn_1._activation == "gelu"
    assert ffn.ffn_2._epilogue_dropout == pytest.approx(0.4)
    rng = np.random.RandomState(5)
    x = nd.array(rng.randn(2, 3, 16).astype(np.float32))
    got = ffn(x).asnumpy()
    w1 = ffn.ffn_1.weight.data().asnumpy()
    b1 = ffn.ffn_1.bias.data().asnumpy()
    w2 = ffn.ffn_2.weight.data().asnumpy()
    b2 = ffn.ffn_2.bias.data().asnumpy()
    h = np.asarray(jax.nn.gelu(x.asnumpy() @ w1.T + b1,
                               approximate=False))
    want = h @ w2.T + b2
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_blockwise_attention_routes_through_registry(clean_tier,
                                                     monkeypatch):
    """The long-context kernel shares the tier's guard story: auto mode
    runs the online-softmax kernel (a verified backend on CPU), the kill
    switch falls back to the dense reference."""
    from mxnet_tpu.parallel.ring_attention import (attention_reference,
                                                   blockwise_attention)
    import jax.numpy as jnp
    rng = np.random.RandomState(6)
    q = jnp.asarray(rng.randn(2, 2, 32, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 2, 32, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 2, 32, 8), jnp.float32)
    out = blockwise_attention(q, k, v, block_size=8, causal=True)
    prov = pallas.tier_provenance()["blockwise_attention"]
    assert prov["pallas"] == 1          # cpu IS a verified backend here
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    monkeypatch.setenv("MXNET_TPU_PALLAS", "off")
    out2 = blockwise_attention(q, k, v, block_size=8, causal=True)
    prov = pallas.tier_provenance()["blockwise_attention"]
    assert prov["fallback_reasons"].get("mode_off") == 1
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_bench_pallas_flag(clean_tier, monkeypatch, capsys):
    """bench.py --pallas {on,off,auto}: valid modes export the env knob
    for the deadlined child; an invalid mode is a structured one-line
    diagnostic, not a crash."""
    import importlib
    bench = importlib.import_module("bench")
    assert bench._parse_pallas_flag(["bench.py", "--pallas", "off"]) == "off"
    assert bench._parse_pallas_flag(["bench.py", "--pallas=on"]) == "on"
    assert bench._parse_pallas_flag(["bench.py"]) is None
    monkeypatch.setattr("sys.argv", ["bench.py", "--pallas", "sideways"])
    monkeypatch.delenv("MXNET_TPU_PALLAS", raising=False)
    rc = bench.main()
    assert rc == 2
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["error"] == "bad_flag"
    assert rec["metric"] == bench.METRIC
    # valid flag exports the knob (parent env -> child inherits)
    monkeypatch.setattr("sys.argv", ["bench.py", "--pallas", "off",
                                     "--body"])
    monkeypatch.setattr(bench, "_run_body", lambda: 0)
    try:
        assert bench.main() == 0
        assert os.environ["MXNET_TPU_PALLAS"] == "off"
    finally:
        # bench.main set the var itself; delenv on an absent var
        # registers no undo, so restore by hand or it leaks into
        # every later test in the process
        os.environ.pop("MXNET_TPU_PALLAS", None)


def test_blockwise_reference_chunking_is_exact(clean_tier):
    """The kill-switch fallback for attention chunks its query axis
    (bounded score-matrix memory) — same math as the unchunked dense
    reference, bottom-right causal alignment included, s_q != s_kv and
    empty-row edges covered."""
    import jax.numpy as jnp
    from mxnet_tpu.pallas.kernels import _blockwise_ref
    from mxnet_tpu.parallel.ring_attention import attention_reference
    rng = np.random.RandomState(7)
    cases = [(40, 40), (48, 32), (32, 48)]   # square, s_q>s_kv, s_q<s_kv
    for s_q, s_kv in cases:
        q = jnp.asarray(rng.randn(2, 2, s_q, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 2, s_kv, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 2, s_kv, 8), jnp.float32)
        for causal in (False, True):
            got = _blockwise_ref(q, k, v, causal=causal, _chunk=16)
            want = attention_reference(q, k, v, causal=causal)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5,
                err_msg=f"s_q={s_q} s_kv={s_kv} causal={causal}")

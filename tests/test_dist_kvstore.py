"""Multi-process dist kvstore test (ref: tests/nightly/
dist_sync_kvstore.py run via `tools/launch.py -n 2 --launcher local`):
worker processes join through the JAX coordination service and verify
push/pull aggregates across processes."""
import os
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
import mxnet_tpu as mx

kv = mx.kv.create("dist_sync")
rank = kv.rank
n = kv.num_workers
assert n == 2, n

val = mx.nd.ones((4,)) * (rank + 1)     # worker 0: 1s, worker 1: 2s
kv.init(3, mx.nd.zeros((4,)))
kv.push(3, val)
out = mx.nd.zeros((4,))
kv.pull(3, out=out)
expect = np.full(4, 3.0)                 # 1 + 2 summed across workers
np.testing.assert_allclose(out.asnumpy(), expect)

# row_sparse push over DCN (round-2 verdict #8): workers touch
# overlapping row sets; the sparse allgather-reduce must sum overlaps
# and union the rest, without shipping the dense table
from mxnet_tpu.ndarray.sparse import RowSparseNDArray
shape = (6, 3)
kv.init("emb", mx.nd.zeros(shape))
if rank == 0:
    rows = np.array([0, 2], np.int64)         # worker 0 touches rows 0,2
else:
    rows = np.array([2, 5], np.int64)         # worker 1 touches rows 2,5
vals = np.full((2, 3), float(rank + 1), np.float32)
kv.push("emb", RowSparseNDArray(vals, rows, shape))
dense = mx.nd.zeros(shape)
kv.pull("emb", out=dense)
want = np.zeros(shape, np.float32)
want[0] = 1.0
want[2] = 3.0                                  # overlap: 1 + 2
want[5] = 2.0
np.testing.assert_allclose(dense.asnumpy(), want)
picked = kv.row_sparse_pull("emb", row_ids=mx.nd.array([2, 5]))
np.testing.assert_allclose(np.asarray(picked.data),
                           want[[2, 5]])
print(f"rank {rank} OK")
"""


def test_dist_sync_kvstore_two_processes(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": REPO})
    env = dict(os.environ)
    # clean slate: the TPU-tunnel site hook must not claim the chip in
    # both workers
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "-p", "9233",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=280, env=env)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "rank 0 OK" in r.stdout
    assert "rank 1 OK" in r.stdout

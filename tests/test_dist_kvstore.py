"""Multi-process dist kvstore test (ref: tests/nightly/
dist_sync_kvstore.py run via `tools/launch.py -n 2 --launcher local`):
worker processes join through the JAX coordination service and verify
push/pull aggregates across processes."""
import os
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
import mxnet_tpu as mx

kv = mx.kv.create("dist_sync")
rank = kv.rank
n = kv.num_workers
assert n == 2, n

val = mx.nd.ones((4,)) * (rank + 1)     # worker 0: 1s, worker 1: 2s
kv.init(3, mx.nd.zeros((4,)))
kv.push(3, val)
out = mx.nd.zeros((4,))
kv.pull(3, out=out)
expect = np.full(4, 3.0)                 # 1 + 2 summed across workers
np.testing.assert_allclose(out.asnumpy(), expect)

# row_sparse push over DCN (round-2 verdict #8): workers touch
# overlapping row sets; the sparse allgather-reduce must sum overlaps
# and union the rest, without shipping the dense table
from mxnet_tpu.ndarray.sparse import RowSparseNDArray
shape = (6, 3)
kv.init("emb", mx.nd.zeros(shape))
if rank == 0:
    rows = np.array([0, 2], np.int64)         # worker 0 touches rows 0,2
else:
    rows = np.array([2, 5], np.int64)         # worker 1 touches rows 2,5
vals = np.full((2, 3), float(rank + 1), np.float32)
kv.push("emb", RowSparseNDArray(vals, rows, shape))
dense = mx.nd.zeros(shape)
kv.pull("emb", out=dense)
want = np.zeros(shape, np.float32)
want[0] = 1.0
want[2] = 3.0                                  # overlap: 1 + 2
want[5] = 2.0
np.testing.assert_allclose(dense.asnumpy(), want)
picked = kv.row_sparse_pull("emb", row_ids=mx.nd.array([2, 5]))
np.testing.assert_allclose(np.asarray(picked.data),
                           want[[2, 5]])
print(f"rank {rank} OK")
"""


def test_dist_sync_kvstore_two_processes(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": REPO})
    env = dict(os.environ)
    # clean slate: the TPU-tunnel site hook must not claim the chip in
    # both workers
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "-p", "9233",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=280, env=env)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "rank 0 OK" in r.stdout
    assert "rank 1 OK" in r.stdout


TRAIN_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

kv = mx.kv.create("dist_sync")
rank, n = kv.rank, kv.num_workers
assert n == 8, n

# synthetic separable 4-class problem; each worker trains on its OWN
# shard (the reference's dist_sync nightly uses per-worker data too)
rng = np.random.RandomState(100)          # same gen -> same w_true
w_true = rng.randn(8, 4)
rs = np.random.RandomState(1000 + rank)   # different shard per worker
x = rs.randn(200, 8).astype(np.float32)
y = np.argmax(x @ w_true, axis=1).astype(np.float32)

mx.random.seed(11)                        # identical init on every rank
net = gluon.nn.HybridSequential()
with net.name_scope():
    net.add(gluon.nn.Dense(16, activation="relu", in_units=8))
    net.add(gluon.nn.Dense(4, in_units=16))
net.initialize(mx.init.Xavier())

trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.2, "momentum": 0.9},
                        kvstore=kv)
lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
B = 40
for epoch in range(12):
    for i in range(0, 200, B):
        xb, yb = mx.nd.array(x[i:i + B]), mx.nd.array(y[i:i + B])
        with autograd.record():
            L = lossfn(net(xb), yb)
        L.backward()
        # dist_sync SUMS gradients across workers (reference semantics:
        # ref kvstore_dist_server DataHandleEx accumulate-then-apply), so
        # normalize by the GLOBAL batch
        trainer.step(B * n)

# 1) post-training weights must be IDENTICAL across workers (gather
# every worker's flattened weights; kv push/pull is not usable here —
# with update_on_kvstore the store treats pushed values as gradients,
# reference semantics)
import jax.numpy as jnp
from jax.experimental import multihost_utils
flat = np.concatenate([p.data().asnumpy().ravel()
                       for p in net.collect_params().values()])
allw = np.asarray(multihost_utils.process_allgather(jnp.asarray(flat)))
for r in range(n):
    np.testing.assert_allclose(allw[r], allw[0], rtol=1e-6, atol=1e-6)

# 2) convergence gate on the local shard
pred = net(mx.nd.array(x)).asnumpy().argmax(axis=1)
acc = float((pred == y).mean())
assert acc > 0.9, f"rank {rank} acc {acc}"
print(f"rank {rank} OK acc={acc:.3f}")
"""


def test_dist_sync_training_eight_processes(tmp_path):
    """VERDICT r3 #8: launch.py -n 8 --launcher local drives a REAL
    dist_sync training loop (gluon.Trainer over the coordination
    service); asserts bit-identical post-training weights on every
    worker and a convergence floor (ref: tests/nightly/
    dist_sync_kvstore.py + test_distributed_training)."""
    script = tmp_path / "train_worker.py"
    script.write_text(TRAIN_WORKER % {"repo": REPO})
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "8", "--launcher", "local", "-p", "9241",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    for rank in range(8):
        assert f"rank {rank} OK" in r.stdout, r.stdout


SHARD_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import io

kv = mx.kv.create("dist_sync")
rank, n = kv.rank, kv.num_workers
assert n == 8, n

# NO num_parts/part_index kwargs: the launcher env must wire the shard
it = io.ImageRecordIter(path_imgrec=%(rec)r, path_imgidx=%(idx)r,
                        data_shape=(3, 16, 16), batch_size=1)
labels = []
try:
    while True:
        labels.append(int(it.next().label[0].asnumpy()[0]))
except StopIteration:
    pass

import jax.numpy as jnp
from jax.experimental import multihost_utils
# fixed-width gather: one row per rank, -1-padded
row = np.full(64, -1, np.int32)
row[:len(labels)] = labels
allrows = np.asarray(multihost_utils.process_allgather(jnp.asarray(row)))
union = [int(v) for r_ in allrows for v in r_ if v >= 0]
assert len(union) == len(set(union)), "ranks read duplicate records"
assert sorted(union) == list(range(40)), sorted(union)
print(f"rank {rank} OK n_local={len(labels)}")
"""


def test_dist_input_sharding_eight_processes(tmp_path):
    """VERDICT r4 Missing #1: with `launch.py -n 8`, every rank must read
    a DISJOINT shard of one shared RecordIO pack, jointly covering it —
    wired purely from the launcher env, no per-rank code (ref:
    src/io/iter_image_recordio_2.cc num_parts/part_index [H])."""
    import numpy as np
    from mxnet_tpu import recordio
    rec, idx = str(tmp_path / "d.rec"), str(tmp_path / "d.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(40):
        img = np.full((16, 16, 3), i % 251, np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()
    script = tmp_path / "shard_worker.py"
    script.write_text(SHARD_WORKER % {"repo": REPO, "rec": rec, "idx": idx})
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "8", "--launcher", "local", "-p", "9247",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=540, env=env)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    for rank in range(8):
        assert f"rank {rank} OK" in r.stdout, r.stdout

"""Multi-process dist kvstore test (ref: tests/nightly/
dist_sync_kvstore.py run via `tools/launch.py -n 2 --launcher local`):
worker processes join through the JAX coordination service and verify
push/pull aggregates across processes."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
import mxnet_tpu as mx

kv = mx.kv.create("dist_sync")
rank = kv.rank
n = kv.num_workers
assert n == 2, n

val = mx.nd.ones((4,)) * (rank + 1)     # worker 0: 1s, worker 1: 2s
kv.init(3, mx.nd.zeros((4,)))
kv.push(3, val)
out = mx.nd.zeros((4,))
kv.pull(3, out=out)
expect = np.full(4, 3.0)                 # 1 + 2 summed across workers
np.testing.assert_allclose(out.asnumpy(), expect)
print(f"rank {rank} OK")
"""


def test_dist_sync_kvstore_two_processes(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": REPO})
    env = dict(os.environ)
    # clean slate: the TPU-tunnel site hook must not claim the chip in
    # both workers
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "-p", "9233",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=280, env=env)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "rank 0 OK" in r.stdout
    assert "rank 1 OK" in r.stdout

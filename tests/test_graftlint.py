"""graftlint static-analysis tier (mxnet_tpu/analysis/): every G-rule
against its seeded-violation fixture (flag at the right line, disabled
twin stays silent), the W-rule port, suppression + baseline semantics,
the emitters, the ci/lint.py shim, the repo's own cleanliness modulo
the committed baseline, and the runtime fixes the analyzer drove
(backend-free shape inference, journaled waitall)."""
import json
import os
import re
import subprocess
import sys

import pytest

from mxnet_tpu.analysis import core
from mxnet_tpu.analysis import baseline as bl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "graftlint")
G_FIXTURES = sorted(f for f in os.listdir(FIXTURES) if f.endswith(".py"))

_EXPECT_RE = re.compile(r"#\s*expect:\s*([A-Z]\d+)")


def _rules(codes):
    registry = core.load_rules()
    return [registry[c] for c in codes]


def _g_rules():
    return _rules([c for c in core.load_rules() if c.startswith("G")])


def _expected(path):
    with open(path, encoding="utf-8") as f:
        return {(i, m.group(1))
                for i, line in enumerate(f, 1)
                for m in [_EXPECT_RE.search(line)] if m}


# -- the G-rules against their seeded fixtures -------------------------------

@pytest.mark.parametrize("fname", G_FIXTURES)
def test_g_rule_fixture_flags_exact_lines(fname):
    """Each seeded violation is flagged at its exact line; the
    `# graftlint: disable=` twin and the clean variants are silent."""
    path = os.path.join(FIXTURES, fname)
    got = {(f.line, f.code)
           for f in core.lint_file(path, rules=_g_rules(), root=REPO)}
    want = _expected(path)
    assert want, f"fixture {fname} has no # expect: markers"
    assert got == want


def test_g21_real_read_paths_are_clean():
    """The shipped deserialize surfaces satisfy G21 by construction:
    aotcache.load validates (CRC + envelope) before cache.from_serialized
    unpickles caller-validated bytes, and optimizer.set_states receives
    bytes (no file read) so the reader owns the check."""
    for rel in ("mxnet_tpu/serving/aotcache.py",
                "mxnet_tpu/serving/cache.py",
                "mxnet_tpu/serving/aot_report.py",
                "mxnet_tpu/optimizer/optimizer.py"):
        findings = [f for f in core.lint_file(
            os.path.join(REPO, rel), rules=_rules(["G21"]), root=REPO)]
        assert findings == [], (rel, [f.render() for f in findings])


def test_g1_was_invisible_to_the_legacy_w_tier():
    """The acceptance-criteria case: a module-scope jax.devices() that
    the seed's ci/lint.py (W-rules only) let through is a G1 error for
    the framework."""
    path = os.path.join(FIXTURES, "g1_module_dial.py")
    legacy = core.lint_file(
        path, rules=_rules(["W1", "W2", "W3", "W4", "W5", "W6"]),
        root=REPO)
    assert legacy == [], "old tier should see nothing wrong here"
    modern = core.lint_file(path, rules=_g_rules(), root=REPO)
    assert any(f.code == "G1" and "jax.devices" in f.message
               for f in modern)


# -- generic tier port -------------------------------------------------------

def test_w_rules_ported_bitcompatible(tmp_path):
    src = (
        "import os\n"                                # W1 unused
        "import sys  # noqa\n"                       # legacy suppression
        "def f(x=[]):\n"                             # W3
        "    try:\n"
        "        return x\n"
        "    except:\n"                              # W2
        "        pass\n"
        "s = f''\n"                                  # W4
        "t = 'trailing '   \n"                       # W5
        "u = '" + "x" * 101 + "'\n"                  # W6
    )
    p = tmp_path / "bad.py"
    p.write_text(src)
    codes = sorted({f.code for f in core.lint_file(
        str(p), rules=_rules(["W1", "W2", "W3", "W4", "W5", "W6"]))})
    assert codes == ["W1", "W2", "W3", "W4", "W5", "W6"]
    lines = {f.code: f.line for f in core.lint_file(
        str(p), rules=_rules(["W1", "W2"]))}
    assert lines == {"W1": 1, "W2": 6}               # sys import: noqa'd


def test_syntax_error_is_e1(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    (f,) = core.lint_file(str(p))
    assert f.code == "E1" and f.severity == "error"


def test_baselined_e1_in_one_file_never_masks_another(tmp_path):
    """E1 findings carry real (path-keyed) fingerprints: accepting a
    syntax error in file A must not absorb a fresh one in file B."""
    a, b = tmp_path / "a.py", tmp_path / "b.py"
    a.write_text("def f(:\n")
    b.write_text("x = 1\n")
    blp = str(tmp_path / "base.json")
    scan = lambda: core.run([str(a), str(b)], root=str(tmp_path))[0]
    bl.write_baseline(blp, scan())
    a.write_text("x = 1\n")                         # A fixed...
    b.write_text("def g(:\n")                       # ...B freshly broken
    new, based = bl.partition(scan(), bl.load_baseline(blp))
    assert based == []
    assert len(new) == 1 and new[0].path == "b.py" and new[0].code == "E1"


# -- suppression syntax ------------------------------------------------------

def test_suppression_same_line_next_line_and_codes(tmp_path):
    src = (
        "import jax\n"
        "A = jax.devices()\n"
        "B = jax.devices()  # graftlint: disable=G1 justified here\n"
        "# graftlint: disable=G1 standalone comment covers next line\n"
        "C = jax.devices()\n"
        "D = jax.devices()  # graftlint: disable=G4 wrong code: no effect\n"
        "E = jax.devices()  # graftlint: disable=G4, G1 spaced list works\n"
    )
    p = tmp_path / "sup.py"
    p.write_text(src)
    lines = [f.line for f in core.lint_file(str(p), rules=_rules(["G1"]))]
    assert lines == [2, 6]


def test_suppression_on_multiline_statement_continuation(tmp_path):
    """Findings anchor to a statement's first line; the natural comment
    spot is the closing line — a disable anywhere on a multi-line
    simple statement covers it."""
    src = (
        "import subprocess\n"
        "r = subprocess.run(\n"
        "    ['x'],\n"
        "    capture_output=True)  # graftlint: disable=G5 deadline upstream\n"
        "q = subprocess.run(\n"
        "    ['y'])\n"
    )
    p = tmp_path / "ml.py"
    p.write_text(src)
    lines = [f.line for f in core.lint_file(str(p), rules=_rules(["G5"]))]
    assert lines == [5]


def test_suppression_on_compound_statement_header(tmp_path):
    """A disable on the closing line of a multi-line compound HEADER
    (if/while test) reaches the finding anchored at the opening line —
    but never leaks into the body."""
    src = (
        "import subprocess\n"
        "def f():\n"
        "    if subprocess.run(\n"
        "            ['x']).returncode:  # graftlint: disable=G5 probed\n"
        "        subprocess.run(['y'])\n"
    )
    p = tmp_path / "ch.py"
    p.write_text(src)
    lines = [f.line for f in core.lint_file(str(p), rules=_rules(["G5"]))]
    assert lines == [5]


def test_legacy_noqa_stays_line_only_on_multiline_statements(tmp_path):
    """`# noqa` suppresses every code but ONLY its own line — it must
    not ride the statement-span union onto the opening line."""
    src = (
        "import subprocess\n"
        "r = subprocess.run(\n"
        "    ['x'])  # noqa\n"
    )
    p = tmp_path / "nq.py"
    p.write_text(src)
    lines = [f.line for f in core.lint_file(str(p), rules=_rules(["G5"]))]
    assert lines == [2]


def test_suppression_syntax_inside_string_literal_is_inert(tmp_path):
    """Only REAL comments suppress: a string that merely quotes the
    syntax (help text) must not mask a co-located finding."""
    src = (
        "import subprocess\n"
        'HELP = "add # graftlint: disable=G5 why"; '
        "r = subprocess.run(['x'])\n"
    )
    p = tmp_path / "s.py"
    p.write_text(src)
    lines = [f.line for f in core.lint_file(str(p), rules=_rules(["G5"]))]
    assert lines == [2]


def test_suppression_span_does_not_leak_across_match_arms(tmp_path):
    """match is a compound statement: a disable inside one case arm
    must not suppress findings in sibling arms."""
    src = (
        "import subprocess\n"
        "def f(x):\n"
        "    match x:\n"
        "        case 1:\n"
        "            subprocess.run(['a'])  # graftlint: disable=G5 ok\n"
        "        case _:\n"
        "            subprocess.run(['b'])\n"
    )
    p = tmp_path / "m.py"
    p.write_text(src)
    lines = [f.line for f in core.lint_file(str(p), rules=_rules(["G5"]))]
    assert lines == [7]


def test_cli_nonexistent_path_is_an_error():
    out = _cli(["mxnet_tpu/enigne.py"])             # typo'd path
    assert out.returncode == 2
    assert "no .py files" in out.stderr
    # a typo among valid paths must not pass as clean either, and the
    # message names only the missing one
    out = _cli(["mxnet_tpu/engine.py", "mxnet_tpu/enigne.py"])
    assert out.returncode == 2
    assert "mxnet_tpu/enigne.py" in out.stderr
    assert "mxnet_tpu/engine.py" not in out.stderr


def test_overlapping_paths_dedup_and_walk_excludes():
    """A dir plus a file inside it lints each file once (a duplicate
    finding would spuriously exceed the baseline budget); walking a
    PARENT of an excluded dir keeps the exclusion, while naming the
    excluded dir itself opts in."""
    fixture_dir = "tests/data/graftlint"
    one = core.run([fixture_dir], rules=_g_rules(), root=REPO)
    both = core.run([fixture_dir, fixture_dir + "/g5_subprocess.py"],
                    rules=_g_rules(), root=REPO)
    assert [f.sort_key() for f in both[0]] == [f.sort_key() for f in one[0]]
    assert one[0], "opt-in scan of the excluded fixture dir must lint it"
    parent, _ = core.run(["tests"], rules=_g_rules(), root=REPO)
    assert not any(f.path.startswith("tests/data/") for f in parent)


# -- baseline ----------------------------------------------------------------

def test_baseline_partition_and_justification_roundtrip(tmp_path):
    path = os.path.join(FIXTURES, "g5_subprocess.py")
    findings = core.lint_file(path, rules=_g_rules(), root=REPO)
    assert findings
    blp = str(tmp_path / "base.json")
    entries = bl.write_baseline(blp, findings)
    assert len(entries) == len(findings)
    new, based = bl.partition(findings, bl.load_baseline(blp))
    assert new == [] and len(based) == len(findings)
    # a human-edited justification survives regeneration
    data = json.load(open(blp))
    data["entries"][0]["justification"] = "accepted: fixture debt"
    json.dump(data, open(blp, "w"))
    bl.write_baseline(blp, findings)
    assert json.load(open(blp))["entries"][0]["justification"] == \
        "accepted: fixture debt"


def test_baseline_is_content_keyed_not_line_keyed(tmp_path):
    """Shifting a finding down by unrelated edits must not re-open it;
    new findings must not be absorbed by it."""
    p = tmp_path / "mod.py"
    p.write_text("import subprocess\n"
                 "r = subprocess.run(['x'])\n")
    blp = str(tmp_path / "b.json")
    bl.write_baseline(blp, core.lint_file(str(p), rules=_rules(["G5"])))
    # unrelated edit above: same content, new line number -> still matched
    p.write_text("import subprocess\n"
                 "# a comment pushing things down\n\n"
                 "r = subprocess.run(['x'])\n")
    new, based = bl.partition(core.lint_file(str(p), rules=_rules(["G5"])),
                              bl.load_baseline(blp))
    assert new == [] and len(based) == 1
    # a second, different undeadlined call IS new
    p.write_text("import subprocess\n"
                 "r = subprocess.run(['x'])\n"
                 "q = subprocess.check_output(['y'])\n")
    new, based = bl.partition(core.lint_file(str(p), rules=_rules(["G5"])),
                              bl.load_baseline(blp))
    assert len(based) == 1 and len(new) == 1
    assert new[0].message.startswith("subprocess.check_output")


# -- CLI / emitters / shim ---------------------------------------------------

def _cli(args, **kw):
    return subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis"] + args,
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, **kw)


def test_self_run_repo_is_clean_modulo_baseline():
    """The acceptance criterion: the analyzer exits 0 on the repo with
    the committed baseline (tests/data fixtures excluded by default) —
    and that baseline is EMPTY: every rule family, the G22-G25 race
    detectors included, landed with its live findings fixed or
    reason-disabled inline, none grandfathered."""
    out = _cli([])
    assert out.returncode == 0, out.stdout + out.stderr[-500:]
    assert "0 new" in out.stdout
    with open(os.path.join(REPO, "ci", "lint_baseline.json")) as f:
        assert json.load(f)["entries"] == []


def test_cli_json_and_sarif_emitters():
    rel = "tests/data/graftlint/g4_device_probe.py"
    out = _cli(["--format=json", "--no-baseline", rel])
    assert out.returncode == 1
    data = json.loads(out.stdout)
    assert data["tool"] == "graftlint" and data["files"] == 1
    assert {f["rule"] for f in data["new"]} == {"G4"}
    out = _cli(["--format=sarif", "--no-baseline", rel])
    assert out.returncode == 1
    sarif = json.loads(out.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"G1", "G2", "G3", "G4", "G5", "G6", "W1", "E1"} <= rule_ids
    res = run["results"]
    assert res and all(r["ruleId"] == "G4" for r in res)
    assert res[0]["baselineState"] == "new"
    loc = res[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == rel
    assert loc["region"]["startLine"] > 0


def test_cli_write_baseline_flow(tmp_path):
    rel = "tests/data/graftlint/g6_silent_swallow.py"
    blp = str(tmp_path / "b.json")
    out = _cli(["--write-baseline", "--baseline", blp, rel])
    assert out.returncode == 0, out.stderr[-500:]
    out = _cli(["--baseline", blp, rel])
    assert out.returncode == 0, out.stdout
    assert "0 new" in out.stdout


def test_malformed_baseline_is_a_usage_error_and_self_heals(tmp_path):
    blp = str(tmp_path / "b.json")
    with open(blp, "w") as f:
        f.write("<<<<<<< HEAD merge junk")
    rel = "tests/data/graftlint/g4_device_probe.py"
    out = _cli(["--baseline", blp, rel])
    assert out.returncode == 2 and "not valid JSON" in out.stderr
    # valid JSON but the wrong shape is equally a usage error
    with open(blp, "w") as f:
        f.write("[1, 2]")
    out = _cli(["--baseline", blp, rel])
    assert out.returncode == 2 and "regenerate" in out.stderr
    # --write-baseline regenerates past the broken file
    out = _cli(["--write-baseline", "--baseline", blp, rel])
    assert out.returncode == 0, out.stderr[-300:]
    out = _cli(["--baseline", blp, rel])
    assert out.returncode == 0


def test_cli_rules_filter_and_errors():
    out = _cli(["--rules", "G99"])
    assert out.returncode == 2 and "unknown rule" in out.stderr
    out = _cli(["--list-rules"])
    assert out.returncode == 0
    for code in ["G1", "G2", "G3", "G4", "G5", "G6", "G7", "G8", "G9",
                 "G15", "G16", "G17", "G18", "G19",
                 "G22", "G23", "G24", "G25",
                 "E1", "W1", "W2", "W3", "W4", "W5", "W6"]:
        assert code in out.stdout


def test_cli_write_baseline_refuses_partial_scan_of_default():
    """A narrowed scan must not clobber the committed baseline (it
    would drop every out-of-scope entry); an explicit --baseline FILE
    opts into a scoped file."""
    out = _cli(["--write-baseline", "mxnet_tpu/engine.py"])
    assert out.returncode == 2 and "clobber" in out.stderr
    out = _cli(["--write-baseline", "--rules", "G5"])
    assert out.returncode == 2


def test_ci_lint_shim_is_standalone(tmp_path):
    """The shim must lint WITHOUT executing mxnet_tpu/__init__ (no jax,
    no runtime import) — so tier-0 still reports findings when the
    runtime package itself is un-importable. Proven by running it with
    jax poisoned out of existence."""
    code = (
        "import sys\n"
        "sys.modules['jax'] = None          # any jax import explodes\n"
        "sys.modules['mxnet_tpu'] = None    # any runtime import explodes\n"
        "sys.argv = ['lint.py', 'tests/data/graftlint/g1_module_dial.py',\n"
        "            '--no-baseline']\n"
        "import runpy\n"
        "rc = 0\n"
        "try:\n"
        "    runpy.run_path('ci/lint.py', run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    rc = e.code\n"
        "assert rc == 1, f'expected findings exit, got {rc}'\n"
        "print('STANDALONE_OK')\n")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-800:]
    assert "STANDALONE_OK" in out.stdout


def test_ci_lint_shim_still_works():
    """`python ci/lint.py` keeps its contract: exit 0 on the clean repo
    (checked by test_self_run via the same engine) and exit 1 with the
    finding listed when pointed at a violation."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "ci", "lint.py"),
         "tests/data/graftlint/g1_module_dial.py", "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 1
    assert "G1" in out.stdout and "module-scope backend dial" \
        in out.stdout


# -- the runtime fixes the analyzer drove ------------------------------------

def test_infer_shape_never_makes_a_concrete_key(monkeypatch):
    """symbol shape inference on an rng-consuming op must not construct
    a concrete PRNGKey (a backend dial inside eval_shape — the G1/G2
    finding fixed this PR): the key rides as an abstract argument."""
    import jax
    from mxnet_tpu import sym
    calls = []
    orig = jax.random.PRNGKey
    monkeypatch.setattr(jax.random, "PRNGKey",
                        lambda *a, **k: (calls.append(a), orig(*a, **k))[1])
    out = sym.Dropout(sym.var("data"), p=0.5, mode="always")
    _args, out_shapes, _aux = out.infer_shape(data=(4, 8))
    assert out_shapes == [(4, 8)]
    assert not calls, "shape inference dialed a concrete PRNG key"


def test_g7_sanctioned_atomic_path_is_clean():
    """The rule's point: the atomic writer itself (and the commit
    protocol built on it) must not trip G7 — only direct artifact
    writes do. Proven by linting the resilience package explicitly."""
    findings, n = core.run(["mxnet_tpu/resilience"],
                           rules=_rules(["G7"]), root=REPO)
    assert n >= 4 and findings == []


def test_g8_serving_subsystem_is_clean():
    """The rule's raison d'etre: the serving subsystem — all stdlib
    threads + queues — must itself satisfy the bounded-queue /
    deadlined-wait discipline (bounded admission queue, timeout= on
    every get, deadlined thread joins)."""
    findings, n = core.run(["mxnet_tpu/serving"],
                           rules=_rules(["G8"]), root=REPO)
    assert n >= 6 and findings == []


def test_g8_tracks_receivers_not_names():
    """dict.get() and untracked .join() receivers stay silent; only
    names bound to queue/thread constructions are held to the
    timeout discipline (no false positives on mappings)."""
    path = os.path.join(FIXTURES, "g8_unbounded_queue.py")
    got = core.lint_file(path, rules=_rules(["G8"]), root=REPO)
    flagged_lines = {f.line for f in got}
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            if "dict.get: silent" in line or "untracked receiver" in line:
                assert i not in flagged_lines, line


def test_waitall_journals_instead_of_swallowing(monkeypatch, tmp_path):
    """The G6 fix: a dead backend during waitall leaves a structured
    breadcrumb and does not raise (narrow catch + journal, replacing
    `except Exception: pass`)."""
    from mxnet_tpu import engine
    from mxnet_tpu.diagnostics import guard, journal

    def boom(local=False):
        raise RuntimeError("backend torn down")

    monkeypatch.setattr(guard, "devices", boom)
    journal.reset_journal(str(tmp_path / "j.jsonl"))
    try:
        engine.waitall()                   # must not raise
    finally:
        journal.reset_journal()
    recs = [json.loads(l) for l in open(tmp_path / "j.jsonl")]
    (rec,) = [r for r in recs if r["kind"] == "waitall_failed"]
    assert rec["error"] == "RuntimeError"
    assert "torn down" in rec["detail"]

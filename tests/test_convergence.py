"""Convergence-threshold gates (ref: tests/python/train/test_mlp.py,
test_conv.py — the reference's trainer tier asserts FINAL ACCURACY above a
threshold, SURVEY §4). Round-2 verdict #6: a wrong-but-running model must
FAIL the suite — these tests gate on the number, not on "training ran".

Data is the same learnable synthetic MNIST stand-in the examples use
(class-keyed quadrant brightening): separable enough that a correct
optimizer/loss/model reaches ≥97% train accuracy in a few epochs on CPU,
and any sign/scaling regression in the loss, gradients, or updates
lands far below the gate.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def synthetic_mnist(n=1024, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.25
    y = rng.randint(0, 10, n).astype(np.float32)
    # learnable structure: class c brightens a distinct 7x7 tile
    for i in range(n):
        c = int(y[i])
        r, col = divmod(c, 4)
        x[i, 0, r * 7:(r + 1) * 7, col * 7:(col + 1) * 7] += 0.75
    return x, y


def _train_accuracy(net, x, y, epochs, batch_size=128, lr=0.05,
                    optimizer="sgd", hybridize=True):
    mx.random.seed(0)
    net.initialize(mx.init.Xavier())
    if hybridize:
        net.hybridize()
    params = {"learning_rate": lr}
    if optimizer == "sgd":
        params["momentum"] = 0.9
    trainer = gluon.Trainer(net.collect_params(), optimizer, params)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    n = x.shape[0]
    for _ in range(epochs):
        for i in range(0, n - batch_size + 1, batch_size):
            xb = nd.array(x[i:i + batch_size])
            yb = nd.array(y[i:i + batch_size])
            with autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(batch_size)
    correct = 0
    for i in range(0, n, 256):
        out = net(nd.array(x[i:i + 256])).asnumpy()
        correct += (out.argmax(1) == y[i:i + 256]).sum()
    return correct / n


def test_mlp_converges_to_97pct():
    """ref: tests/python/train/test_mlp.py — MLP accuracy gate."""
    x, y = synthetic_mnist()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Flatten(),
            gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    acc = _train_accuracy(net, x, y, epochs=6, lr=0.1)
    assert acc >= 0.97, f"MLP train accuracy {acc:.3f} below the 0.97 gate"


def test_lenet_converges_to_97pct():
    """ref: tests/python/train/test_conv.py — LeNet accuracy gate
    (driver config #1's correctness criterion, BASELINE.md)."""
    x, y = synthetic_mnist()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Conv2D(32, 5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(10))
    acc = _train_accuracy(net, x, y, epochs=4, lr=0.05)
    assert acc >= 0.97, \
        f"LeNet train accuracy {acc:.3f} below the 0.97 gate"


def test_module_fit_converges():
    """The symbolic Module.fit path reaches the same gate (both worlds
    must train correctly, not just run — ref: Module.fit score())."""
    from mxnet_tpu import io, sym
    x, y = synthetic_mnist(n=512)
    data = sym.var("data")
    f = sym.Flatten(data)
    fc1 = sym.Activation(sym.FullyConnected(f, num_hidden=64),
                         act_type="relu")
    fc2 = sym.FullyConnected(fc1, num_hidden=10)
    net = sym.SoftmaxOutput(fc2, name="softmax")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    it = io.NDArrayIter(x, y, batch_size=128, shuffle=True)
    mx.random.seed(0)
    mod.fit(it, num_epoch=8,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            eval_metric="acc")
    it_eval = io.NDArrayIter(x, y, batch_size=128)
    score = dict(mod.score(it_eval, ["acc"]))
    assert score["accuracy"] >= 0.95, \
        f"Module.fit accuracy {score['accuracy']:.3f} below the 0.95 gate"


def _sharded_train_accuracy(x, y, optimizer, params, master_dtype,
                            epochs=6):
    from mxnet_tpu import parallel
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Flatten(),
            gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    mesh = parallel.make_mesh({"data": -1})
    tr = parallel.ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 optimizer, dict(params), mesh=mesh,
                                 master_dtype=master_dtype)
    n, bs = x.shape[0], 128
    for _ in range(epochs):
        for i in range(0, n - bs + 1, bs):
            tr.step(x[i:i + bs], y[i:i + bs])
    # pull the trained (mesh-sharded) params back to the default device
    # for a plain eager evaluation pass
    for p in net.collect_params().values():
        p.set_data(nd.array(np.asarray(p.data().asnumpy(),
                                       dtype=np.float32)))
    out = net(nd.array(x)).asnumpy()
    return (out.argmax(1) == y).mean()


def test_bf16_master_sgd_converges():
    """bf16 master weights + momentum (the bench.py throughput config,
    docs/perf_notes.md round 4) must clear the SAME accuracy gate as fp32
    masters — storage dtype is a perf knob, not a correctness trade."""
    x, y = synthetic_mnist(n=512)
    acc32 = _sharded_train_accuracy(
        x, y, "sgd", {"learning_rate": 0.1, "momentum": 0.9}, None)
    acc16 = _sharded_train_accuracy(
        x, y, "sgd", {"learning_rate": 0.1, "momentum": 0.9}, "bfloat16")
    assert acc32 >= 0.97, f"fp32-master control fell to {acc32:.3f}"
    assert acc16 >= 0.97, \
        f"bf16-master accuracy {acc16:.3f} below the fp32 gate ({acc32:.3f})"


def test_bf16_master_adam_converges():
    """Adam with bf16 m/v/params (benchmarks/bert.py's config) clears the
    same gate — guards the BERT headline number's validity."""
    x, y = synthetic_mnist(n=512)
    acc32 = _sharded_train_accuracy(
        x, y, "adam", {"learning_rate": 1e-3}, None, epochs=8)
    acc16 = _sharded_train_accuracy(
        x, y, "adam", {"learning_rate": 1e-3}, "bfloat16", epochs=8)
    assert acc32 >= 0.97, f"fp32-master control fell to {acc32:.3f}"
    assert acc16 >= 0.97, \
        f"bf16-master accuracy {acc16:.3f} below the fp32 gate ({acc32:.3f})"


def test_wrong_loss_fails_the_gate():
    """Meta-test: the gate actually catches a broken training setup — a
    sign-flipped loss (ascending gradient) must land far below 0.97."""
    x, y = synthetic_mnist(n=512)

    class NegCE(gluon.loss.Loss):
        def __init__(self):
            super().__init__(None, 0)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, pred, label):
            return -self._ce(pred, label)

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Flatten(), gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = NegCE()
    for i in range(0, 512 - 127, 128):
        xb, yb = nd.array(x[i:i + 128]), nd.array(y[i:i + 128])
        with autograd.record():
            loss = loss_fn(net(xb), yb)
        loss.backward()
        trainer.step(128)
    out = net(nd.array(x)).asnumpy()
    acc = (out.argmax(1) == y).mean()
    assert acc < 0.97, "sign-flipped loss should not pass the gate"

"""Unified telemetry (docs/observability.md): span tracing semantics
(nesting, thread propagation, ring bounds), the metrics registry and
its Prometheus exposition, the Chrome-trace/Perfetto export golden
tests, the journal trace-id correlation, the serving per-request span
tree, and the disabled-overhead transfer-guard contract across all four
training paths.

The ``*smoke*`` tests are CI's tier-0.5 observability smoke
(ci/run_tests.sh): one traced training step + one traced serving
request, both exporters parsed.
"""
import json
import re
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, io, observability, parallel, sym
from mxnet_tpu.diagnostics import journal
from mxnet_tpu.guardrails import GuardConfig
from mxnet_tpu.observability import export, instrument, metrics, trace
from mxnet_tpu.observability.report import metrics_report, trace_report
from mxnet_tpu.serving import Server, ServerConfig
from mxnet_tpu.testing import faults


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Every test starts from the env default (tracing off) and a clean
    metrics registry, and leaves no tracer/journal state behind."""
    trace.reset_tracer()
    metrics.reset_metrics()
    yield
    trace.reset_tracer()
    metrics.reset_metrics()


@pytest.fixture
def ring():
    return trace.configure(mode="ring")


@pytest.fixture
def jfile(tmp_path):
    jf = str(tmp_path / "journal.jsonl")
    journal.reset_journal(jf)
    try:
        yield jf
    finally:
        journal.reset_journal()


def _read_journal(path):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


def _mlp():
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu", in_units=8))
        net.add(gluon.nn.Dense(4, in_units=16))
    net.initialize()
    return net


def _sharded(guard=None, **kw):
    net = _mlp()
    mesh = parallel.make_mesh({"data": -1})
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh, guard=guard, **kw)
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16,))
    return tr, x, y


# -- span semantics ----------------------------------------------------------

def test_span_nesting_ids_and_ring(ring):
    with trace.span("outer", a=1) as outer:
        assert trace.current_span() is outer
        with trace.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        trace.event("pt", x=2)
    assert trace.current_span() is None
    spans = {s["name"]: s for s in ring.spans()}
    assert set(spans) == {"outer", "inner", "pt"}
    assert spans["outer"]["parent_id"] is None
    assert spans["outer"]["attrs"] == {"a": 1}
    assert spans["pt"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["pt"]["dur_s"] == 0.0
    assert all(s["dur_s"] >= 0 for s in spans.values())
    # two separate roots get distinct trace ids (process-token prefixed)
    with trace.span("other"):
        pass
    other = [s for s in ring.spans() if s["name"] == "other"][0]
    assert other["trace_id"] != spans["outer"]["trace_id"]


def test_ring_is_bounded_and_counts_drops():
    tr = trace.configure(mode="ring", ring=4)
    for i in range(10):
        with trace.span(f"s{i}"):
            pass
    assert len(tr.spans()) == 4
    assert tr.recorded == 10 and tr.dropped == 6
    assert [s["name"] for s in tr.spans()] == ["s6", "s7", "s8", "s9"]


def test_thread_parent_propagation(ring):
    """contextvars don't cross threads: the capture token does — the
    serving-worker pattern."""
    got = {}

    def worker(ctx):
        with trace.span("child", parent=ctx) as sp:
            got["trace"] = sp.trace_id
            got["parent"] = sp.parent_id

    with trace.span("root") as root:
        ctx = trace.current_context()
        t = threading.Thread(target=worker, args=(ctx,))
        t.start()
        t.join(10)
    assert got["trace"] == root.trace_id
    assert got["parent"] == root.span_id
    child = [s for s in ring.spans() if s["name"] == "child"][0]
    assert child["thread"] != "MainThread"


def test_disabled_tracing_is_inert_noop():
    assert trace.mode() == "off"
    sp = trace.span("x", a=1)
    sp2 = trace.span("y")
    assert sp is sp2                         # one shared no-op object
    with sp:
        assert trace.current_ids() == {}
        assert trace.annotate(k=1) is False
    assert trace.get_tracer().recorded == 0


def test_bad_trace_mode_degrades_off(monkeypatch, jfile):
    monkeypatch.setenv("MXNET_TPU_TRACE", "bogus")
    tr = trace.reset_tracer()
    assert tr.mode == "off"
    recs = [r for r in _read_journal(jfile) if r["kind"] == "trace_bad_mode"]
    assert recs and recs[0]["value"] == "bogus"


# -- journal correlation (the satellite: one trace across journals) ----------

def test_journal_records_carry_trace_ids_inside_spans(ring, jfile):
    j = journal.get_journal()
    j.event("plain")                         # outside any span
    with trace.span("scope") as sp:
        j.event("inside", foo=1)
        # explicit fields always win over the provider
        j.event("explicit", trace_id="mine")
    recs = {r["kind"]: r for r in _read_journal(jfile)}
    assert "trace_id" not in recs["plain"]   # bit-identical when off-span
    assert recs["inside"]["trace_id"] == sp.trace_id
    assert recs["inside"]["span_id"] == sp.span_id
    assert recs["inside"]["foo"] == 1
    assert recs["explicit"]["trace_id"] == "mine"


def test_guardrail_skip_record_correlates_with_step_trace(ring, jfile):
    tr, x, y = _sharded(guard=True)
    tr.step(x, y)
    tr.step(faults.poison_batch(x), y)
    skip = [r for r in _read_journal(jfile)
            if r["kind"] == "nonfinite_grad"][0]
    assert "trace_id" in skip and "span_id" in skip
    steps = [s for s in trace.get_tracer().spans()
             if s["name"] == "sharded_trainer.step"]
    assert skip["trace_id"] in {s["trace_id"] for s in steps}


# -- metrics registry + exposition -------------------------------------------

def test_metrics_registry_families_and_snapshot():
    reg = metrics.MetricsRegistry()
    c = reg.counter("req_total", "requests", ("route",))
    c.labels(route="a").inc()
    c.labels(route="a").inc(2)
    c.labels(route="b").inc()
    g = reg.gauge("depth", "queue depth")
    g.set(7)
    s = reg.summary("lat_ms", "latency", ())
    for v in (1.0, 2.0, 10.0):
        s.observe(v)
    snap = reg.snapshot()
    assert snap["req_total"]["values"] == {"route=a": 3.0, "route=b": 1.0}
    assert snap["depth"]["values"][""] == 7.0
    assert snap["lat_ms"]["values"][""]["count"] == 3
    # idempotent getter; kind mismatch is structural
    assert reg.counter("req_total", labelnames=("route",)) is c
    with pytest.raises(Exception, match="already registered"):
        reg.gauge("req_total")
    with pytest.raises(Exception, match="takes labels"):
        c.labels(wrong="x")


def test_prometheus_exposition_format():
    reg = metrics.MetricsRegistry()
    reg.counter("c_total", "a counter", ("site",)).labels(
        site='we"ird\\x').inc(5)
    reg.gauge("g", "a gauge").set(1.5)
    s = reg.summary("s_ms", "a summary")
    s.observe(4.0)
    text = reg.prometheus_text()
    lines = text.splitlines()
    assert "# TYPE c_total counter" in lines
    assert "# HELP c_total a counter" in lines
    assert 'c_total{site="we\\"ird\\\\x"} 5' in lines
    assert "g 1.5" in lines
    assert 's_ms{quantile="0.5"} 4' in lines
    assert "s_ms_sum 4" in lines and "s_ms_count 1" in lines
    # every non-comment line is `name{labels} value`
    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? '
        r'(NaN|[+-]?Inf|[-+0-9.e]+)$')
    for ln in lines:
        if ln and not ln.startswith("#"):
            assert sample_re.match(ln), ln


def test_latency_summary_is_reexported_for_compat():
    from mxnet_tpu.metric import LatencySummary
    assert LatencySummary is metrics.LatencySummary
    ls = LatencySummary(reservoir_size=4)
    for v in range(100):
        ls.observe(float(v))
    assert ls.count == 100
    assert len(ls._buf) == 4
    with pytest.raises(mx.MXNetError):
        LatencySummary(reservoir_size=0)


def test_step_phase_metrics_are_always_on_even_with_trace_off():
    """The bench provenance path: compile counts and step-phase
    summaries accumulate with tracing disabled."""
    assert trace.mode() == "off"
    tr, x, y = _sharded()
    tr.step(x, y)
    tr.step(x, y)
    snap = observability.snapshot()
    phases = snap["metrics"][instrument.PHASE_METRIC]["values"]
    key = "trainer=sharded_trainer,phase=compiled_step"
    assert phases[key]["count"] == 2
    comp = observability.compile_stats(snap)
    assert comp["compiles"] == 1
    assert comp["by_site"] == {"sharded_trainer.step": 1}
    assert snap["trace"]["recorded"] == 0


# -- Perfetto / Chrome-trace export golden -----------------------------------

def _assert_chrome_doc(doc):
    """The format contract Perfetto's JSON importer needs: a
    traceEvents list of complete events with name/ph/ts/dur/pid/tid."""
    assert set(doc) >= {"traceEvents"}
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert ev["ph"] == "X"
        assert isinstance(ev["name"], str) and ev["name"]
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], str)
        assert "span_id" in ev["args"] and "trace_id" in ev["args"]
    json.loads(json.dumps(doc))              # round-trips as pure JSON


def _containment(doc, child_name, parent_name):
    """Child events sit inside their parent's [ts, ts+dur] window."""
    evs = doc["traceEvents"]
    by_id = {e["args"]["span_id"]: e for e in evs}
    checked = 0
    for e in evs:
        if e["name"] != child_name:
            continue
        parent = by_id.get(e["args"].get("parent_id"))
        if parent is None or parent["name"] != parent_name:
            continue
        eps = 1e3  # 1 ms slack for rounding
        assert e["ts"] >= parent["ts"] - eps
        assert e["ts"] + e["dur"] <= parent["ts"] + parent["dur"] + eps
        checked += 1
    assert checked > 0, f"no {child_name} under {parent_name}"


def test_smoke_traced_training_step_perfetto_export(tmp_path, ring):
    """Acceptance: a traced training run exports Chrome-trace JSON with
    compile events, step phases and checkpoint commits as nested
    spans."""
    tr, x, y = _sharded(guard=True)
    tr.step(x, y)
    tr.step(x, y)
    tr.checkpoint(str(tmp_path / "ckpt"))
    out = str(tmp_path / "trace.json")
    n = export.export_chrome(out)
    with open(out, encoding="utf-8") as f:
        doc = json.load(f)
    assert len(doc["traceEvents"]) == n
    _assert_chrome_doc(doc)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"sharded_trainer.step", "sharded_trainer.data_wait",
            "sharded_trainer.compiled_step",
            "sharded_trainer.guard_fetch", "xla_compile",
            "ckpt_commit"} <= names
    _containment(doc, "sharded_trainer.compiled_step",
                 "sharded_trainer.step")
    _containment(doc, "xla_compile", "sharded_trainer.compiled_step")
    # exactly one compile event for two same-shape steps
    compiles = [e for e in doc["traceEvents"] if e["name"] == "xla_compile"]
    assert len(compiles) == 1
    assert compiles[0]["args"]["shapes"] == [[16, 8], [16]]


def _fit_mod(tmp_path=None, num_epoch=2, prefix=None):
    rng = np.random.RandomState(0)
    x = rng.randn(40, 6).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    it = io.NDArrayIter(x, y, batch_size=10)
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=2, name="fc")
    net = sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=num_epoch, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            checkpoint_prefix=prefix)
    return mod, it


def test_traced_module_fit_epoch_perfetto_export(tmp_path, ring):
    """Acceptance: a traced module.fit epoch exports a Perfetto-valid
    trace with the epoch/step/phase/compile/checkpoint span tree."""
    _fit_mod(prefix=str(tmp_path / "ck" / "mlp"))
    doc = export.to_chrome_trace()
    _assert_chrome_doc(doc)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"module_fit.epoch", "module_fit.step",
            "module_fit.forward_backward", "module_fit.update",
            "module_fit.data_wait", "xla_compile",
            "ckpt_commit"} <= names
    _containment(doc, "module_fit.step", "module_fit.epoch")
    _containment(doc, "module_fit.forward_backward", "module_fit.step")
    _containment(doc, "ckpt_commit", "module_fit.epoch")
    # the bind compile is tagged with the module site
    sites = {e["args"].get("site") for e in doc["traceEvents"]
             if e["name"] == "xla_compile"}
    assert "module_bind" in sites


def test_chrome_trace_from_journal_roundtrip(tmp_path, jfile):
    trace.configure(mode="journal")
    with trace.span("a", k=1):
        with trace.span("b"):
            pass
    doc = export.chrome_trace_from_journal(jfile)
    _assert_chrome_doc(doc)
    assert {e["name"] for e in doc["traceEvents"]} == {"a", "b"}
    # journal mode also keeps the ring populated
    assert len(trace.get_tracer().spans()) == 2


# -- serving: one linked span tree per request --------------------------------

class _Scale(gluon.block.HybridBlock):
    def __init__(self, k=3.0, **kw):
        super().__init__(**kw)
        self.k = k

    def hybrid_forward(self, F, x):
        return x * self.k


def test_smoke_serving_request_linked_span_tree(ring, jfile):
    """Acceptance: each served request owns one span tree —
    serving_request root with enqueue/execute/respond children — and
    the execute child names the shared batch span; the serving_batch
    journal record carries the batch span's ids."""
    net = _Scale()
    net.initialize()
    srv = Server(net, ServerConfig(max_batch=4, window_ms=2.0)).start()
    try:
        outs = [srv.predict(np.ones((3,), np.float32) * i)
                for i in range(2)]
    finally:
        srv.stop()
    for i, o in enumerate(outs):
        np.testing.assert_allclose(np.asarray(o), np.ones(3) * i * 3.0)

    spans = trace.get_tracer().spans()
    roots = [s for s in spans if s["name"] == "serving_request"]
    assert len(roots) == 2
    batch_ids = {s["span_id"] for s in spans if s["name"] == "serving_batch"}
    for root in roots:
        kids = {s["name"]: s for s in spans
                if s.get("parent_id") == root["span_id"]}
        assert {"enqueue", "execute", "respond"} <= set(kids)
        assert all(s["trace_id"] == root["trace_id"]
                   for s in kids.values())
        assert kids["execute"]["attrs"]["batch_span"] in batch_ids
        assert root["attrs"]["status"] == "ok"
    # batch journal record carries the batch span ids (worker thread)
    recs = [r for r in _read_journal(jfile) if r["kind"] == "serving_batch"]
    assert recs and all(r.get("span_id") in batch_ids for r in recs)
    # and the whole ring exports as a Perfetto-valid doc
    _assert_chrome_doc(export.to_chrome_trace())


def test_serving_shed_record_carries_request_trace(ring, jfile):
    from mxnet_tpu.serving import ServerOverloaded
    net = _Scale()
    net.initialize()
    srv = Server(net, ServerConfig(max_batch=2, max_queue=1))
    # not started: the queue fills and the second submit sheds
    srv.submit(np.ones((3,), np.float32))
    with pytest.raises(ServerOverloaded):
        srv.submit(np.ones((3,), np.float32))
    shed = [r for r in _read_journal(jfile) if r["kind"] == "serving_shed"]
    sheds = [s for s in trace.get_tracer().spans()
             if s["name"] == "serving_request"
             and s["attrs"].get("status") == "shed"]
    assert shed and sheds
    assert shed[0]["trace_id"] == sheds[0]["trace_id"]
    srv._fail_remaining([])                 # drain the queued request


# -- Prometheus endpoint on the serving server -------------------------------

def test_server_metrics_text_and_http_endpoint():
    import http.client
    net = _Scale()
    net.initialize()
    srv = Server(net, ServerConfig(max_batch=4, window_ms=2.0)).start()
    try:
        srv.predict(np.ones((3,), np.float32))
        text = srv.metrics_text()
        sid = srv._metrics_id
        assert "# TYPE mxnet_tpu_serving_queue_depth gauge" in text
        assert (f'mxnet_tpu_serving_events{{server="{sid}",'
                f'event="served"}} 1') in text
        assert (f'mxnet_tpu_serving_cache_events{{server="{sid}",'
                f'event="misses"}} 1') in text
        # the shared registry rides along: the serving compile is there
        assert 'mxnet_tpu_xla_compiles_total{site="serving_predictor"} 1' \
            in text
        httpd = srv.start_metrics_server(port=0)
        assert srv.start_metrics_server() is httpd      # idempotent
        port = httpd.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode("utf-8")
        assert resp.status == 200
        assert "text/plain" in resp.getheader("Content-Type")
        assert "mxnet_tpu_serving_events" in body
        conn.request("GET", "/nope")
        assert conn.getresponse().status == 404
        conn.close()
    finally:
        srv.stop()
    assert srv._metrics_httpd is None       # stop() shut the endpoint


# -- the disabled-overhead contract ------------------------------------------

def test_trace_off_zero_host_reads_sharded_and_pipelined():
    """With MXNET_TPU_TRACE=off the instrumented compiled step paths
    add ZERO device→host transfers: the fused trainers run under
    transfer_guard(disallow) (the guardrails technique)."""
    import jax
    assert trace.mode() == "off"
    tr, x, y = _sharded(guard=GuardConfig(mode="deferred"))
    tr.step(x, y)                           # compile + warm
    xb = [tr._shard_batch_arg(b) for b in (x, y)]
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(2):
            tr.step(*xb)

    mesh = parallel.make_mesh({"pipe": 2, "data": 4})
    emb = gluon.nn.Embedding(16, 8)
    body = [gluon.nn.Dense(8, in_units=8, flatten=False)
            for _ in range(2)]
    head = gluon.nn.Dense(16, in_units=8, flatten=False)
    for b in (emb, *body, head):
        b.initialize()
    ptr = parallel.PipelinedTrainer(
        emb, body, head, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh, num_microbatches=2)
    tok = np.arange(32, dtype=np.int32).reshape(8, 4) % 16
    lab = tok.copy()
    ptr.step(tok, lab)                      # compile + warm
    import jax.numpy as jnp
    tokd, labd = jnp.asarray(tok), jnp.asarray(lab)
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(2):
            ptr.step(tokd, labd)


def test_trace_off_zero_host_reads_gluon_trainer_and_module():
    """The eager paths: gluon Trainer.step (no guard/scaler) and the
    module fit step loop (no metric sync) also add zero transfers."""
    import jax
    from mxnet_tpu import autograd
    assert trace.mode() == "off"
    net = _mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    x = mx.nd.array(np.random.RandomState(0).randn(8, 8)
                    .astype(np.float32))
    y = mx.nd.array(np.random.RandomState(1).randint(0, 4, (8,)))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def one_step():
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.step(batch_size=8)

    one_step()                              # warm every jitted kernel
    with jax.transfer_guard_device_to_host("disallow"):
        one_step()

    # module fit's instrumented batch loop (_fit_epoch), metric no-op'd
    class _NoSync(mx.metric.EvalMetric):
        def update(self, labels, preds):
            pass

    mod, it = _fit_mod(num_epoch=1)
    it.reset()
    with jax.transfer_guard_device_to_host("disallow"):
        stopped, steps = mod._fit_epoch(
            it, _NoSync("nosync"), epoch=1, monitor=None,
            anomaly_monitor=None, checkpoint_prefix=None,
            batch_end_callback=None, watch=None, global_step=0)
    assert not stopped and steps == 4


# -- reports + doctor surfaces ------------------------------------------------

def test_trace_report_summarizes_journal(tmp_path, jfile):
    trace.configure(mode="journal")
    with trace.span("stepish"):
        with trace.span("phase"):
            pass
    rep = trace_report(jfile)
    assert rep["ok"] and rep["spans"] == 2 and rep["traces"] == 1
    assert set(rep["by_name"]) == {"stepish", "phase"}
    assert rep["slowest"][0]["name"] in ("stepish", "phase")
    bad = trace_report(str(tmp_path / "missing.jsonl"))
    assert bad["ok"] is False
    empty = trace_report(__file__)
    assert empty["ok"] is False and "no span records" in empty["error"]


def test_metrics_report_reads_bench_artifact(tmp_path):
    tr, x, y = _sharded()
    tr.step(x, y)
    artifact = {"metric": "whatever", "value": 1,
                "observability": observability.snapshot()}
    p = str(tmp_path / "BENCH_x.json")
    with open(p, "w", encoding="utf-8") as f:
        json.dump(artifact, f)
    rep = metrics_report(p)
    assert rep["ok"]
    assert rep["compiles_total"] == 1
    assert any("sharded_trainer" in k for k in rep["step_phase_ms"])
    bad = metrics_report(str(tmp_path / "missing.json"))
    assert bad["ok"] is False


def test_doctor_dispatch_table_covers_all_reporters():
    """The doctor cleanup satellite: one table row per report surface,
    and the new --trace/--metrics surfaces are rows in it."""
    from mxnet_tpu.diagnostics import __main__ as dmain
    keys = [row[0] for row in dmain._REPORT_TABLE]
    assert keys == ["checkpoint", "serving", "guardrails", "trace",
                    "metrics", "timeline", "aot", "lint", "tuned",
                    "chaos"]
    for _key, flag, _env, _mv, _help, load, summ in dmain._REPORT_TABLE:
        assert flag.startswith("--") and callable(load) and callable(summ)


@pytest.mark.slow
def test_observability_cli_dump_and_report(tmp_path):
    import subprocess
    import sys
    jf = str(tmp_path / "j.jsonl")
    out = str(tmp_path / "trace.json")
    code = ("from mxnet_tpu.observability import trace\n"
            "with trace.span('cli_root'):\n"
            "    with trace.span('cli_child'):\n"
            "        pass\n")
    env = dict(__import__('os').environ, JAX_PLATFORMS="cpu",
               MXNET_TPU_JOURNAL=jf, MXNET_TPU_TRACE="journal")
    r = subprocess.run([sys.executable, "-c", code], env=env, timeout=240)
    assert r.returncode == 0
    r = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.observability", "dump",
         "--journal", jf, "--out", out],
        env=env, capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr
    with open(out, encoding="utf-8") as f:
        doc = json.load(f)
    _assert_chrome_doc(doc)
    assert {e["name"] for e in doc["traceEvents"]} == {"cli_root",
                                                       "cli_child"}
    r = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.observability", "report",
         "--journal", jf],
        env=env, capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["ok"] and rep["spans"] == 2
